"""Convolution layer family — NHWC / HWIO, lowered to XLA convolutions.

Parity targets (semantics, not code):
- ConvolutionLayer       <- DL4J nn/conf/layers/ConvolutionLayer.java; impl
  nn/layers/convolution/ConvolutionLayer.java (im2col+gemm at :208-224, cuDNN
  helper at :75-85). Here the conv IS one XLA op that tiles directly onto the
  MXU — no im2col materialization, no helper fallback needed.
- SubsamplingLayer       <- nn/conf/layers/SubsamplingLayer.java (MAX/AVG/PNORM)
- Upsampling2D, ZeroPaddingLayer, SpaceToDepth, SpaceToBatch, Cropping2D
- Deconvolution2D, SeparableConvolution2D, DepthwiseConvolution2D
- GlobalPoolingLayer     <- nn/conf/layers/GlobalPoolingLayer.java (MAX/AVG/SUM/PNORM,
  works on CNN and RNN input, mask-aware for RNN)
- CnnLossLayer           <- nn/conf/layers/CnnLossLayer.java

ConvolutionMode parity (nn/conf/ConvolutionMode.java): Same -> XLA SAME
padding; Truncate -> VALID (silently truncates); Strict -> VALID + static
shape check at config time.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import InputType, Kind, LayerConf, register_layer
from deeplearning4j_tpu.nn.initializers import get_initializer
from deeplearning4j_tpu.nn.losses import get_loss


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_out_dim(size, k, s, d, mode) -> int:
    eff_k = (k - 1) * d + 1
    if mode == "same":
        return -(-size // s)
    out = (size - eff_k) // s + 1
    if mode == "strict" and (size - eff_k) % s != 0:
        raise ValueError(
            f"ConvolutionMode.Strict: input size {size} with kernel {k}, "
            f"stride {s}, dilation {d} does not tile exactly")
    return out


def _padding(mode):
    return "SAME" if mode == "same" else "VALID"


@register_layer
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(LayerConf):
    n_out: int = 0                       # output channels
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"   # same | truncate | strict
    activation: str = "identity"
    weight_init: str = "relu"
    bias_init: float = 0.0
    has_bias: bool = True
    n_in: Optional[int] = None

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        oh = _conv_out_dim(h, kh, sh, dh, self.convolution_mode)
        ow = _conv_out_dim(w, kw, sw, dw, self.convolution_mode)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        c_in = self.n_in or input_type.shape[2]
        kh, kw = _pair(self.kernel)
        fan_in = c_in * kh * kw
        fan_out = self.n_out * kh * kw
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (kh, kw, c_in, self.n_out), fan_in, fan_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # No preferred_element_type here (or in the other conv variants):
        # JAX's conv transpose rule rejects the mixed-dtype cotangent it
        # produces under bf16 compute, and the TPU MXU accumulates bf16
        # convolutions in f32 regardless — the f32-accumulation invariant
        # holds without requesting it.
        x = self.maybe_dropout_input(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_padding(self.convolution_mode),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (DL4J nn/conf/layers/Deconvolution2D.java)."""

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
        if self.convolution_mode == "same":
            oh, ow = h * sh, w * sw
        else:
            oh, ow = (h - 1) * sh + ekh, (w - 1) * sw + ekw
        return InputType.convolutional(oh, ow, self.n_out)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = lax.conv_transpose(
            x, params["W"],
            strides=_pair(self.stride),
            padding=_padding(self.convolution_mode),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class DepthwiseConvolution2D(LayerConf):
    """Per-channel convolution (DL4J DepthwiseConvolution2D); XLA
    feature_group_count — TPU lowers this natively."""
    depth_multiplier: int = 1
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    activation: str = "identity"
    weight_init: str = "relu"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        oh = _conv_out_dim(h, kh, sh, dh, self.convolution_mode)
        ow = _conv_out_dim(w, kw, sw, dw, self.convolution_mode)
        return InputType.convolutional(oh, ow, c * self.depth_multiplier)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        c_in = input_type.shape[2]
        kh, kw = _pair(self.kernel)
        w_init = get_initializer(self.weight_init)
        fan_in = kh * kw
        params = {"W": w_init(key, (kh, kw, 1, c_in * self.depth_multiplier),
                              fan_in, fan_in * self.depth_multiplier, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((c_in * self.depth_multiplier,), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=_pair(self.stride),
            padding=_padding(self.convolution_mode),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in,
        )
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SeparableConvolution2D(LayerConf):
    """Depthwise + pointwise (DL4J SeparableConvolution2D)."""
    n_out: int = 0
    depth_multiplier: int = 1
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    activation: str = "identity"
    weight_init: str = "relu"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        oh = _conv_out_dim(h, kh, sh, dh, self.convolution_mode)
        ow = _conv_out_dim(w, kw, sw, dw, self.convolution_mode)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        c_in = input_type.shape[2]
        kh, kw = _pair(self.kernel)
        k1, k2 = jax.random.split(key)
        w_init = get_initializer(self.weight_init)
        mid = c_in * self.depth_multiplier
        params = {
            "dW": w_init(k1, (kh, kw, 1, mid), kh * kw, kh * kw, dtype),
            "pW": w_init(k2, (1, 1, mid, self.n_out), mid, self.n_out, dtype),
        }
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x, params["dW"], window_strides=_pair(self.stride),
            padding=_padding(self.convolution_mode),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in,
        )
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(LayerConf):
    """2D pooling (DL4J SubsamplingLayer; impl
    nn/layers/convolution/subsampling/SubsamplingLayer.java, cuDNN helper
    CudnnSubsamplingHelper). XLA reduce_window replaces both paths."""
    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    pooling_type: str = "max"            # max | avg | sum | pnorm
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        oh = _conv_out_dim(h, kh, sh, 1, self.convolution_mode)
        ow = _conv_out_dim(w, kw, sw, 1, self.convolution_mode)
        return InputType.convolutional(oh, ow, c)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pad = _padding(self.convolution_mode)
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif pt == "avg":
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        elif pt == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
            y = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type}")
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(LayerConf):
    """Global pooling over spatial or time dims (DL4J GlobalPoolingLayer).
    Mask-aware for RNN input, mirroring MaskedReductionUtil."""
    pooling_type: str = "max"            # max | avg | sum | pnorm
    pnorm: int = 2
    collapse_dimensions: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(input_type.features)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim == 4:       # (B,H,W,C)
            axes = (1, 2)
        elif x.ndim == 3:     # (B,T,F)
            axes = (1,)
        else:
            raise ValueError(f"GlobalPooling expects 3d/4d input, got {x.shape}")
        pt = self.pooling_type.lower()
        if mask is not None and x.ndim == 3:
            m = mask[..., None].astype(x.dtype)
            if pt == "max":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt == "sum":
                y = jnp.sum(x * m, axis=1)
            elif pt == "avg":
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            elif pt == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
            else:
                raise ValueError(self.pooling_type)
            return y, state
        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "avg":
            y = jnp.mean(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(self.pooling_type)
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class Upsampling2D(LayerConf):
    size: Tuple[int, int] = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        sh, sw = _pair(self.size)
        return InputType.convolutional(h * sh, w * sw, c)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(LayerConf):
    padding: Tuple[int, int, int, int] = (0, 0, 0, 0)   # top,bottom,left,right

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        t, b, l, r = self.padding
        return InputType.convolutional(h + t + b, w + l + r, c)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Cropping2D(LayerConf):
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top,bottom,left,right

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        t, b, l, r = self.cropping
        return InputType.convolutional(h - t - b, w - l - r, c)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self.cropping
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b if b else h, l:w - r if r else w, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class SpaceToDepthLayer(LayerConf):
    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        bs = self.block_size
        return InputType.convolutional(h // bs, w // bs, c * bs * bs)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b, h, w, c = x.shape
        bs = self.block_size
        x = x.reshape(b, h // bs, bs, w // bs, bs, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, h // bs, w // bs, bs * bs * c), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SpaceToBatchLayer(LayerConf):
    block_size: Tuple[int, int] = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        bh, bw = _pair(self.block_size)
        return InputType.convolutional(h // bh, w // bw, c)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        b, h, w, c = x.shape
        bh, bw = _pair(self.block_size)
        x = x.reshape(b, h // bh, bh, w // bw, bw, c)
        x = x.transpose(2, 4, 0, 1, 3, 5)
        return x.reshape(b * bh * bw, h // bh, w // bw, c), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Convolution1DLayer(LayerConf):
    """1D convolution over (B, T, C) (DL4J Convolution1DLayer)."""
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    dilation: int = 1
    convolution_mode: str = "same"
    activation: str = "identity"
    weight_init: str = "relu"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        ot = _conv_out_dim(t, self.kernel, self.stride, self.dilation,
                           self.convolution_mode)
        return InputType(Kind.RNN, (ot, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        c_in = input_type.shape[1]
        fan_in = c_in * self.kernel
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (self.kernel, c_in, self.n_out), fan_in,
                              self.n_out * self.kernel, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,),
            padding=_padding(self.convolution_mode),
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(LayerConf):
    kernel: int = 2
    stride: int = 2
    pooling_type: str = "max"
    convolution_mode: str = "truncate"

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        ot = _conv_out_dim(t, self.kernel, self.stride, 1, self.convolution_mode)
        return InputType(Kind.RNN, (ot, c))

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        dims, strides = (1, self.kernel, 1), (1, self.stride, 1)
        pad = _padding(self.convolution_mode)
        if self.pooling_type == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides, pad)
            y = s / cnt
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class CnnLossLayer(LayerConf):
    """Per-pixel loss head for dense prediction (DL4J CnnLossLayer)."""
    activation: str = "softmax"
    loss: str = "mcxent"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        b = x.shape[0]
        z = x.reshape(b, -1, x.shape[-1])
        lab = labels.reshape(b, -1, labels.shape[-1])
        loss_fn = get_loss(self.loss)
        per_pix_mask = None
        if mask is not None:
            per_pix_mask = mask.reshape(b, -1)
        return loss_fn(lab, z, self.activation, mask=per_pix_mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class Cropping1D(LayerConf):
    """Crop timesteps off a (B, T, C) sequence (DL4J
    nn/conf/layers/convolutional/Cropping1D.java)."""
    cropping: Tuple[int, int] = (0, 0)      # (head, tail)

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        a, b = self.cropping
        return InputType(Kind.RNN, (t - a - b, c))

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        a, b = self.cropping
        T = x.shape[1]
        return x[:, a:T - b if b else T, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class Upsampling1D(LayerConf):
    """Repeat each timestep `size` times (DL4J Upsampling1D.java)."""
    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        return InputType(Kind.RNN, (t * int(self.size), c))

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, int(self.size), axis=1), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ZeroPadding1DLayer(LayerConf):
    """Zero-pad the time axis of a (B, T, C) sequence (DL4J
    ZeroPadding1DLayer.java)."""
    padding: Tuple[int, int] = (0, 0)       # (head, tail)

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        a, b = self.padding
        return InputType(Kind.RNN, (t + a + b, c))

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        a, b = self.padding
        return jnp.pad(x, ((0, 0), (a, b), (0, 0))), state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocallyConnected1D(LayerConf):
    """1D convolution with UNTIED weights — a distinct kernel per output
    position (DL4J nn/conf/layers/LocallyConnected1D.java, a SameDiff
    layer in the reference; here one einsum over extracted patches, which
    XLA maps onto the MXU as a batched matmul).

    W: (ot, k*c_in, n_out); b: (ot, n_out) — matching Keras
    LocallyConnected1D's storage so import is a verbatim copy."""
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    convolution_mode: str = "truncate"
    activation: str = "identity"
    weight_init: str = "xavier"
    has_bias: bool = True

    def _out_len(self, t: int) -> int:
        return _conv_out_dim(t, self.kernel, self.stride, 1,
                             "truncate" if self.convolution_mode != "strict"
                             else "strict")

    def output_type(self, input_type: InputType) -> InputType:
        t, c = input_type.shape
        return InputType(Kind.RNN, (self._out_len(t), self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        t, c = input_type.shape
        ot = self._out_len(t)
        fan_in = self.kernel * c
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (ot, self.kernel * c, self.n_out),
                              fan_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((ot, self.n_out), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        ot = params["W"].shape[0]
        # patches[b, o, k*c] for output position o
        idx = (jnp.arange(ot)[:, None] * self.stride
               + jnp.arange(self.kernel)[None, :])        # (ot, k)
        patches = x[:, idx, :]                            # (B, ot, k, C)
        patches = patches.reshape(x.shape[0], ot, -1)     # (B, ot, k*C)
        y = jnp.einsum("bok,okn->bon", patches, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocallyConnected2D(LayerConf):
    """2D convolution with untied weights (DL4J LocallyConnected2D.java).
    W: (oh*ow, kh*kw*c_in, n_out); b: (oh, ow, n_out) — Keras
    LocallyConnected2D storage, verbatim import."""
    n_out: int = 0
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    activation: str = "identity"
    weight_init: str = "xavier"
    has_bias: bool = True

    def _out_hw(self, h: int, w: int) -> Tuple[int, int]:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        mode = "strict" if self.convolution_mode == "strict" else "truncate"
        return (_conv_out_dim(h, kh, sh, 1, mode),
                _conv_out_dim(w, kw, sw, 1, mode))

    def output_type(self, input_type: InputType) -> InputType:
        h, w, c = input_type.shape
        oh, ow = self._out_hw(h, w)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        h, w, c = input_type.shape
        oh, ow = self._out_hw(h, w)
        kh, kw = _pair(self.kernel)
        fan_in = kh * kw * c
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (oh * ow, fan_in, self.n_out),
                              fan_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((oh, ow, self.n_out), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        B, H, W, C = x.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        oh, ow = self._out_hw(H, W)
        iy = (jnp.arange(oh)[:, None] * sh
              + jnp.arange(kh)[None, :])                  # (oh, kh)
        ix = (jnp.arange(ow)[:, None] * sw
              + jnp.arange(kw)[None, :])                  # (ow, kw)
        # (B, oh, kh, ow, kw, C) -> (B, oh, ow, kh, kw, C)
        patches = x[:, iy[:, :, None, None], ix[None, None, :, :], :]
        patches = patches.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, oh * ow, kh * kw * C)
        y = jnp.einsum("bok,okn->bon", patches, params["W"])
        y = y.reshape(B, oh, ow, self.n_out)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state
