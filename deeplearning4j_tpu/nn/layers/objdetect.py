"""Object detection output layer (YOLOv2).

Parity target: DL4J `nn/layers/objdetect/Yolo2OutputLayer.java` +
`nn/conf/layers/objdetect/Yolo2OutputLayer.java` — the YOLOv2 loss head used
by the TinyYOLO / YOLO2 zoo models, plus `DetectedObject` /
`YoloUtils`-style decoding (non-max suppression).

TPU-native design notes:
- Activations are NHWC (B, H, W, A*(5+C)); DL4J is NCHW. Labels are
  (B, H, W, 4+C): [x1, y1, x2, y2] in *grid units* plus one-hot class —
  the same logical content as DL4J's (mb, 4+C, H, W) label format.
- The whole loss (responsible-anchor assignment via IOU argmax, coordinate
  SSE, confidence and class terms) is branch-free vectorized XLA; there is
  no per-cell Python loop, so it fuses into the surrounding training step.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import (
    InputType, LayerConf, register_layer,
)


def _split_predictions(x, n_anchors: int, n_classes: int):
    """(B,H,W,A*(5+C)) -> xy (sig), wh (raw), conf (sig), class logits."""
    b, h, w, _ = x.shape
    x = x.reshape(b, h, w, n_anchors, 5 + n_classes)
    txy = jax.nn.sigmoid(x[..., 0:2])          # offset within cell
    twh = x[..., 2:4]                          # raw; box = anchor * exp(twh)
    conf = jax.nn.sigmoid(x[..., 4])
    cls_logits = x[..., 5:]
    return txy, twh, conf, cls_logits


@register_layer
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(LayerConf):
    """YOLOv2 loss head (DL4J Yolo2OutputLayer).

    lambda_coord / lambda_no_obj mirror DL4J's `lambdaCoord` (5.0) and
    `lambdaNoObj` (0.5) defaults.
    """
    anchors: Tuple[Tuple[float, float], ...] = ()
    n_classes: int = 20
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        """Activated predictions: sigmoid(xy, conf), anchor*exp(wh),
        softmax(class) — DL4J Yolo2OutputLayer.activate()."""
        b, h, w, _ = x.shape
        n_a = len(self.anchors)
        txy, twh, conf, cls_logits = _split_predictions(x, n_a, self.n_classes)
        anchors = jnp.asarray(self.anchors, x.dtype)          # (A, 2)
        wh = anchors * jnp.exp(twh)
        probs = jax.nn.softmax(cls_logits, axis=-1)
        out = jnp.concatenate(
            [txy, wh, conf[..., None], probs], axis=-1)
        return out.reshape(b, h, w, n_a * (5 + self.n_classes)), state

    # ----------------------------------------------------------------- loss
    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        """YOLOv2 loss (DL4J Yolo2OutputLayer.computeScore):
        coordinate SSE (responsible anchors, lambda_coord) + confidence
        (IOU target for responsible, lambda_no_obj elsewhere) + class SSE.
        labels: (B, H, W, 4+C), boxes as [x1,y1,x2,y2] in grid units."""
        # accumulate in >= f32 (f64 under float64 gradient checking)
        f32 = jnp.promote_types(jnp.float32, x.dtype)
        x = x.astype(f32)
        labels = labels.astype(f32)
        b, h, w, _ = x.shape
        n_a = len(self.anchors)
        txy, twh, conf, cls_logits = _split_predictions(x, n_a, self.n_classes)

        lab_box = labels[..., 0:4]                       # (B,H,W,4) grid units
        lab_cls = labels[..., 4:]                        # (B,H,W,C)
        # object mask: a cell has an object iff its label box has area > 0
        gt_wh = lab_box[..., 2:4] - lab_box[..., 0:2]
        obj = (gt_wh[..., 0] * gt_wh[..., 1] > 0).astype(f32)   # (B,H,W)

        gt_center = 0.5 * (lab_box[..., 0:2] + lab_box[..., 2:4])
        gt_xy_in_cell = gt_center - jnp.floor(gt_center)        # (B,H,W,2)

        anchors = jnp.asarray(self.anchors, f32)                # (A,2)
        pred_wh = anchors * jnp.exp(twh)                        # (B,H,W,A,2)

        # Predicted box corners in grid units: center = cell index +
        # sigmoid(txy) (DL4J predictedXYCenterGrid, Yolo2OutputLayer.java:153).
        cell_x = jax.lax.broadcasted_iota(f32, (h, w), 1)[None, :, :, None]
        cell_y = jax.lax.broadcasted_iota(f32, (h, w), 0)[None, :, :, None]
        pred_cx = cell_x + txy[..., 0]
        pred_cy = cell_y + txy[..., 1]
        pred_x1 = pred_cx - pred_wh[..., 0] * 0.5
        pred_x2 = pred_cx + pred_wh[..., 0] * 0.5
        pred_y1 = pred_cy - pred_wh[..., 1] * 0.5
        pred_y2 = pred_cy + pred_wh[..., 1] * 0.5

        # IOU against the actual label corner positions (DL4J
        # calculateIOULabelPredicted): overlap of true rectangles.
        ix = (jnp.minimum(pred_x2, lab_box[..., None, 2]) -
              jnp.maximum(pred_x1, lab_box[..., None, 0]))
        iy = (jnp.minimum(pred_y2, lab_box[..., None, 3]) -
              jnp.maximum(pred_y1, lab_box[..., None, 1]))
        inter = jnp.maximum(ix, 0.0) * jnp.maximum(iy, 0.0)
        union = (pred_wh[..., 0] * pred_wh[..., 1] +
                 (gt_wh[..., 0] * gt_wh[..., 1])[..., None] - inter)
        iou = inter / (union + 1e-9)                            # (B,H,W,A)
        responsible = jax.nn.one_hot(jnp.argmax(iou, axis=-1), n_a,
                                     dtype=f32) * obj[..., None]  # (B,H,W,A)

        # coordinate loss: xy SSE within the cell; wh SSE on sqrt of actual
        # grid-unit sizes (DL4J Yolo2OutputLayer.java:128,147 — sqrt(w),
        # sqrt(h), NOT sqrt(w/anchor)).
        xy_err = jnp.sum((txy - gt_xy_in_cell[..., None, :]) ** 2, axis=-1)
        wh_err = jnp.sum((jnp.sqrt(jnp.maximum(pred_wh, 1e-9)) -
                          jnp.sqrt(jnp.maximum(gt_wh[..., None, :], 1e-9)))
                         ** 2, axis=-1)
        coord_loss = self.lambda_coord * jnp.sum(
            responsible * (xy_err + wh_err))

        # confidence: target IOU where responsible, 0 elsewhere
        conf_obj = jnp.sum(responsible * (conf - iou) ** 2)
        conf_noobj = self.lambda_no_obj * jnp.sum(
            (1.0 - responsible) * conf ** 2)

        # class loss: softmax SSE over responsible cells (DL4J default
        # LossL2 on softmax output)
        probs = jax.nn.softmax(cls_logits, axis=-1)
        cls_err = jnp.sum((probs - lab_cls[..., None, :]) ** 2, axis=-1)
        cls_loss = jnp.sum(responsible * cls_err)

        total = coord_loss + conf_obj + conf_noobj + cls_loss
        return total / jnp.asarray(b, f32)


def decode_detections(activated, anchors, n_classes: int,
                      conf_threshold: float = 0.5):
    """Decode activated YOLO output into (boxes, scores, classes) per image —
    the analog of DL4J `Yolo2OutputLayer.getPredictedObjects`.

    activated: (B, H, W, A*(5+C)) from Yolo2OutputLayer.apply. Returns numpy
    lists (host-side postprocessing, like DL4J's DetectedObject list)."""
    import numpy as np
    activated = np.asarray(activated)
    b, h, w, _ = activated.shape
    n_a = len(anchors)
    act = activated.reshape(b, h, w, n_a, 5 + n_classes)
    results = []
    for i in range(b):
        boxes, scores, classes = [], [], []
        xy = act[i, ..., 0:2]
        wh = act[i, ..., 2:4]
        conf = act[i, ..., 4]
        probs = act[i, ..., 5:]
        for yy in range(h):
            for xx in range(w):
                for a in range(n_a):
                    if conf[yy, xx, a] < conf_threshold:
                        continue
                    cx = xx + xy[yy, xx, a, 0]
                    cy = yy + xy[yy, xx, a, 1]
                    bw, bh = wh[yy, xx, a]
                    boxes.append([cx - bw / 2, cy - bh / 2,
                                  cx + bw / 2, cy + bh / 2])
                    scores.append(float(conf[yy, xx, a]))
                    classes.append(int(np.argmax(probs[yy, xx, a])))
        results.append((np.asarray(boxes, np.float32),
                        np.asarray(scores, np.float32),
                        np.asarray(classes, np.int32)))
    return results


def non_max_suppression(boxes, scores, classes=None,
                        iou_threshold: float = 0.45):
    """Greedy NMS over decoded boxes (DL4J YoloUtils.nms). Host-side.

    Like DL4J (YoloUtils.java:105-124), suppression only applies between
    boxes of the same predicted class; pass `classes=None` to treat all
    boxes as one class."""
    import numpy as np
    if len(boxes) == 0:
        return []
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for idx in order:
        if suppressed[idx]:
            continue
        keep.append(int(idx))
        x1 = np.maximum(boxes[idx, 0], boxes[:, 0])
        y1 = np.maximum(boxes[idx, 1], boxes[:, 1])
        x2 = np.minimum(boxes[idx, 2], boxes[:, 2])
        y2 = np.minimum(boxes[idx, 3], boxes[:, 3])
        inter = np.maximum(0, x2 - x1) * np.maximum(0, y2 - y1)
        area_i = ((boxes[idx, 2] - boxes[idx, 0]) *
                  (boxes[idx, 3] - boxes[idx, 1]))
        areas = ((boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]))
        iou = inter / (area_i + areas - inter + 1e-9)
        over = iou > iou_threshold
        if classes is not None:
            over &= np.asarray(classes) == classes[idx]
        suppressed |= over
    return keep
