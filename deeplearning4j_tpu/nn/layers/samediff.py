"""User-defined layers and freezing wrappers.

- SameDiffLayer <- DL4J nn/layers/samediff/SameDiffLayer.java: the escape
  hatch for custom layers. Here a custom layer supplies plain JAX functions
  (define_params / forward) — autodiff handles backward, as SameDiff's graph
  did in the reference.
- FrozenLayerWrapper <- DL4J nn/layers/FrozenLayer.java: wraps any layer,
  stopping gradients (lax.stop_gradient) so transfer learning can freeze
  feature extractors.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.base import InputType, LayerConf, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class SameDiffLayer(LayerConf):
    """Custom layer from user-supplied pure functions.

    define_params(key, input_type, dtype) -> params dict
    forward(params, x, train) -> y
    out_type(input_type) -> InputType

    Not JSON-serializable unless the callables are module-level and
    re-registered on load (same caveat as DL4J custom layers needing
    the class on the classpath).
    """
    define_params: Optional[Callable] = None
    forward: Optional[Callable] = None
    out_type: Optional[Callable] = None

    def output_type(self, input_type: InputType) -> InputType:
        if self.out_type is not None:
            return self.out_type(input_type)
        return input_type

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        if self.define_params is None:
            return {}, {}
        return self.define_params(key, input_type, dtype), {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.forward(params, x, train), state


@register_layer
@dataclasses.dataclass(frozen=True)
class FrozenLayerWrapper(LayerConf):
    """Stop-gradient wrapper (DL4J FrozenLayer). Params exist but receive no
    gradient; the updater additionally maps them to NoOp (see
    MultiLayerNetwork._label_params)."""
    layer: Optional[LayerConf] = None

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        return self.layer.init(key, input_type, dtype)

    def has_params(self):
        return self.layer.has_params()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        # frozen layers run in inference mode (DL4J FrozenLayer semantics)
        return self.layer.apply(frozen, state, x, train=False, rng=rng, mask=mask)

    def apply_seq(self, params, x, carry, *, train=False, rng=None,
                  mask=None):
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        return self.layer.apply_seq(frozen, x, carry, train=False,
                                    rng=rng, mask=mask)

    def rnn_step(self, params, x_t, carry):
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        return self.layer.rnn_step(frozen, x_t, carry)

    def __getattr__(self, name):
        # delegate the rest of the layer contract (score for output
        # layers, regularization_score, n_out, ...) so a frozen vertex
        # stays a drop-in for its wrapped layer; stateful entry points
        # above freeze their params explicitly.
        if name.startswith("__") or name == "layer":
            raise AttributeError(name)
        inner = object.__getattribute__(self, "layer")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)
