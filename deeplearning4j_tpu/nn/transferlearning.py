"""Transfer learning — graph surgery on trained networks.

Parity target: DL4J `nn/transferlearning/`:
- `TransferLearning.Builder` (MultiLayerNetwork): `setFeatureExtractor(n)`
  freeze up to layer n, `removeOutputLayer`/`removeLayersFromOutput`,
  `addLayer`, `nOutReplace`, `fineTuneConfiguration`.
- `TransferLearning.GraphBuilder` (ComputationGraph): same by vertex name.
- `FineTuneConfiguration`: override updater/lr/dropout on retained layers.
- `TransferLearningHelper`: featurize — split frozen body from trainable
  head and train only the head on cached features.

Params are pytrees here, so "surgery" is dict manipulation + re-init of new
layers; frozen layers keep weights via FrozenLayerWrapper (stop_gradient +
NoOp updater — MultiLayerNetwork._label_params).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import LayerConf
from deeplearning4j_tpu.nn.layers.samediff import FrozenLayerWrapper
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global overrides applied to every retained layer (DL4J
    FineTuneConfiguration: updater, l1/l2, dropout, seed...)."""
    updater: Optional[Any] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply_to_layer(self, layer: LayerConf) -> LayerConf:
        changes = {}
        for f in ("l1", "l2", "dropout"):
            v = getattr(self, f)
            if v is not None and hasattr(layer, f):
                changes[f] = v
        return dataclasses.replace(layer, **changes) if changes else layer


class TransferLearning:
    """Builder for surgically-modified networks (DL4J TransferLearning.Builder)."""

    def __init__(self, network: MultiLayerNetwork):
        if network.params is None:
            raise ValueError("source network must be initialized/trained")
        self._net = network
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._remove_from: Optional[int] = None
        self._appended: List[LayerConf] = []
        self._n_out_replace: Dict[int, int] = {}

    def fine_tune_configuration(self, cfg: FineTuneConfiguration):
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, layer_index: int):
        """Freeze layers [0..layer_index] (DL4J setFeatureExtractor)."""
        self._freeze_until = layer_index
        return self

    def remove_output_layer(self):
        return self.remove_layers_from_output(1)

    def remove_layers_from_output(self, n: int):
        self._remove_from = len(self._net.layers) - n
        return self

    def n_out_replace(self, layer_index: int, n_out: int):
        """Change a layer's width; its params and the next layer's input
        params are re-initialized (DL4J nOutReplace)."""
        self._n_out_replace[layer_index] = n_out
        return self

    def add_layer(self, layer: LayerConf):
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._net
        keep = len(src.layers) if self._remove_from is None else self._remove_from
        reinit: set = set()
        new_layers: List[LayerConf] = []
        for i in range(keep):
            layer = src.layers[i]
            base = layer.layer if isinstance(layer, FrozenLayerWrapper) else layer
            if i in self._n_out_replace:
                base = dataclasses.replace(base,
                                           n_out=self._n_out_replace[i])
                reinit.add(i)
                # the width change invalidates every following layer up
                # to and including the next one with its own n_out (BN /
                # activation / dropout are width-transparent: their
                # params, if any, are shaped by the new width AND the
                # width flows on to the next projection)
                for j in range(i + 1, keep):
                    reinit.add(j)
                    nxt = src.layers[j]
                    nxt = nxt.layer if isinstance(nxt, FrozenLayerWrapper) \
                        else nxt
                    if getattr(nxt, "n_out", 0):
                        break
            if self._fine_tune is not None:
                base = self._fine_tune.apply_to_layer(base)
            if self._freeze_until is not None and i <= self._freeze_until:
                new_layers.append(FrozenLayerWrapper(layer=base))
            else:
                new_layers.append(base)
        n_kept = len(new_layers)
        new_layers.extend(self._appended)

        conf_changes = {"layers": tuple(new_layers)}
        if self._fine_tune is not None:
            if self._fine_tune.updater is not None:
                conf_changes["updater"] = self._fine_tune.updater
            if self._fine_tune.seed is not None:
                conf_changes["seed"] = self._fine_tune.seed
        new_conf = dataclasses.replace(src.conf, **conf_changes)
        net = MultiLayerNetwork(new_conf).init()
        # copy weights for retained, non-reinitialized layers. Real
        # copies, not aliases: the derived network's train step donates
        # its buffers, and donated aliases would delete the SOURCE
        # network's params out from under it.
        for i in range(n_kept):
            if i in reinit:
                continue
            net.params[str(i)] = jax.tree_util.tree_map(
                jnp.copy, src.params[str(i)])
            net.state[str(i)] = jax.tree_util.tree_map(
                jnp.copy, src.state[str(i)])
        net._build_optimizer()
        return net


class TransferLearningHelper:
    """Featurization workflow (DL4J TransferLearningHelper): run the frozen
    body once per input, train only the head on the features."""

    def __init__(self, network: MultiLayerNetwork, frozen_until: int):
        """frozen_until: last frozen layer index (inclusive)."""
        self.src = network
        self.frozen_until = frozen_until
        self._split = frozen_until + 1
        head_layers = network.layers[self._split:]
        import dataclasses as dc
        # head input type = output type of the frozen body
        types = network._resolve_types()
        if self._split < len(network.layers):
            body_out = network.layers[self._split - 1].output_type(
                types[self._split - 1])
        else:
            raise ValueError("frozen_until leaves no trainable head")
        head_conf = dc.replace(network.conf, layers=tuple(head_layers),
                               input_type=body_out)
        self.head = MultiLayerNetwork(head_conf).init()
        import jax.numpy as jnp
        for i, _ in enumerate(head_layers):
            # materialized copies: the head's train step donates its
            # buffers, and aliasing would delete the source network's
            # parameters out from under it
            self.head.params[str(i)] = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True),
                network.params[str(self._split + i)])
            self.head.state[str(i)] = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True),
                network.state[str(self._split + i)])
        self.head._build_optimizer()

    def featurize(self, features):
        """Frozen-body forward (cache these — DL4J featurize())."""
        import jax.numpy as jnp
        x, _, _ = self.src._forward(self.src.params, self.src.state,
                                    jnp.asarray(features), False, None,
                                    upto=self._split)
        return x

    def fit_featurized(self, features, labels, epochs: int = 1,
                       batch_size: int = 32):
        self.head.fit((features, labels), epochs=epochs,
                      batch_size=batch_size)
        return self.head

    def unfrozen_network(self) -> MultiLayerNetwork:
        """Write the trained head back into a full network copy."""
        import jax.numpy as jnp
        net = self.src.copy()
        for i in range(self._split, len(net.layers)):
            # copies, not aliases: training the returned network donates
            # its buffers, which must not delete the head's parameters
            net.params[str(i)] = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True),
                self.head.params[str(i - self._split)])
        return net


class TransferLearningGraph:
    """Surgical modification of a ComputationGraph (DL4J
    TransferLearning.GraphBuilder): freeze by vertex name, remove
    vertices/connections, attach new layers, swap outputs — keeping the
    retained vertices' trained weights."""

    def __init__(self, graph):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if not isinstance(graph, ComputationGraph):
            raise TypeError("TransferLearningGraph wraps a ComputationGraph")
        if graph.params is None:
            raise ValueError("source graph must be initialized/trained")
        self._net = graph
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_at: List[str] = []
        self._removed: List[str] = []
        self._added: List[tuple] = []        # (name, layer, inputs)
        self._n_out_replace: Dict[str, int] = {}
        self._outputs: Optional[tuple] = None

    def fine_tune_configuration(self, cfg: FineTuneConfiguration):
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, *vertex_names: str):
        """Freeze the named vertices and every ancestor feeding them
        (DL4J setFeatureExtractor(vertexName))."""
        self._freeze_at.extend(vertex_names)
        return self

    def remove_vertex_and_connections(self, name: str):
        self._removed.append(name)
        return self

    def add_layer(self, name: str, layer: LayerConf, *inputs: str):
        self._added.append((name, layer, tuple(inputs)))
        return self

    def n_out_replace(self, name: str, n_out: int):
        self._n_out_replace[name] = n_out
        return self

    def set_outputs(self, *names: str):
        self._outputs = tuple(names)
        return self

    # ------------------------------------------------------------- build
    def _ancestors(self, conf, targets) -> set:
        out = set()
        stack = list(targets)
        while stack:
            v = stack.pop()
            if v in out or v in conf.network_inputs:
                continue
            out.add(v)
            stack.extend(conf.vertices[v].inputs)
        return out

    def build(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        src = self._net
        conf = src.conf
        known = set(conf.vertices) | set(conf.network_inputs)
        referenced = (set(self._freeze_at) | set(self._removed)
                      | set(self._n_out_replace))
        unknown = sorted(referenced - known)
        if unknown:
            raise ValueError(f"unknown vertex names {unknown}; graph has "
                             f"{sorted(conf.vertices)}")
        for name in self._n_out_replace:
            if not hasattr(conf.vertices[name].vertex, "n_out"):
                raise ValueError(
                    f"n_out_replace('{name}'): vertex type "
                    f"{type(conf.vertices[name].vertex).__name__} has no "
                    "n_out to replace")
        removed = set(self._removed)
        # removing a vertex also drops every descendant that depends on
        # it — iterate to closure over vanished inputs
        changed = True
        while changed:
            changed = False
            for name, vd in conf.vertices.items():
                if name in removed:
                    continue
                if any(i in removed for i in vd.inputs):
                    removed.add(name)
                    changed = True

        frozen = self._ancestors(conf, self._freeze_at) if self._freeze_at \
            else set()
        # a width change invalidates every consumer whose fan-in changed,
        # INCLUDING through parameterless pass-through vertices
        # (Merge/ElementWise/...) that forward the new width downstream
        reinit: set = set(self._n_out_replace)
        width_changed = set(self._n_out_replace)
        for name in conf.topological_order():
            vd = conf.vertices.get(name)
            if vd is None or name in width_changed:
                continue
            if any(i in width_changed for i in vd.inputs):
                reinit.add(name)
                vertex = vd.vertex
                if isinstance(vertex, FrozenLayerWrapper):
                    vertex = vertex.layer
                # width flows through anything without its own n_out
                # projection (Merge/ElementWise, BatchNorm, activations)
                if not getattr(vertex, "n_out", 0):
                    width_changed.add(name)

        from deeplearning4j_tpu.nn.conf.network import VertexDef
        new_vertices: Dict[str, Any] = {}
        for name, vd in conf.vertices.items():
            if name in removed:
                continue
            vertex = vd.vertex
            if isinstance(vertex, FrozenLayerWrapper):
                vertex = vertex.layer
            if name in self._n_out_replace and hasattr(vertex, "n_out"):
                vertex = dataclasses.replace(
                    vertex, n_out=self._n_out_replace[name])
            if self._fine_tune is not None and isinstance(vertex, LayerConf):
                vertex = self._fine_tune.apply_to_layer(vertex)
            if name in frozen and isinstance(vertex, LayerConf):
                vertex = FrozenLayerWrapper(layer=vertex)
            new_vertices[name] = dataclasses.replace(vd, vertex=vertex)
        for name, layer, inputs in self._added:
            if name in new_vertices:
                raise ValueError(
                    f"add_layer('{name}'): a vertex with that name is "
                    "already retained — remove it first or pick another "
                    "name")
            missing = [i for i in inputs
                       if i not in new_vertices
                       and i not in conf.network_inputs]
            if missing:
                raise ValueError(f"add_layer('{name}'): unknown inputs "
                                 f"{missing}")
            new_vertices[name] = VertexDef(layer, tuple(inputs))

        outputs = self._outputs if self._outputs is not None else tuple(
            o for o in conf.network_outputs if o in new_vertices)
        if not outputs:
            raise ValueError("resulting graph has no outputs — call "
                             "set_outputs(...)")
        conf_changes = {"vertices": new_vertices,
                        "network_outputs": outputs}
        if self._fine_tune is not None:
            if self._fine_tune.updater is not None:
                conf_changes["updater"] = self._fine_tune.updater
            if self._fine_tune.seed is not None:
                conf_changes["seed"] = self._fine_tune.seed
        new_conf = dataclasses.replace(conf, **conf_changes)
        net = ComputationGraph(new_conf).init()
        added_names = {n for n, _, _ in self._added}
        for name in new_vertices:
            if name in added_names or name in reinit:
                continue
            if name in src.params:
                # real copies — donation in the derived net's train step
                # must not delete the source network's buffers
                net.params[name] = jax.tree_util.tree_map(
                    jnp.copy, src.params[name])
            if src.state and name in src.state:
                net.state[name] = jax.tree_util.tree_map(
                    jnp.copy, src.state[name])
        net._build_optimizer()
        return net
