"""Updaters (optimizers) and learning-rate schedules.

Capability parity with DL4J's IUpdater configs applied by
nn/updater/BaseMultiLayerUpdater.java:208-223 and the ISchedule family.
Realized as optax gradient transformations — the optimizer state is a pytree
(the analog of DL4J's flat updaterState view, ModelSerializer.java:109-125),
serialized alongside params in checkpoints.

Supports DL4J's per-layer updater overrides: `resolve_updater` builds one
transformation per layer via optax.multi_transform when layer configs override
the global updater (DL4J: Layer config `.updater(...)`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import optax


# ---------------------------------------------------------------- schedules
@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base LR schedule config (DL4J ISchedule). `to_optax()` yields an
    optax schedule fn: step -> lr."""

    def to_optax(self):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedSchedule(Schedule):
    value: float

    def to_optax(self):
        return optax.constant_schedule(self.value)


@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    """DL4J StepSchedule: lr * decay^floor(iter/step)."""
    initial: float
    decay_rate: float
    step: int

    def to_optax(self):
        return lambda count: self.initial * (self.decay_rate ** (count // self.step))


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    """DL4J ExponentialSchedule: lr * gamma^iter."""
    initial: float
    gamma: float

    def to_optax(self):
        return lambda count: self.initial * (self.gamma ** count)


@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    """DL4J InverseSchedule: lr / (1 + gamma*iter)^power."""
    initial: float
    gamma: float
    power: float = 1.0

    def to_optax(self):
        return lambda count: self.initial / (1.0 + self.gamma * count) ** self.power


@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    """DL4J PolySchedule: lr * (1 - iter/maxIter)^power."""
    initial: float
    power: float
    max_iter: int

    def to_optax(self):
        return optax.polynomial_schedule(
            init_value=self.initial, end_value=0.0, power=self.power,
            transition_steps=self.max_iter)


@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    """DL4J SigmoidSchedule: lr / (1 + exp(-gamma*(iter-stepSize)))."""
    initial: float
    gamma: float
    step_size: int

    def to_optax(self):
        import jax.numpy as jnp
        return lambda count: self.initial / (1.0 + jnp.exp(-self.gamma * (count - self.step_size)))


@dataclasses.dataclass(frozen=True)
class WarmupCosineSchedule(Schedule):
    """TPU-native addition: linear warmup + cosine decay (no DL4J analog;
    standard for large-batch pod training)."""
    peak: float
    warmup_steps: int
    total_steps: int
    end_value: float = 0.0

    def to_optax(self):
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=self.peak, warmup_steps=self.warmup_steps,
            decay_steps=self.total_steps, end_value=self.end_value)


# ---------------------------------------------------------------- updaters
@dataclasses.dataclass(frozen=True)
class Updater:
    """Base updater config (DL4J IUpdater)."""
    learning_rate: float = 1e-3
    schedule: Optional[Schedule] = None

    def _lr(self):
        if self.schedule is not None:
            return self.schedule.to_optax()
        return self.learning_rate

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    def to_optax(self):
        return optax.sgd(self._lr())


@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    learning_rate: float = 0.1
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self._lr(), momentum=self.momentum, nesterov=True)


@dataclasses.dataclass(frozen=True)
class Momentum(Updater):
    learning_rate: float = 0.1
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self._lr(), momentum=self.momentum, nesterov=False)


@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdamW(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 1e-2

    def to_optax(self):
        return optax.adamw(self._lr(), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@dataclasses.dataclass(frozen=True)
class AMSGrad(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.amsgrad(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.nadam(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    learning_rate: float = 2e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(self._lr(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    learning_rate: float = 1e-1
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(self._lr(), eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    """DL4J AdaDelta carries no learning rate — the update magnitude is
    the RMS(dx)/RMS(g) ratio itself (nd4j AdaDeltaUpdater applies the
    raw delta), i.e. an effective LR of 1.0. optax >= 0.2 defaults
    ``adadelta(learning_rate=None)`` which crashes inside
    ``scale_by_learning_rate``; pin the DL4J semantics explicitly."""
    learning_rate: float = 1.0
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adadelta(self._lr(), rho=self.rho, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    learning_rate: float = 1e-1
    decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(self._lr(), decay=self.decay, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    """Frozen params (DL4J NoOp updater, used by FrozenLayer)."""

    def to_optax(self):
        return optax.set_to_zero()


@dataclasses.dataclass(frozen=True)
class Lars(Updater):
    """TPU-native addition: layer-wise adaptive rate scaling for large-batch
    pod-scale data parallelism (no DL4J analog)."""
    learning_rate: float = 1.0
    momentum: float = 0.9
    weight_decay: float = 0.0

    def to_optax(self):
        return optax.lars(self._lr(), weight_decay=self.weight_decay,
                          momentum=self.momentum)


UPDATERS = {
    "sgd": Sgd,
    "nesterovs": Nesterovs,
    "momentum": Momentum,
    "adam": Adam,
    "adamw": AdamW,
    "amsgrad": AMSGrad,
    "nadam": Nadam,
    "adamax": AdaMax,
    "adagrad": AdaGrad,
    "adadelta": AdaDelta,
    "rmsprop": RmsProp,
    "noop": NoOp,
    "lars": Lars,
}


def get_updater(spec: Any) -> Updater:
    """Resolve an updater from an Updater instance, name, or (name, lr)."""
    if isinstance(spec, Updater):
        return spec
    if isinstance(spec, str):
        return UPDATERS[spec.lower()]()
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        return UPDATERS[str(spec[0]).lower()](learning_rate=float(spec[1]))
    raise ValueError(f"Cannot resolve updater from {spec!r}")


def build_optimizer(updater: Any, grad_clip_norm: Optional[float] = None,
                    grad_clip_value: Optional[float] = None) -> optax.GradientTransformation:
    """Build the final optax chain, including DL4J GradientNormalization
    equivalents (ClipL2PerParamType ~ clip_by_global_norm; ClipElementWise ~
    clip)."""
    tx = get_updater(updater).to_optax()
    chain = []
    if grad_clip_value is not None:
        chain.append(optax.clip(grad_clip_value))
    if grad_clip_norm is not None:
        chain.append(optax.clip_by_global_norm(grad_clip_norm))
    chain.append(tx)
    return optax.chain(*chain) if len(chain) > 1 else tx
