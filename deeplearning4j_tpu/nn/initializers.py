"""Weight initializers.

Capability parity with DL4J WeightInit / WeightInitUtil
(deeplearning4j-nn/.../nn/weights/WeightInit.java, WeightInitUtil.java).
Each initializer is `fn(key, shape, fan_in, fan_out, dtype) -> Array`;
fan values are supplied by the layer (DL4J computes them per-layer too).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _uniform(key, shape, lo, hi, dtype):
    return jax.random.uniform(key, shape, minval=lo, maxval=hi, dtype=dtype)


def zero(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J WeightInit.NORMAL: N(0, 1/sqrt(fanIn))
    return jax.random.normal(key, shape, dtype) / math.sqrt(max(fan_in, 1))


def lecun_normal(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / max(fan_in, 1))


def lecun_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    b = math.sqrt(3.0 / max(fan_in, 1))
    return _uniform(key, shape, -b, b, dtype)


def uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J WeightInit.UNIFORM: U(-a, a), a = 1/sqrt(fanIn)
    a = 1.0 / math.sqrt(max(fan_in, 1))
    return _uniform(key, shape, -a, a, dtype)


def xavier(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # DL4J WeightInit.XAVIER: N(0, 2/(fanIn+fanOut))
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / max(fan_in + fan_out, 1))


def xavier_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    b = math.sqrt(6.0 / max(fan_in + fan_out, 1))
    return _uniform(key, shape, -b, b, dtype)


def xavier_fan_in(key, shape, fan_in, fan_out, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / max(fan_in, 1))


def relu_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    # He init: N(0, 2/fanIn)
    return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / max(fan_in, 1))


def relu_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    b = math.sqrt(6.0 / max(fan_in, 1))
    return _uniform(key, shape, -b, b, dtype)


def sigmoid_uniform(key, shape, fan_in, fan_out, dtype=jnp.float32):
    b = 4.0 * math.sqrt(6.0 / max(fan_in + fan_out, 1))
    return _uniform(key, shape, -b, b, dtype)


def identity_init(key, shape, fan_in, fan_out, dtype=jnp.float32):
    if len(shape) == 2 and shape[0] == shape[1]:
        return jnp.eye(shape[0], dtype=dtype)
    raise ValueError("IDENTITY weight init requires a square 2d shape")


INITIALIZERS = {
    "zero": zero,
    "ones": ones,
    "normal": normal,
    "lecun_normal": lecun_normal,
    "lecun_uniform": lecun_uniform,
    "uniform": uniform,
    "xavier": xavier,
    "xavier_uniform": xavier_uniform,
    "xavier_fan_in": xavier_fan_in,
    "relu": relu_init,
    "he_normal": relu_init,
    "relu_uniform": relu_uniform,
    "he_uniform": relu_uniform,
    "sigmoid_uniform": sigmoid_uniform,
    "identity": identity_init,
}


def get_initializer(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in INITIALIZERS:
        raise ValueError(f"Unknown weight init '{name_or_fn}'. Known: {sorted(INITIALIZERS)}")
    return INITIALIZERS[key]
