"""Loss functions.

Capability parity with DL4J's ILossFunction family (nd4j-api losses consumed by
deeplearning4j-nn output layers; see LossFunctions.LossFunction enum usage in
nn/conf/layers/OutputLayer.java).

Every loss is a pure function
    loss(labels, preout, activation_fn, mask=None, weights=None) -> scalar mean score
with a matching per-example variant used by evaluation. Losses consume the
*pre-activation* output plus the output activation, mirroring DL4J where
ILossFunction.computeGradient receives preOutput and the IActivation — but here
autodiff differentiates through the activation, so there are no hand-derived
fused gradients; the finite-difference gradient-check suite is the oracle
instead (as in deeplearning4j-core/src/test/.../gradientcheck/LossFunctionGradientCheck.java).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation

_EPS = 1e-7


def _apply_mask_and_mean(per_example, mask):
    """Reduce per-example scores to the mean score, respecting an optional mask.

    per_example: (B,) or (B,T) array of per-sample scores.
    mask: broadcastable 0/1 array; masked-out samples contribute nothing
    (DL4J divides by minibatch size of *unmasked* elements for time series).
    """
    if mask is None:
        return jnp.mean(per_example)
    mask = jnp.reshape(mask, per_example.shape)
    total = jnp.sum(per_example * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def _weighted(err, weights):
    if weights is not None:
        err = err * weights
    return err


def mse(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    err = _weighted((out - labels) ** 2, weights)
    return _apply_mask_and_mean(jnp.mean(err, axis=-1), mask)


def mae(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    err = _weighted(jnp.abs(out - labels), weights)
    return _apply_mask_and_mean(jnp.mean(err, axis=-1), mask)


def l1(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    err = _weighted(jnp.abs(out - labels), weights)
    return _apply_mask_and_mean(jnp.sum(err, axis=-1), mask)


def l2(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    err = _weighted((out - labels) ** 2, weights)
    return _apply_mask_and_mean(jnp.sum(err, axis=-1), mask)


def xent(labels, preout, activation="sigmoid", mask=None, weights=None):
    """Binary cross-entropy (DL4J LossBinaryXENT). Computed stably from logits
    when the output activation is sigmoid."""
    act = str(activation).lower() if not callable(activation) else None
    if act == "sigmoid":
        # stable: max(z,0) - z*y + log(1+exp(-|z|))
        z = preout
        per = jnp.maximum(z, 0) - z * labels + jnp.log1p(jnp.exp(-jnp.abs(z)))
    else:
        out = jnp.clip(get_activation(activation)(preout), _EPS, 1.0 - _EPS)
        per = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    per = _weighted(per, weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


def mcxent(labels, preout, activation="softmax", mask=None, weights=None):
    """Multi-class cross-entropy / negative log likelihood
    (DL4J LossMCXENT / LossNegativeLogLikelihood — identical when the output
    activation is softmax). Computed from logits via log_softmax for stability."""
    act = str(activation).lower() if not callable(activation) else None
    if act == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        logp = jnp.log(jnp.clip(get_activation(activation)(preout), _EPS, 1.0))
    per = -_weighted(labels * logp, weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


def sparse_mcxent(labels, preout, activation="softmax", mask=None, weights=None):
    """MCXENT with integer class labels (DL4J LossSparseMCXENT)."""
    logp = jax.nn.log_softmax(preout, axis=-1)
    labels = labels.astype(jnp.int32)
    per = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if weights is not None:
        per = per * jnp.take(weights, labels)
    return _apply_mask_and_mean(per, mask)


negativeloglikelihood = mcxent


def kl_divergence(labels, preout, activation="softmax", mask=None, weights=None):
    out = jnp.clip(get_activation(activation)(preout), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = _weighted(lab * (jnp.log(lab) - jnp.log(out)), weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


def poisson(labels, preout, activation="identity", mask=None, weights=None):
    out = jnp.clip(get_activation(activation)(preout), _EPS, None)
    per = _weighted(out - labels * jnp.log(out), weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


def cosine_proximity(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    dot = jnp.sum(out * labels, axis=-1)
    norm = jnp.linalg.norm(out, axis=-1) * jnp.linalg.norm(labels, axis=-1)
    per = -dot / jnp.maximum(norm, _EPS)
    return _apply_mask_and_mean(per, mask)


def hinge(labels, preout, activation="identity", mask=None, weights=None):
    # labels in {-1, +1}
    out = get_activation(activation)(preout)
    per = _weighted(jnp.maximum(0.0, 1.0 - labels * out), weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


def squared_hinge(labels, preout, activation="identity", mask=None, weights=None):
    out = get_activation(activation)(preout)
    per = _weighted(jnp.maximum(0.0, 1.0 - labels * out) ** 2, weights)
    return _apply_mask_and_mean(jnp.sum(per, axis=-1), mask)


LOSSES = {
    "mse": mse,
    "mae": mae,
    "l1": l1,
    "l2": l2,
    "xent": xent,
    "binary_crossentropy": xent,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "sparse_mcxent": sparse_mcxent,
    "kl_divergence": kl_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
}


def get_loss(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name_or_fn}'. Known: {sorted(LOSSES)}")
    return LOSSES[key]
