"""Package-local call graph for interprocedural graftlint rules.

PR 9's rules are per-module and lexical; the dominant bug class of PRs
8/11/13/14 was *interprocedural* — a blocking launch one call below the
supervisor tick lock, a lock acquired by a helper three frames under
another lock. This module gives rules the one fact those bugs share:
"calling F may execute G".

Resolution is deliberately the cheap 95%: dotted module-level names
through each module's import map (``fleet.http_probe``,
``from x import y``), ``self.``/``cls.``-method calls within the
defining class, plain names against the enclosing function's nested
defs then the module's top level. Anything duck-typed (``replica.kill()``
on a parameter) stays unresolved — rules built on this graph are
therefore under-approximate: they miss dynamic dispatch, they never
invent calls that cannot happen. Precision notes live with each rule.

Qualified names (``qual``) look like
``deeplearning4j_tpu.serving.fleet.ReplicaSupervisor.tick`` —
module dotted path (repo-relative; basename for out-of-tree fixture
files) + class chain + function name. Nested functions append their own
name (``...SubprocessReplica.launch._read_stdout``).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.core import ModuleInfo, _ROOT


def module_dotted(path: str) -> str:
    """``<repo>/deeplearning4j_tpu/serving/fleet.py`` ->
    ``deeplearning4j_tpu.serving.fleet``; files outside the repo (temp
    fixtures) key by basename so fixture graphs are self-contained."""
    ap = os.path.abspath(path)
    if ap.startswith(_ROOT + os.sep):
        rel = os.path.relpath(ap, _ROOT)
    else:
        rel = os.path.basename(ap)
    rel = rel[:-3] if rel.endswith(".py") else rel
    dotted = rel.replace(os.sep, ".")
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    return dotted


class FunctionInfo:
    """One function/method definition and where it lives."""

    __slots__ = ("qual", "node", "module", "cls", "name")

    def __init__(self, qual: str, node: ast.AST, module: ModuleInfo,
                 cls: Optional[str]):
        self.qual = qual
        self.node = node
        self.module = module
        self.cls = cls                      # enclosing class qual, if any
        self.name = node.name               # type: ignore[attr-defined]

    def __repr__(self):                     # pragma: no cover - debug aid
        return f"FunctionInfo({self.qual})"


def _collect_functions(mod: ModuleInfo) -> List[FunctionInfo]:
    base = module_dotted(mod.path)
    out: List[FunctionInfo] = []

    def visit(node: ast.AST, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}",
                      f"{prefix}.{child.name}")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}"
                out.append(FunctionInfo(qual, child, mod, cls))
                # nested defs (thread bodies, spawn closures) get their
                # own node keyed under the enclosing function
                visit(child, qual, cls)

    visit(mod.tree, base, None)
    return out


class CallGraph:
    """Dotted-name + ``self.``-method call edges over a set of modules."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.modules = list(modules)
        self.functions: Dict[str, FunctionInfo] = {}
        #: method name -> quals (for last-resort same-name diagnostics;
        #: NOT used for edge building — too imprecise)
        self._by_module: Dict[str, str] = {}
        for mod in self.modules:
            self._by_module[module_dotted(mod.path)] = mod.path
            for fi in _collect_functions(mod):
                self.functions[fi.qual] = fi
        #: caller qual -> {callee qual}
        self.edges: Dict[str, Set[str]] = {}
        #: (caller, callee) -> first call site node (for findings)
        self.sites: Dict[Tuple[str, str], ast.Call] = {}
        for fi in self.functions.values():
            self._index_calls(fi)

    # ------------------------------------------------------------ building
    def _index_calls(self, fi: FunctionInfo):
        callees = self.edges.setdefault(fi.qual, set())
        for node in self._own_nodes(fi):
            if isinstance(node, ast.Call):
                target = self.resolve(fi, node.func)
                if target is not None and target in self.functions \
                        and target != fi.qual:
                    callees.add(target)
                    self.sites.setdefault((fi.qual, target), node)

    @staticmethod
    def _own_nodes(fi: FunctionInfo) -> Iterable[ast.AST]:
        """Walk `fi`'s body WITHOUT descending into nested function/class
        definitions — their statements execute on their own activation
        (often a different thread), not as part of `fi`."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.Lambda):
                # a lambda body runs when CALLED, not at definition —
                # but spawn-site lambdas (`lambda: self._relaunch(r)`)
                # are how PR 8 moved launches off the tick lock; treat
                # the body as part of the function for reachability
                # (over-approximate in the safe direction for rules
                # that ask "can this be reached from here").
                stack.extend(ast.iter_child_nodes(node))
                yield node
                continue
            stack.extend(ast.iter_child_nodes(node))
            yield node

    # ---------------------------------------------------------- resolution
    def resolve(self, fi: FunctionInfo, func: ast.AST) -> Optional[str]:
        """Resolve a call/reference expression inside `fi` to a known
        function qual, or None. Handles:

        - ``self.method`` / ``cls.method``  -> method on the defining class
        - plain ``name``                    -> nested def in the enclosing
          function chain, else module-level def, else import-resolved
        - dotted ``pkg.mod.fn`` via the module's import map
        """
        mod = fi.module
        base = module_dotted(mod.path)
        # self.method / cls.method
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id in ("self", "cls") and fi.cls:
            cand = f"{fi.cls}.{func.attr}"
            return cand if cand in self.functions else None
        dotted = mod.dotted(func)
        if dotted is None:
            return None
        if "." not in dotted:
            # plain name: nested def in the enclosing function chain
            # first (shadowing), then module level
            prefix = fi.qual
            while True:
                cand = f"{prefix}.{dotted}"
                if cand in self.functions:
                    return cand
                if prefix == base or "." not in prefix:
                    return None
                prefix = prefix.rsplit(".", 1)[0]
        # import-resolved dotted name: "fleet.http_probe" already came
        # back import-expanded from ModuleInfo.dotted
        if dotted in self.functions:
            return dotted
        # `from deeplearning4j_tpu.serving import fleet; fleet.f()` gives
        # "deeplearning4j_tpu.serving.fleet.f" directly; a class-method
        # path like "mod.Class.method" is already the qual shape. One
        # more chance: the head segment may alias a module by basename
        # (fixture files import each other bare).
        head, _, rest = dotted.partition(".")
        if head in self._by_module:
            cand = f"{head}.{rest}"
            return cand if cand in self.functions else None
        return None

    # --------------------------------------------------------- reachability
    def reach_chains(self, start: str, depth: int
                     ) -> Dict[str, List[str]]:
        """BFS: every function reachable from `start` within `depth` call
        edges, mapped to ONE shortest call chain ``[start, ..., target]``
        (for human-readable findings)."""
        chains: Dict[str, List[str]] = {start: [start]}
        frontier = [start]
        for _ in range(depth):
            nxt: List[str] = []
            for q in frontier:
                for callee in sorted(self.edges.get(q, ())):
                    if callee not in chains:
                        chains[callee] = chains[q] + [callee]
                        nxt.append(callee)
            frontier = nxt
            if not frontier:
                break
        return chains
