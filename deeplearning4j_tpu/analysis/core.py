"""graftlint core: AST loading, name resolution, pragmas, baselines.

The framework half of the project-native static-analysis suite (see
docs/STATIC_ANALYSIS.md). Dependency-free by design — stdlib ``ast``
only — because it runs in tier-1 on every PR and must never hinge on a
linter version the container doesn't pin.

Pieces:

- `Finding`: one diagnostic (rule, path, line, message), hashable into a
  stable baseline key that survives unrelated line drift (the key hashes
  the *source line text*, not the line number).
- `ModuleInfo`: a parsed file plus the cross-rule plumbing every rule
  needs — parent links, enclosing-function lookup, and best-effort
  resolution of call names through imports (`from time import sleep`
  still resolves to ``time.sleep``).
- Pragmas: ``# graftlint: disable=<rule>[,<rule>] -- <justification>``
  suppresses findings on its line; ``# graftlint: disable-file=<rule> --
  <justification>`` suppresses for the whole file. The justification is
  REQUIRED and must be non-empty — a suppression is a recorded decision,
  not an escape hatch. Unknown rule names and pragmas that suppress
  nothing are themselves findings (`pragma-hygiene`), so stale
  suppressions rot loudly.
- Baseline: `--write-baseline` snapshots today's unsuppressed findings
  so a NEW rule can land gating only new code while the burn-down file
  shrinks; `--baseline` filters against it and reports stale entries.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: this repo's root (analysis/ is self-hosted two levels below it) —
#: used to relativize baseline keys so they are checkout-portable
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _portable(path: str) -> str:
    """Repo-relative when under the repo, basename otherwise (temp
    fixtures): the same finding must key identically on every checkout."""
    ap = os.path.abspath(path)
    if ap.startswith(_ROOT + os.sep):
        return os.path.relpath(ap, _ROOT).replace(os.sep, "/")
    return os.path.basename(ap)


PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)="
    r"(?P<rules>[A-Za-z0-9_,\- ]+?)"
    r"(?:\s+--\s*(?P<why>.*?))?\s*$")

#: rule id for framework-level pragma findings
PRAGMA_RULE = "pragma-hygiene"

#: rule id for files the analyzer could not read/parse — a lint gate
#: must never treat an unparseable file as clean
PARSE_RULE = "parse-error"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str
    col: int = 0

    def key(self, source_line: str, occurrence: int = 0) -> str:
        """Stable baseline key: rule + repo-relative path + the flagged
        line's text (whitespace-normalized) + an occurrence ordinal —
        survives the file growing above it AND the repo living at a
        different checkout path (a committed baseline must match on
        every machine); the ordinal keeps two identical offending lines
        in one file from sharing a key (a NEW duplicate must gate)."""
        text = " ".join(source_line.split())
        h = hashlib.sha1(
            f"{self.rule}|{_portable(self.path)}|{text}|{occurrence}"
            .encode()).hexdigest()
        return h[:16]

    def render(self, root: Optional[str] = None) -> str:
        p = os.path.relpath(self.path, root) if root else self.path
        return f"{p}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Pragma:
    line: int
    file_level: bool
    rules: Tuple[str, ...]
    justification: str
    used: bool = False
    #: a pragma on a comment-only line suppresses the NEXT line, so long
    #: justifications don't force long source lines (the one place the
    #: targeting rule lives is _apply_pragmas)
    own_line: bool = False


class ModuleInfo:
    """One parsed source file + the shared analysis plumbing."""

    def __init__(self, path: str, text: str, tree: ast.Module):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.imports = _import_map(tree)
        self.pragmas = _parse_pragmas(self.lines)

    # -------------------------------------------------------- navigation
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # ---------------------------------------------------- name resolution
    def dotted(self, node: ast.AST) -> Optional[str]:
        """`jnp.asarray` -> "jax.numpy.asarray" (through import aliases);
        plain names resolve through `from x import y`. Best-effort: None
        for anything not a Name/Attribute chain."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = self.imports.get(cur.id, cur.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def call_name(self, call: ast.Call) -> Optional[str]:
        return self.dotted(call.func)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

def _import_map(tree: ast.Module) -> Dict[str, str]:
    """local alias -> full dotted origin. `import jax.numpy as jnp` maps
    jnp -> jax.numpy; `from time import sleep` maps sleep -> time.sleep;
    `from jax import jit as j` maps j -> jax.jit."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _parse_pragmas(lines: Sequence[str]) -> List[Pragma]:
    out = []
    for i, raw in enumerate(lines, 1):
        m = PRAGMA_RE.search(raw)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",")
                      if r.strip())
        out.append(Pragma(line=i, file_level=(m.group(1) == "disable-file"),
                          rules=rules,
                          justification=(m.group("why") or "").strip(),
                          own_line=raw.lstrip().startswith("#")))
    return out


# ---------------------------------------------------------------- rules
class Rule:
    """Base class: subclasses set `name` (kebab-case id), `summary`, and
    `historical` (the shipped bug this rule encodes), and implement
    `check(module) -> iterable[Finding]`."""

    name = ""
    summary = ""
    historical = ""

    def check(self, module: ModuleInfo) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, message: str) -> Finding:
        return Finding(rule=self.name, path=module.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0), message=message)


class Project:
    """Everything project-wide rules share for one run: the parsed
    modules plus lazily-built, cached cross-module models (call graph,
    concurrency facts). Built once by `run()` so four rules don't build
    four call graphs."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.modules = list(modules)
        self._concurrency = None

    def concurrency(self):
        """The shared ConcurrencyModel (analysis/concurrency.py) —
        imported lazily to keep core.py's import graph acyclic."""
        if self._concurrency is None:
            from deeplearning4j_tpu.analysis.concurrency import (
                ConcurrencyModel,
            )
            self._concurrency = ConcurrencyModel(self.modules)
        return self._concurrency


class ProjectRule(Rule):
    """A rule that needs the WHOLE analyzed tree — the interprocedural
    concurrency family. `check()` is a per-module no-op; the runner
    calls `check_project(project)` once and routes each finding back to
    its module for pragma suppression."""

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project
                      ) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


# ---------------------------------------------------------------- runner
def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(os.path.abspath(p))
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        out.append(os.path.abspath(os.path.join(dirpath, f)))
    return sorted(dict.fromkeys(out))


def load_module(path: str) -> Optional[ModuleInfo]:
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        return ModuleInfo(path, text, ast.parse(text, filename=path))
    except (OSError, SyntaxError):
        return None


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]            # unsuppressed
    suppressed: List[Finding]          # pragma-silenced
    pragma_findings: List[Finding]     # bad/unused pragmas
    files: int = 0
    #: the Project built for this run (lock-graph export reuses its
    #: already-built concurrency model instead of re-analyzing)
    project: Optional[Project] = None

    @property
    def all_unsuppressed(self) -> List[Finding]:
        return sorted(self.findings + self.pragma_findings,
                      key=lambda f: (f.path, f.line, f.rule))


def run(paths: Sequence[str], rules: Sequence[Rule],
        select: Optional[Set[str]] = None,
        module_findings: Optional[Dict[str, List[Finding]]] = None
        ) -> RunResult:
    """Run `rules` over every .py under `paths`, applying pragma
    suppression and pragma hygiene checks.

    `module_findings` (path -> raw findings) lets a caller supply the
    per-module rules' output computed elsewhere — the CLI's multiprocess
    pass (tools/graftlint.py) farms exactly that part out to workers;
    project-wide rules, pragmas and parse-error reporting always run
    here (they need every module in one address space)."""
    active = [r for r in rules if select is None or r.name in select]
    mod_rules = [r for r in active if not isinstance(r, ProjectRule)]
    proj_rules = [r for r in active if isinstance(r, ProjectRule)]
    known = {r.name for r in rules} | {PRAGMA_RULE, PARSE_RULE}
    res = RunResult([], [], [])
    modules: List[ModuleInfo] = []
    for path in iter_py_files(paths):
        mod = load_module(path)
        if mod is None:
            # unreadable/syntax-broken: surface it — zero findings from
            # a file the analyzer never inspected is not "clean"
            res.findings.append(Finding(
                rule=PARSE_RULE, path=path, line=1,
                message="file could not be read/parsed — the analyzer "
                        "inspected none of it"))
            continue
        modules.append(mod)
    res.files = len(modules)
    raw_by_path: Dict[str, List[Finding]] = {m.path: [] for m in modules}
    if module_findings is not None:
        for path, fs in module_findings.items():
            if path in raw_by_path:
                raw_by_path[path].extend(fs)
    else:
        for mod in modules:
            for rule in mod_rules:
                raw_by_path[mod.path].extend(rule.check(mod))
    project = Project(modules)
    res.project = project       # lock-graph export reuses the build
    for rule in proj_rules:
        for f in rule.check_project(project):
            raw_by_path.setdefault(f.path, []).append(f)
    for mod in modules:
        _apply_pragmas(mod, raw_by_path[mod.path], known, res, select)
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return res


def _apply_pragmas(mod: ModuleInfo, raw: List[Finding], known: Set[str],
                   res: RunResult, select: Optional[Set[str]]) -> None:
    line_pragmas: Dict[int, List[Pragma]] = {}
    file_pragmas: List[Pragma] = []
    for pr in mod.pragmas:
        if pr.file_level:
            file_pragmas.append(pr)
        else:
            target = pr.line + 1 if pr.own_line else pr.line
            line_pragmas.setdefault(target, []).append(pr)
        for rname in pr.rules:
            if rname not in known:
                res.pragma_findings.append(Finding(
                    rule=PRAGMA_RULE, path=mod.path, line=pr.line,
                    message=f"pragma names unknown rule {rname!r}"))
        if not pr.justification:
            res.pragma_findings.append(Finding(
                rule=PRAGMA_RULE, path=mod.path, line=pr.line,
                message="suppression requires a justification: "
                        "`# graftlint: disable=<rule> -- <why>`"))
    for f in raw:
        suppressing = None
        for pr in line_pragmas.get(f.line, []):
            if f.rule in pr.rules:
                suppressing = pr
                break
        if suppressing is None:
            for pr in file_pragmas:
                if f.rule in pr.rules:
                    suppressing = pr
                    break
        if suppressing is not None and suppressing.justification:
            suppressing.used = True
            res.suppressed.append(f)
        else:
            if suppressing is not None:
                suppressing.used = True   # used, but invalid (no why)
            res.findings.append(f)
    # a pragma that suppressed nothing is stale — unless the run was
    # rule-filtered (--select), where "its" rule may simply not have run
    if select is None:
        for pr in mod.pragmas:
            if not pr.used and all(r in known for r in pr.rules):
                res.pragma_findings.append(Finding(
                    rule=PRAGMA_RULE, path=mod.path, line=pr.line,
                    message="pragma suppresses nothing on this line — "
                            "remove it (stale suppressions hide regressions)"))


# -------------------------------------------------------------- baseline
def _keyed(result: RunResult) -> List[Tuple[str, Finding]]:
    """(stable-key, finding) pairs; each file read once. Findings that
    would hash identically (same rule+file+line text) get consecutive
    occurrence ordinals in source order, so duplicates stay distinct."""
    cache: Dict[str, List[str]] = {}
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Tuple[str, Finding]] = []
    for f in result.all_unsuppressed:
        if f.path not in cache:
            try:
                with open(f.path, encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        lines = cache[f.path]
        text = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        ident = (f.rule, f.path, " ".join(text.split()))
        n = seen.get(ident, 0)
        seen[ident] = n + 1
        out.append((f.key(text, occurrence=n), f))
    return out


def write_baseline(path: str, result: RunResult) -> None:
    findings = {k: f.render() for k, f in _keyed(result)}
    data = {"version": 1, "findings": findings}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def apply_baseline(path: str, result: RunResult
                   ) -> Tuple[List[Finding], List[str]]:
    """Split result against a baseline: returns (new_findings,
    stale_baseline_keys). Baselined findings don't gate; stale keys mean
    the burn-down shrank — rewrite the file to bank the progress."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    known = set(data.get("findings", {}))
    keyed = _keyed(result)
    new = [f for k, f in keyed if k not in known]
    stale = sorted(known - {k for k, _ in keyed})
    return new, stale
