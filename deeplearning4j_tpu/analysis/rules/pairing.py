"""Rule: resource-pairing — the PR-8 half-open-slot-leak class.

Some resources in this tree are acquired by one call and MUST be given
back by a matching call on **every** path: a circuit breaker's
half-open probe slot (``allow()`` -> exactly one of ``release()`` /
``record_success()`` / ``record_failure()``), a kvcache slot
(``admit``/``admit_prompt`` -> ``release``), a shared-memory segment
(``SharedMemory(create=True)`` -> ``unlink``). PR 8 shipped the
canonical miss: a half-open probe answered with a 429 hit a branch that
recorded *neither* success nor failure nor release — the slot leaked
and the breaker wedged half-open FOREVER, silently excluding a healthy
replica until a generation bump.

The check is per-function and deliberately narrow (no interprocedural
protocol tracking — a scheduler that admits in one method and releases
in another is out of scope and stays silent):

- it engages only when a function contains BOTH an acquire and at least
  one matching release on the *same receiver expression* — that is the
  "this function owns the pairing" signal;
- releases inside a ``finally`` block satisfy every path at once;
- otherwise, any ``return`` / ``raise`` / ``continue`` / ``break``
  between the acquire and the function's last release that has no
  release on its own branch path is flagged — that early exit walks
  away holding the resource;
- denied-acquire branches (``if not x.allow(): return`` and the
  ``while not x.allow():`` pick loop) are exempt: a denied acquire
  holds nothing.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional, Tuple

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

#: (acquire attr, release attrs, what leaks) — attribute-call pairs
#: matched on identical receiver source text
_ATTR_PAIRS = (
    ("allow", ("release", "record_success", "record_failure"),
     "the breaker's half-open probe slot"),
    ("admit", ("release",), "the kvcache slot + its pages"),
    ("admit_prompt", ("release",), "the kvcache slot + its pages"),
)

#: constructor-style acquire: SharedMemory(create=True) must meet
#: .unlink() (the owner side) in the same function or a finally
_SHM_RELEASES = ("unlink", "close")


@dataclasses.dataclass
class _Acquire:
    node: ast.Call
    recv: str                     # receiver source text ("" for ctor)
    releases: Tuple[str, ...]
    what: str


def _recv_text(call: ast.Call) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    try:
        return ast.unparse(call.func.value)
    except Exception:             # pragma: no cover
        return None


class ResourcePairingRule(Rule):
    name = "resource-pairing"
    summary = ("declared acquire/release pairs (breaker allow/release, "
               "kvcache admit/release, SharedMemory create/unlink) must "
               "pair on every path or sit in try/finally")
    historical = ("PR 8: a half-open probe slot consumed by allow() "
                  "leaked on the 429 branch (neither release nor "
                  "record_*) and wedged the breaker half-open forever, "
                  "excluding a healthy replica until a generation bump")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(mod, node)

    # ---------------------------------------------------------- function
    def _check_function(self, mod: ModuleInfo, fn: ast.AST
                        ) -> Iterable[Finding]:
        acquires: List[_Acquire] = []
        # collect this function's own calls — nested defs excluded for
        # ACQUIRES (they run later, on their own activation) but
        # included for RELEASES (a completion callback owning the
        # release is a legitimate pairing pattern, e.g. the router's
        # stream done() closure)
        for call in _walk_skip_defs(fn):
            if not isinstance(call, ast.Call):
                continue
            if isinstance(call.func, ast.Attribute):
                for acq, rels, what in _ATTR_PAIRS:
                    if call.func.attr == acq:
                        recv = _recv_text(call) or ""
                        acquires.append(_Acquire(call, recv, rels, what))
            if isinstance(call.func, (ast.Name, ast.Attribute)):
                name = mod.call_name(call) or ""
                if name.endswith("SharedMemory") and any(
                        kw.arg == "create" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is True for kw in call.keywords):
                    acquires.append(_Acquire(call, "", _SHM_RELEASES,
                                             "the shared-memory segment"))
        if not acquires:
            return                # the overwhelmingly common fast path
        all_calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
        for acq in acquires:
            yield from self._check_acquire(mod, fn, acq, all_calls)

    def _check_acquire(self, mod: ModuleInfo, fn: ast.AST, acq: _Acquire,
                       all_calls: List[ast.Call]) -> Iterable[Finding]:
        if acq.recv:
            releases = [c for c in all_calls
                        if isinstance(c.func, ast.Attribute)
                        and c.func.attr in acq.releases
                        and _recv_text(c) == acq.recv]
        else:
            # ctor acquire: match any release-named call in the function
            releases = [c for c in all_calls
                        if isinstance(c.func, ast.Attribute)
                        and c.func.attr in acq.releases]
        if not releases:
            return                # cross-function protocol: out of scope
        if all(_in_finally(mod, r) for r in releases):
            return                # every path pays on the way out
        a_line = acq.node.lineno
        last_release = max(r.lineno for r in releases)
        for exit_node in _walk_skip_defs(fn):
            if not isinstance(exit_node, (ast.Return, ast.Raise,
                                          ast.Continue, ast.Break)):
                continue
            e_line = exit_node.lineno
            if not (a_line < e_line < last_release):
                continue
            if _in_denied_branch(mod, exit_node, acq.node):
                continue
            if any(r.lineno <= e_line and _on_path(mod, r, exit_node)
                   for r in releases):
                continue
            if _in_finally(mod, exit_node):
                continue
            kind = type(exit_node).__name__.lower()
            yield self.finding(
                mod, exit_node,
                f"this {kind} exits while still holding {acq.what} "
                f"acquired at line {a_line} ({acq.recv or 'ctor'}."
                f"{_attr_of(acq.node)}) — no "
                f"{'/'.join(acq.releases)} on this path (the PR-8 "
                "half-open-slot leak shape); release on every path or "
                "move the release into try/finally")


def _attr_of(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return getattr(call.func, "id", "<call>")


def _walk_skip_defs(fn: ast.AST) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _in_finally(mod: ModuleInfo, node: ast.AST) -> bool:
    child = node
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.Try) and child in _subtree_set(anc.finalbody):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        child = anc
    return False


def _subtree_set(stmts) -> set:
    out = set()
    for s in stmts:
        for n in ast.walk(s):
            out.add(n)
    return out


def _assigned_name(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """`info = x.admit_prompt(p)` -> "info" (single-Name assignment)."""
    parent = mod.parent(call)
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1 and \
            isinstance(parent.targets[0], ast.Name):
        return parent.targets[0].id
    return None


def _in_denied_branch(mod: ModuleInfo, exit_node: ast.AST,
                      acquire: ast.Call) -> bool:
    """`if not x.allow(): return` / `while not x.allow(): ...continue` /
    `info = x.admit(n); if info is None: return` — the exit lives in a
    branch where the acquire was DENIED, so nothing is held."""
    result_name = _assigned_name(mod, acquire)
    for anc in mod.ancestors(exit_node):
        if isinstance(anc, (ast.If, ast.While)):
            test = anc.test
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not) and \
                    acquire in set(ast.walk(test)):
                return True
            if result_name is not None and isinstance(test, ast.Compare) \
                    and isinstance(test.left, ast.Name) \
                    and test.left.id == result_name \
                    and len(test.ops) == 1 \
                    and isinstance(test.ops[0], ast.Is) \
                    and isinstance(test.comparators[0], ast.Constant) \
                    and test.comparators[0].value is None:
                return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _on_path(mod: ModuleInfo, release: ast.AST, exit_node: ast.AST) -> bool:
    """Approximate 'release executes before this exit': true unless the
    release sits in a DIFFERENT branch of the lowest common If/Try
    ancestor (then one of the two paths skips it)."""
    r_anc = [release] + list(mod.ancestors(release))
    e_anc = set([exit_node] + list(mod.ancestors(exit_node)))
    lca = next((a for a in r_anc if a in e_anc), None)
    if lca is None or not isinstance(lca, (ast.If, ast.Try)):
        return True
    # which branch of the LCA holds each node?
    def branch_of(node):
        fields = [("body", lca.body)]
        if isinstance(lca, ast.If):
            fields.append(("orelse", lca.orelse))
        else:
            fields.append(("handlers", lca.handlers))
            fields.append(("orelse", lca.orelse))
            fields.append(("finalbody", lca.finalbody))
        for fname, stmts in fields:
            if node in _subtree_set(stmts):
                return fname
        return None

    return branch_of(release) == branch_of(exit_node)
