"""graftlint rule registry — one module per bug-class family.

Every rule encodes a defect this repo actually shipped (the historical
note on each Rule subclass names the PR). Adding a rule: subclass
`analysis.core.Rule`, register it in ALL_RULES here, add a
positive+negative fixture pair under tests/fixtures/graftlint/, and
document it in docs/STATIC_ANALYSIS.md.
"""
from deeplearning4j_tpu.analysis.rules.donation import DonatedAliasingRule
from deeplearning4j_tpu.analysis.rules.envknobs import EnvKnobContractRule
from deeplearning4j_tpu.analysis.rules.excepts import BareExceptSwallowRule
from deeplearning4j_tpu.analysis.rules.hotpath import (
    HostSyncInHotPathRule, RecompileHazardRule,
)
from deeplearning4j_tpu.analysis.rules.locks import BlockingUnderLockRule
from deeplearning4j_tpu.analysis.rules.lockorder import (
    LockOrderInversionRule, TransitiveBlockingUnderLockRule,
)
from deeplearning4j_tpu.analysis.rules.pairing import ResourcePairingRule
from deeplearning4j_tpu.analysis.rules.restore import (
    UnlaunderedRestorePlacementRule,
)
from deeplearning4j_tpu.analysis.rules.telemetry import (
    MetricFamilyRegistrationRule, TelemetryZeroCostRule,
)
from deeplearning4j_tpu.analysis.rules.threads import ThreadLifecycleRule

ALL_RULES = [
    DonatedAliasingRule(),
    UnlaunderedRestorePlacementRule(),
    HostSyncInHotPathRule(),
    RecompileHazardRule(),
    EnvKnobContractRule(),
    BlockingUnderLockRule(),
    LockOrderInversionRule(),
    TransitiveBlockingUnderLockRule(),
    ThreadLifecycleRule(),
    ResourcePairingRule(),
    TelemetryZeroCostRule(),
    BareExceptSwallowRule(),
    MetricFamilyRegistrationRule(),
]

__all__ = ["ALL_RULES"]
