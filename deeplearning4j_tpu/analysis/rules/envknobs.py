"""Rule: env-knob-contract — every ``DL4J_TPU_*`` knob goes through
util/env.py.

The contract (util/env.py docstring): kill switches are ``=="0"``-ONLY-
disables, opt-ins ``=="1"``-only-enables, ``""`` is unset. PRs 5, 7, and
8 each re-fixed scattered hand-rolled reads that got one of those wrong
(``!= '1'`` turning ``""`` into a disable; ``== "1"`` turning ``"true"``
into one; ``int('')`` crashing a fit). After the PR-9 migration the
accessors are the only reader — this rule locks the door:

- any ``os.environ.get/[]``, ``os.getenv`` read of a literal
  ``DL4J_TPU_*`` name is flagged (writes — ``os.environ[k] = v``,
  ``setdefault`` used to seed child processes, ``del`` — are fine);
- comparing an accessor result against ``"0"``/``"1"`` re-implements
  flag truthiness by hand and is flagged too: boolean knobs use
  `env_flag`.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

_READ_CALLS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
               "os.environ.setdefault", "environ.setdefault"}
_ACCESSORS = {"env_str", "env_raw", "env_int", "env_float"}


def _literal_knob(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("DL4J_TPU_"):
        return node.value
    return None


class EnvKnobContractRule(Rule):
    name = "env-knob-contract"
    summary = ("DL4J_TPU_* reads must go through util/env.py typed "
               "accessors (the =='0'-only-disables contract)")
    historical = ("PRs 5/7/8 each re-fixed a hand-rolled read: != '1' "
                  "made '' disable a default-on feature; == '1' made "
                  "'true' disable one; int('') crashed the fit")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(mod, node)
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, ast.Load):
                if mod.dotted(node.value) in ("os.environ", "environ"):
                    knob = _literal_knob(node.slice)
                    if knob:
                        yield self.finding(
                            mod, node,
                            f"direct os.environ[{knob!r}] read — use the "
                            "util/env.py typed accessor (env_flag/env_int/"
                            "env_str) so the knob contract can't drift")
            elif isinstance(node, ast.Compare):
                yield from self._check_handrolled_flag(mod, node)

    def _check_call(self, mod: ModuleInfo, call: ast.Call
                    ) -> Iterable[Finding]:
        name = mod.call_name(call)
        if name not in _READ_CALLS or not call.args:
            return
        knob = _literal_knob(call.args[0])
        if knob is None:
            return
        if name.endswith(".setdefault"):
            # seeding a default for CHILD processes is a write — but the
            # return value being USED means it doubles as a read
            parent = mod.parent(call)
            if isinstance(parent, ast.Expr):
                return
            yield self.finding(
                mod, call,
                f"os.environ.setdefault({knob!r}) used as a READ — route "
                "the read through util/env.py and keep setdefault for "
                "child-process seeding only")
            return
        yield self.finding(
            mod, call,
            f"raw environment read of {knob!r} — use util/env.py "
            "(env_flag honors the =='0'-only-disables contract; "
            "env_int/env_str treat '' as unset)")

    def _check_handrolled_flag(self, mod: ModuleInfo, cmp: ast.Compare
                               ) -> Iterable[Finding]:
        """`env_str("DL4J_TPU_X") == "1"` — hand-rolled truthiness on an
        accessor result. (Raw-read comparisons are already flagged by
        the read check.)"""
        sides = [cmp.left] + list(cmp.comparators)
        call = next((s for s in sides if isinstance(s, ast.Call)
                     and (mod.call_name(s) or "").split(".")[-1]
                     in _ACCESSORS), None)
        if call is None or not call.args:
            return
        knob = _literal_knob(call.args[0])
        if knob is None:
            return
        lit = next((s for s in sides if isinstance(s, ast.Constant)
                    and s.value in ("0", "1")), None)
        if lit is not None:
            yield self.finding(
                mod, cmp,
                f"hand-rolled flag truthiness on {knob!r} — boolean "
                "knobs use env_flag(name, default=...) so the "
                "=='0'-only-disables contract is applied in one place")
