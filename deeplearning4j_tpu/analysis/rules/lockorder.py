"""Interprocedural lock rules: lock-order-inversion and
transitive-blocking-under-lock.

Both consume the shared ConcurrencyModel (analysis/concurrency.py): a
cross-module lock acquisition-order graph and per-function may-block
facts stitched together by the package call graph.

**lock-order-inversion** — if thread 1 takes A then B while thread 2
takes B then A, each can end up holding one lock and waiting forever on
the other. The acquisition-order graph has an edge A->B for every place
B is acquired while A is held (lexically nested ``with``s OR a call
chain from inside A's region reaching a function that acquires B);
a cycle in that graph is the deadlock precondition. PR 8's original
supervisor shape was one `kill`+`join` away from exactly this — tick()
held the supervisor lock while relaunch paths re-entered registry
locks.

**transitive-blocking-under-lock** — PR 9's blocking-under-lock rule is
lexical: it sees ``time.sleep`` inside ``with lock:`` but not
``self._relaunch()`` inside ``with lock:`` where _relaunch -> launch ->
``Popen.wait``. That one-call-below shape froze the whole fleet in
PR 8 and was only caught in review. This rule follows the call graph up
to K edges out of every held region and reports the chain.

Precision: the call graph resolves dotted + self.-method calls only
(callgraph.py); duck-typed dispatch is invisible, so these rules
under-approximate — they can miss, they don't invent. A reported chain
is a real static call path.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from deeplearning4j_tpu.analysis.core import (
    Finding, Project, ProjectRule,
)
from deeplearning4j_tpu.analysis.rules.locks import _blocking_kind

#: call-edge horizon for the transitive blocking scan ("within K call
#: edges of a held lock"); the PR-8 shape (tick -> _relaunch -> launch
#: -> Popen.wait) needs 3
TRANSITIVE_DEPTH = 3


class LockOrderInversionRule(ProjectRule):
    name = "lock-order-inversion"
    summary = ("cycles in the cross-module lock acquisition-order graph "
               "(two threads can take the locks in opposite orders and "
               "deadlock)")
    historical = ("PR 8: supervisor tick lock held across replica "
                  "relaunch/registry paths — one re-entered lock away "
                  "from an AB/BA deadlock; found twice in review")

    def check_project(self, project: Project) -> Iterable[Finding]:
        model = project.concurrency()
        for cycle in model.cycles():
            desc = " -> ".join(cycle + [cycle[0]])
            # report every edge that participates in the cycle, at its
            # own acquisition site, so each site can be individually
            # fixed or pragma-justified
            members = set(cycle)
            for e in model.order_edges:
                if e.src in members and e.dst in members:
                    via = (" via " + " -> ".join(e.via)) if e.via else \
                        " (lexically nested)"
                    yield Finding(
                        rule=self.name, path=e.module.path,
                        line=getattr(e.node, "lineno", 1),
                        col=getattr(e.node, "col_offset", 0),
                        message=(
                            f"acquires {e.dst!r} while holding "
                            f"{e.src!r}{via}, completing the cycle "
                            f"[{desc}] — another thread taking these "
                            "locks in the opposite order deadlocks "
                            "both; pick ONE global order (see the "
                            "--lock-graph artifact)"))


class TransitiveBlockingUnderLockRule(ProjectRule):
    name = "transitive-blocking-under-lock"
    summary = ("a may-block call reachable within "
               f"{TRANSITIVE_DEPTH} call edges of a held lock "
               "(the lexical blocking-under-lock rule generalized "
               "through the call graph)")
    historical = ("PR 8: SubprocessReplica relaunch — Popen.wait one "
                  "call below the supervisor tick lock — froze probing "
                  "of the whole fleet; lexically invisible, hand-found "
                  "in review twice")

    def check_project(self, project: Project) -> Iterable[Finding]:
        from deeplearning4j_tpu.analysis.concurrency import _region_walk
        model = project.concurrency()
        graph = model.graph
        seen = set()
        for fc in model.functions.values():
            mod = fc.info.module
            for region in fc.regions:
                for stmt in getattr(region.node, "body", []):
                    for node in _region_walk(stmt):
                        if not isinstance(node, ast.Call):
                            continue
                        if _blocking_kind(mod, node):
                            continue          # lexical rule's territory
                        tq = graph.resolve(fc.info, node.func)
                        if tq is None:
                            continue
                        f = self._first_blocking_chain(
                            model, tq, region.lock_name, mod, node)
                        if f is not None:
                            key = (f.path, f.line, f.message)
                            if key not in seen:
                                seen.add(key)
                                yield f

    def _first_blocking_chain(self, model, start: str, lock_name: str,
                              mod, call_node) -> "Finding | None":
        chains = model.graph.reach_chains(start, TRANSITIVE_DEPTH - 1)
        best: "tuple[int, List[str], str] | None" = None
        for reached, chain in chains.items():
            rfc = model.functions.get(reached)
            if rfc is None or not rfc.blocks:
                continue
            kind = rfc.blocks[0].kind
            cand = (len(chain), chain, kind)
            if best is None or cand[0] < best[0]:
                best = cand
        if best is None:
            return None
        _, chain, kind = best
        shown = " -> ".join(q.rsplit(".", 2)[-1] if q.count(".") < 2
                            else ".".join(q.rsplit(".", 2)[-2:])
                            for q in chain)
        # edge count includes the call FROM the lock region into
        # chain[0] — `with lock: helper()` where helper sleeps is
        # 1 edge below the with, not 0
        return Finding(
            rule=self.name, path=mod.path,
            line=getattr(call_node, "lineno", 1),
            col=getattr(call_node, "col_offset", 0),
            message=(
                f"call chain {shown} reaches {kind} while holding "
                f"{lock_name!r} ({len(chain)} call edge(s) below "
                "the `with` — lexically invisible, the PR-8 "
                "fleet-freeze shape); move the blocking work outside "
                "the critical section or cap it with a deadline"))
