"""Rule: unlaundered-restore-placement — the sharding-aware variant of
the PR-3 donated-aliasing shape.

Since the GSPMD ShardingPlan (PR 10), checkpoint restore paths place
parameters onto explicit mesh shardings. ``jax.device_put`` of a freshly
DESERIALIZED value (``np.load`` npz trees, ``flax.serialization
.from_bytes`` updater state, ``pickle.load``) straight onto a sharding
looks correct — the arrays land where the plan wants them — but on CPU
backends a replicated/single-device placement can be ZERO-COPY, so the
"placed" jax array still aliases numpy-owned heap memory; the first
donating train step after resume then frees memory XLA does not own
(the PR-3 serde-resume segfault, now wearing a sharding).

The blessed path is ``util/params.own_tree(tree, shardings)`` /
``owned_leaf(leaf, sharding)`` (or any route that copies first:
``jnp.array(..., copy=True)`` then place) — copy into an XLA-owned
buffer, THEN place.

Detection (per function scope, same lightweight taint style as the
donated-aliasing rule): values assigned from deserialization calls are
tainted; simple-name propagation follows ``x = y``; passing through
``own_tree``/``owned_leaf``/``jnp.array(copy=True)`` clears; a
``device_put`` call whose value argument is tainted AND that names an
explicit placement (second positional arg, or a ``device=``/
``sharding=``/``donate=`` keyword) is flagged.
"""
from __future__ import annotations

import ast
from typing import Iterable, Set

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

_DEVICE_PUT = {"jax.device_put", "device_put"}
_OWNING = {"own_tree", "owned_leaf"}
#: deserialization producers — deliberately NARROWER than the
#: donated-aliasing rule's np.* namespace: plain numpy batch staging may
#: legitimately device_put (batches are never donated); RESTORED state is
#: what reaches donate_argnums.
_TAINT_CALLS = {"numpy.load", "np.load", "pickle.load", "pickle.loads"}
_TAINT_SUFFIX = (".from_bytes",)


def _target_name(t: ast.AST):
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
        return f"{t.value.id}.{t.attr}"
    return None


class UnlaunderedRestorePlacementRule(Rule):
    name = "unlaundered-restore-placement"
    summary = ("restored/deserialized leaves must go through "
               "util/params.own_tree(tree, shardings) (or an explicit "
               "copy) before device_put onto a placement")
    historical = ("PR 3 / PR 10: checkpoint-restored numpy-aliased params "
                  "device_put onto plan shardings can be zero-copy on CPU "
                  "— the donating post-resume step then corrupts the heap "
                  "(the serde-resume segfault, sharding-aware variant)")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        scopes = [mod.tree] + [n for n in ast.walk(mod.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._scope(mod, scope)

    # ------------------------------------------------------------- taint
    def _scope(self, mod: ModuleInfo, scope: ast.AST) -> Iterable[Finding]:
        tainted: Set[str] = set()
        body = scope.body if hasattr(scope, "body") else []
        for stmt in body:
            yield from self._stmt(mod, stmt, tainted)

    def _stmt(self, mod: ModuleInfo, stmt: ast.AST,
              tainted: Set[str]) -> Iterable[Finding]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return      # nested scopes visited on their own
        if isinstance(stmt, ast.Assign):
            taints = self._taints(mod, stmt.value, tainted)
            for t in stmt.targets:
                tn = _target_name(t)
                if tn is not None:
                    (tainted.add if taints else tainted.discard)(tn)
        # check only this statement's OWN expressions — the recursion
        # below visits nested statements exactly once (walking the whole
        # subtree here would double-report a flagged call per enclosing
        # compound statement, the defect class the PR-9 hardening fixed
        # for blocking-under-lock)
        for expr in self._own_exprs(stmt):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    yield from self._check_put(mod, node, tainted)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                yield from self._stmt(mod, child, tainted)

    @staticmethod
    def _own_exprs(stmt: ast.AST):
        """The statement's direct expression children (nested statement
        bodies are excluded — the statement recursion covers those)."""
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                yield child
            elif isinstance(child, (ast.withitem, ast.keyword)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        yield sub

    def _taints(self, mod: ModuleInfo, expr: ast.AST,
                tainted: Set[str]) -> bool:
        if isinstance(expr, ast.Call):
            name = mod.call_name(expr) or ""
            base = name.split(".")[-1]
            if base in _OWNING:
                return False
            if name in ("jax.numpy.array", "jnp.array"):
                copy_kw = next((kw.value.value for kw in expr.keywords
                                if kw.arg == "copy"
                                and isinstance(kw.value, ast.Constant)),
                               None)
                if copy_kw is not False:      # jnp.array default-copies
                    return False
                return bool(expr.args) and self._taints(mod, expr.args[0],
                                                        tainted)
            if name in ("jax.numpy.asarray", "jnp.asarray"):
                # asarray TRANSPORTS taint (zero-copy on CPU)
                return bool(expr.args) and self._taints(mod, expr.args[0],
                                                        tainted)
            if name in _TAINT_CALLS or name.endswith(_TAINT_SUFFIX):
                return True
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            return f"{expr.value.id}.{expr.attr}" in tainted
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._taints(mod, e, tainted) for e in expr.elts)
        return False

    def _check_put(self, mod: ModuleInfo, call: ast.Call,
                   tainted: Set[str]) -> Iterable[Finding]:
        if mod.call_name(call) not in _DEVICE_PUT:
            return
        explicit_placement = len(call.args) >= 2 or any(
            kw.arg in ("device", "sharding", "donate") for kw in call.keywords)
        if not explicit_placement or not call.args:
            return
        if self._taints(mod, call.args[0], tainted):
            yield self.finding(
                mod, call,
                "device_put of a deserialized/restored value onto an "
                "explicit placement without util/params.own_tree — on CPU "
                "the placed array can alias numpy-owned heap memory, and "
                "the first donating step after resume corrupts it (the "
                "PR-3 serde-resume segfault, sharding-aware variant); "
                "launder with own_tree(tree, shardings)/owned_leaf first")
