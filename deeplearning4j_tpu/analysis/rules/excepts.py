"""Rule: bare-except-swallow — silent failure in process-boundary code.

In worker / replica / supervisor processes an exception swallowed with
``except: pass`` doesn't crash anything visibly — the process keeps
running wedged, and the parent's only signal is a probe timeout minutes
later. The resilience layer's whole design (PR 3/8) is that failures
are OBSERVED: counted, logged, or re-raised. This rule flags, in
process-boundary modules (parallel/, serving/, data/pipeline.py,
train/resilience.py):

- bare ``except:`` anywhere (also catches SystemExit/KeyboardInterrupt,
  breaking clean preemption);
- ``except Exception/BaseException`` handlers whose body does NOTHING
  (only pass/continue/break): no re-raise, no logging, no metric, no
  state recorded. A handler that logs, counts, or assigns is fine —
  best-effort cleanup with a recorded decision gets a pragma.
"""
from __future__ import annotations

import ast
from typing import Iterable

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

_SCOPE_MARKERS = ("/parallel/", "/serving/", "/data/pipeline.py",
                  "/train/resilience.py", "/monitor/", "/clustering/")
_BROAD = {"Exception", "BaseException"}


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(m in p for m in _SCOPE_MARKERS)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Body does nothing observable: only pass/continue/break (a leading
    docstring-style constant allowed)."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue
        return False
    return True


class BareExceptSwallowRule(Rule):
    name = "bare-except-swallow"
    summary = ("bare `except:` / silent `except Exception: pass` in "
               "worker/replica/supervisor process code")
    historical = ("PR 8: a wedged replica's only failure signal was a "
                  "probe timeout — swallowed exceptions in process-"
                  "boundary code turn crashes into silent hangs")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not _in_scope(mod.path):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    mod, node,
                    "bare `except:` also catches SystemExit/"
                    "KeyboardInterrupt — clean preemption (PR 3) relies "
                    "on those propagating; catch Exception at most")
            elif _is_broad(node) and _swallows(node):
                yield self.finding(
                    mod, node,
                    "broad exception swallowed with no log/metric/"
                    "re-raise in process-boundary code — failures here "
                    "must be observed (count it, log it, or narrow the "
                    "type); suppress with a justification if this "
                    "cleanup is genuinely best-effort")
