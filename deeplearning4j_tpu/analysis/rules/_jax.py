"""Shared detection of compiled-code regions (used by the hot-path and
telemetry rules): which functions in a module run under `jax.jit`
tracing, and which are `lax` control-flow bodies.

Best-effort and module-local, like the rest of graftlint: a function is
"jitted" when it is (a) decorated with jit/pjit (directly or through
functools.partial), (b) passed by name to a jit call in the same module
(the repo's dominant idiom: ``def step(...): ...; return jax.jit(step,
donate_argnums=...)``), or (c) passed by name (or as an inline lambda)
to lax.scan / fori_loop / while_loop / cond / map / switch.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Union

from deeplearning4j_tpu.analysis.core import ModuleInfo

JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}
LAX_BODY_NAMES = {
    "jax.lax.scan", "lax.scan", "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.while_loop", "lax.while_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.map", "lax.map", "jax.lax.switch", "lax.switch",
    "jax.lax.associative_scan", "lax.associative_scan",
}

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def _is_jit_ref(mod: ModuleInfo, node: ast.AST) -> bool:
    """`jax.jit`, `partial(jax.jit, ...)`, or `jax.jit(...)` (a
    configured jit used as a decorator)."""
    if mod.dotted(node) in JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        name = mod.call_name(node)
        if name in JIT_NAMES:
            return True
        if name in PARTIAL_NAMES and node.args and \
                mod.dotted(node.args[0]) in JIT_NAMES:
            return True
    return False


def compiled_regions(mod: ModuleInfo) -> Dict[FuncNode, str]:
    """function/lambda node -> human reason it runs under tracing.
    Memoized on the ModuleInfo: three rules call this per file, and the
    two ast.walk passes are the expensive part of the run."""
    cached = getattr(mod, "_compiled_regions", None)
    if cached is not None:
        return cached
    regions = _compiled_regions_uncached(mod)
    mod._compiled_regions = regions
    return regions


def _compiled_regions_uncached(mod: ModuleInfo) -> Dict[FuncNode, str]:
    by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)

    regions: Dict[FuncNode, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_ref(mod, dec):
                    regions[node] = "jit-decorated function"
        elif isinstance(node, ast.Call):
            name = mod.call_name(node)
            if name in JIT_NAMES:
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, []):
                            regions[fn] = f"function passed to {name}()"
                    elif isinstance(arg, ast.Lambda):
                        regions[arg] = f"lambda passed to {name}()"
            elif name in LAX_BODY_NAMES:
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, []):
                            regions[fn] = f"{name} body"
                    elif isinstance(arg, ast.Lambda):
                        regions[arg] = f"{name} body"
    return regions


def walk_region(fn: FuncNode):
    """Walk a compiled region's body — nested defs/lambdas INCLUDED
    (they trace too), other regions' duplicates are the caller's concern
    (regions() maps distinct nodes)."""
    if isinstance(fn, ast.Lambda):
        yield from ast.walk(fn.body)
    else:
        for stmt in fn.body:
            yield from ast.walk(stmt)
