"""Rules: telemetry-zero-cost + metric-family-registration.

telemetry-zero-cost: the monitor layer's hard contract (monitor/trace.py
docstring) is zero cost while disabled. Two ways call sites break it:

- telemetry INSIDE a compiled region records at trace time only (or
  forces a retrace) — it can never observe runtime behavior;
- ``span(..., attr=expensive())`` evaluates the attr EAGERLY even while
  tracing is disabled — ``span("step", loss=float(loss))`` puts a
  device->host sync on the always-on path. Expensive attrs belong under
  ``if monitor.tracing_enabled():``.

metric-family-registration: every emitted ``*_total``/``*_seconds``
family must appear in docs/OBSERVABILITY.md's catalog — the catalog is
the operator's contract (dashboards, alerts), and an uncataloged family
is invisible in practice. The extraction half
(`extract_metric_families`) is shared with tools/telemetry_smoke.py so
the static catalog check and the live-scrape check read one source of
truth.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, Rule, iter_py_files, load_module,
)
from deeplearning4j_tpu.analysis.rules._jax import (
    compiled_regions, walk_region,
)

_METRIC_FNS = {"counter", "gauge", "histogram"}
_SPAN_FNS = {"span", "add_span", "instant"}
#: flight-recorder record calls (monitor/flight.py): same
#: zero-cost-when-disabled contract, same compiled-region ban — a
#: flight.note() traced into an XLA program records once at trace time.
#: Generic base names, so these additionally require "flight" in the
#: resolved dotted name (a random obj.note() must not match).
_FLIGHT_FNS = {"begin", "note", "finish", "trip", "record"}
#: calls allowed in span attrs without a tracing_enabled() guard: O(1),
#: never a device sync (str/repr of host objects included — error paths
#: stringify their exception)
_CHEAP_CALLS = {"len", "str", "repr", "type"}


def _monitor_call(mod: ModuleInfo, call: ast.Call, kinds) -> Optional[str]:
    """The kind name when `call` is a monitor-layer call of one of
    `kinds` (resolved through imports; `self.x` excluded)."""
    name = mod.call_name(call)
    if not name or name.startswith("self."):
        return None
    base = name.split(".")[-1]
    if base not in kinds:
        return None
    if base in _FLIGHT_FNS and base not in (_METRIC_FNS | _SPAN_FNS):
        return base if "flight" in name else None
    if "monitor" in name or "metrics" in name or "trace" in name:
        return base
    return None


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class TelemetryZeroCostRule(Rule):
    name = "telemetry-zero-cost"
    summary = ("span()/metric emission inside compiled regions, or "
               "expensive span attrs not behind tracing_enabled()")
    historical = ("PR 4: zero-cost-when-disabled is the monitor layer's "
                  "hard contract — an eager float(loss) in span attrs "
                  "reintroduces the per-step sync the contract exists "
                  "to prevent")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        regions = compiled_regions(mod)
        in_region = set()
        for fn, why in regions.items():
            for node in walk_region(fn):
                if isinstance(node, ast.Call):
                    kind = _monitor_call(
                        mod, node,
                        _METRIC_FNS | _SPAN_FNS | _FLIGHT_FNS)
                    if kind:
                        in_region.add(id(node))
                        yield self.finding(
                            mod, node,
                            f"{kind}() inside a compiled region ({why}) "
                            "— telemetry in traced code records once at "
                            "trace time and never again; emit from the "
                            "host loop around the compiled call")
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or id(node) in in_region:
                continue
            if _monitor_call(mod, node, _SPAN_FNS) is None:
                continue
            expensive = [kw for kw in node.keywords
                         if kw.arg is not None
                         and self._is_expensive(mod, kw.value)]
            if expensive and not self._guarded(mod, node):
                names = ", ".join(kw.arg for kw in expensive)
                yield self.finding(
                    mod, node,
                    f"span attr(s) {names} call functions and are "
                    "evaluated even while tracing is disabled — guard "
                    "the block with `if monitor.tracing_enabled():` or "
                    "pass precomputed values (zero-cost contract, "
                    "monitor/trace.py)")

    @staticmethod
    def _is_expensive(mod: ModuleInfo, expr: ast.AST) -> bool:
        from deeplearning4j_tpu.analysis.rules.hotpath import (
            _mentions_static_only,
        )
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            name = (mod.call_name(sub) or "").split(".")[-1]
            if name in _CHEAP_CALLS:
                continue
            # int(x.shape[0]) / float(len(xs)): static facts, no sync
            if name in ("int", "float", "bool") and sub.args and all(
                    _mentions_static_only(a) or isinstance(a, ast.Constant)
                    for a in sub.args):
                continue
            return True
        return False

    @staticmethod
    def _guarded(mod: ModuleInfo, node: ast.AST) -> bool:
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.If):
                for sub in ast.walk(anc.test):
                    if isinstance(sub, ast.Call) and (
                            mod.call_name(sub) or "").endswith(
                                "tracing_enabled"):
                        return True
        return False


# ------------------------------------------------------------ extraction
def metric_families_in(mod: ModuleInfo) -> List[Tuple[str, int]]:
    """(family-name, line) for every literal-named monitor metric
    emission in the module. Shared source of truth with
    tools/telemetry_smoke.py."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _monitor_call(mod, node, _METRIC_FNS) is None:
            continue
        if not node.args:
            continue
        name = _literal_str(node.args[0])
        if name:
            out.append((name, node.lineno))
    return out


def extract_metric_families(paths) -> Dict[str, List[Tuple[str, int]]]:
    """family-name -> [(path, line), ...] across a source tree."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for path in iter_py_files(paths):
        mod = load_module(path)
        if mod is None:
            continue
        for name, line in metric_families_in(mod):
            out.setdefault(name, []).append((path, line))
    return out


def _find_catalog(start: str) -> Optional[str]:
    cur = os.path.abspath(os.path.dirname(start))
    for _ in range(12):
        cand = os.path.join(cur, "docs", "OBSERVABILITY.md")
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            break
        cur = nxt
    return None


class MetricFamilyRegistrationRule(Rule):
    name = "metric-family-registration"
    summary = ("emitted *_total/*_seconds metric families must appear "
               "in the docs/OBSERVABILITY.md catalog")
    historical = ("PR 4/6: the catalog is the operator contract — an "
                  "uncataloged family exists on /metrics but in no "
                  "dashboard or alert")

    #: injectable for tests; default: walk up from the flagged file
    catalog_path: Optional[str] = None

    def __init__(self, catalog_path: Optional[str] = None):
        if catalog_path is not None:
            self.catalog_path = catalog_path
        self._cache: Dict[str, str] = {}

    def _catalog_text(self, for_file: str) -> Optional[str]:
        path = self.catalog_path or _find_catalog(for_file)
        if path is None:
            return None
        if path not in self._cache:
            try:
                with open(path, encoding="utf-8") as fh:
                    self._cache[path] = fh.read()
            except OSError:
                self._cache[path] = ""
        return self._cache[path]

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        families = [(n, ln) for n, ln in metric_families_in(mod)
                    if n.endswith(("_total", "_seconds"))]
        if not families:
            return
        catalog = self._catalog_text(mod.path)
        if catalog is None:
            return   # no docs tree in reach (fixture sandboxes)
        for name, line in families:
            if name not in catalog:
                yield Finding(
                    rule=self.name, path=mod.path, line=line,
                    message=f"metric family {name!r} is emitted but "
                    "missing from docs/OBSERVABILITY.md's catalog — "
                    "document it (operators alert on the catalog, not "
                    "the code)")
