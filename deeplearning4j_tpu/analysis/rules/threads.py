"""Rule: thread-lifecycle — the PR-11 silent-thread-death class.

Three checks over every discovered thread entry point
(``threading.Thread(target=...)`` plus spawn-helper indirections like
fleet's ``_threaded_spawn``):

1. **Unguarded target.** A worker thread's uncaught exception kills ONLY
   that thread: the process lives on, the component keeps reporting
   healthy, and the work silently never happens again. PR 11 shipped
   exactly this — the decode scheduler (the only thread that reclaims
   KV slots) died on an admission error while the servable still said
   "ready"; review added the fail-loud guard. Resolvable project
   targets must have a top-level ``try/except`` (directly, or at the
   top of their main loop). Opaque targets (``serve_forever`` on an
   stdlib object) can't be checked and are skipped.
2. **Non-daemon thread never joined.** A non-daemon worker with no
   ``join()`` in any ``stop``/``shutdown``/``close``/``drain``-family
   method blocks interpreter exit forever when someone forgets it —
   and a *daemonized* fix would trade that for silent mid-write kills.
   Threads stored on ``self`` are matched against the owning class's
   teardown methods.
3. **Unnamed thread.** PR 13's trace tracks and the deadlock sentinel's
   stack dumps key on thread names; an unnamed ``Thread-23`` makes both
   unreadable. Every spawn must pass ``name=`` (spawn helpers: a
   positional name argument).
"""
from __future__ import annotations

import ast
from typing import Iterable

from deeplearning4j_tpu.analysis.core import (
    Finding, Project, ProjectRule,
)

#: method-name fragments that count as a teardown surface for check 2
_TEARDOWN_HINTS = ("stop", "shutdown", "close", "drain", "join", "__exit__")


def _has_top_level_guard(fn_node: ast.AST) -> bool:
    """True when the function body has a try/except at its top level, or
    at the top level of a directly-nested With / main loop (the
    transport-reader idiom: ``while ...: try: ... except: ...``)."""
    def guarded(stmts, depth: int) -> bool:
        for stmt in stmts:
            if isinstance(stmt, ast.Try) and stmt.handlers:
                return True
            if depth > 0 and isinstance(
                    stmt, (ast.While, ast.For, ast.With, ast.If)):
                if guarded(stmt.body, depth - 1):
                    return True
        return False

    return guarded(getattr(fn_node, "body", []), 2)


class ThreadLifecycleRule(ProjectRule):
    name = "thread-lifecycle"
    summary = ("thread targets without a fail-loud top-level exception "
               "guard; non-daemon threads never joined in any teardown "
               "method; unnamed threads")
    historical = ("PR 11: the decode scheduler thread — the only place "
                  "KV slots are reclaimed — died silently on an "
                  "unguarded admission error while the servable kept "
                  "reporting ready; PR 13 named the fleet's threads so "
                  "traces and stack dumps are attributable")

    def check_project(self, project: Project) -> Iterable[Finding]:
        model = project.concurrency()
        for spawn in model.spawns:
            line = getattr(spawn.node, "lineno", 1)
            col = getattr(spawn.node, "col_offset", 0)

            def mk(msg: str) -> Finding:
                return Finding(rule=self.name, path=spawn.module.path,
                               line=line, col=col, message=msg)

            if not spawn.named:
                yield mk(
                    f"unnamed thread (target={spawn.target_text}) — "
                    "trace tracks and deadlock-sentinel stack dumps "
                    "key on thread names (the PR-13 policy); pass "
                    "name=")
            if spawn.target_qual is not None:
                ti = model.graph.functions.get(spawn.target_qual)
                if ti is not None and not _has_top_level_guard(ti.node):
                    short = spawn.target_qual.rsplit(".", 1)[-1]
                    yield mk(
                        f"thread target {short}() has no top-level "
                        "exception guard — an uncaught exception kills "
                        "only this thread while the process keeps "
                        "reporting healthy (the PR-11 decode-scheduler "
                        "death); wrap the body in try/except that "
                        "records the failure loudly")
            if spawn.daemon is not True and spawn.assigned_attr and \
                    not self._joined_somewhere(model, spawn):
                yield mk(
                    f"non-daemon thread self.{spawn.assigned_attr} is "
                    "never join()ed in any stop/shutdown/close method "
                    "— it blocks interpreter exit forever if teardown "
                    "forgets it; join it in the owner's teardown (or "
                    "daemonize AND guard it)")

    @staticmethod
    def _joined_somewhere(model, spawn) -> bool:
        """Is ``self.<attr>.join`` (or ``<local> = self.<attr> ...
        .join``) called in any teardown-named method of the owning
        class?"""
        cls = getattr(spawn.owner, "cls", None)
        attr = spawn.assigned_attr
        candidates = [
            fi for fi in model.graph.functions.values()
            if fi.cls == cls and any(h in fi.name.lower()
                                     for h in _TEARDOWN_HINTS)
        ] if cls else []
        for fi in candidates:
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "join":
                    base = node.func.value
                    if isinstance(base, ast.Attribute) and \
                            base.attr == attr:
                        return True
                    if isinstance(base, ast.Name):
                        # `t = self._thread; ...; t.join()` — accept a
                        # join on any local in a teardown method whose
                        # body also reads self.<attr> (cheap dataflow)
                        if _reads_self_attr(fi.node, attr):
                            return True
        return False


def _reads_self_attr(fn_node: ast.AST, attr: str) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute) and node.attr == attr and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return True
    return False
