"""Rules: host-sync-in-hot-path + recompile-hazard — compiled-step
hygiene (the defect family ROADMAP item 2's MFU work hunts dynamically;
these two catch the statically-visible cases at review time).

host-sync-in-hot-path: ``float()/int()/bool()/.item()/np.asarray()`` on
traced values inside a compiled region stalls the dispatch pipeline
(device->host sync per step — the exact tax PERF.md measured), and in
fit inner loops an *extra* sync beyond the one deliberate loss fetch
serializes host and device. Shape/dtype reads are static under tracing
and exempt.

recompile-hazard: Python ``if``/``while`` on runtime array VALUES inside
a jitted function either crashes at trace time (TracerBoolConversion) or
— via shape-dependent rebuilding — recompiles per distinct value.
Branching on shapes/dtypes/None-ness is static and fine; use lax.cond /
jnp.where for value branches.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable, Set

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule
from deeplearning4j_tpu.analysis.rules._jax import (
    compiled_regions, walk_region,
)

_SYNC_BUILTINS = {"float", "int", "bool"}
_NP_SYNC = {"numpy.asarray", "np.asarray", "numpy.array", "np.array"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
#: fit-loop functions: the product hot loops around the compiled step
_FIT_LOOP_RE = re.compile(r"^(fit|_fit\w*|do_fit|_run_scan_pipeline)$")


def _mentions_static_only(node: ast.AST) -> bool:
    """True when the expression reads only static facts: .shape/.ndim/
    .dtype/.size chains, len(), constants."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
    return False


def _sync_call_kind(mod: ModuleInfo, call: ast.Call):
    """None, or a description of the host-sync this call performs."""
    name = mod.call_name(call)
    if isinstance(call.func, ast.Name) and call.func.id in _SYNC_BUILTINS:
        if call.args and not _mentions_static_only(call.args[0]) \
                and not isinstance(call.args[0], ast.Constant):
            return f"{call.func.id}() forces a device->host transfer"
        return None
    if name in _NP_SYNC:
        return f"{name}() materializes the value on host"
    if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
            and not call.args:
        return ".item() forces a device->host transfer"
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr == "block_until_ready":
        return ".block_until_ready() stalls the dispatch pipeline"
    return None


class HostSyncInHotPathRule(Rule):
    name = "host-sync-in-hot-path"
    summary = ("float()/int()/bool()/.item()/np.asarray() on traced "
               "values inside jitted functions, lax bodies, or fit "
               "inner loops")
    historical = ("PERF.md round-5: the dispatch-tax investigation; every "
                  "accidental per-step sync serializes host and device — "
                  "the fit loops budget exactly ONE deliberate loss fetch")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        regions = compiled_regions(mod)
        seen: Set[int] = set()
        for fn, why in regions.items():
            for node in walk_region(fn):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                kind = _sync_call_kind(mod, node)
                if kind:
                    seen.add(id(node))
                    yield self.finding(
                        mod, node,
                        f"{kind} inside a compiled region ({why}) — "
                        "hoist it out of the traced code")
        yield from self._check_fit_loops(mod, regions, seen)

    def _check_fit_loops(self, mod: ModuleInfo, regions, seen: Set[int]
                         ) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node in regions or not _FIT_LOOP_RE.match(node.name):
                continue
            for loop in ast.walk(node):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for sub in ast.walk(loop):
                    if id(sub) in seen or not isinstance(sub, ast.Call):
                        continue
                    # fit loops: only the unambiguous sync vectors —
                    # host-side numpy parsing is legitimate ETL there,
                    # and bool()/int() overwhelmingly hit Python values
                    if isinstance(sub.func, ast.Name) \
                            and sub.func.id == "float" \
                            and sub.args \
                            and not isinstance(sub.args[0], ast.Constant) \
                            and not _mentions_static_only(sub.args[0]):
                        kind = (f"{sub.func.id}() is a device->host sync "
                                "point")
                    elif isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr == "item" and not sub.args:
                        kind = ".item() is a device->host sync point"
                    else:
                        continue
                    seen.add(id(sub))
                    yield self.finding(
                        mod, sub,
                        f"{kind} inside the {node.name}() inner loop — "
                        "the loop budgets ONE deliberate loss fetch; "
                        "anything else serializes host and device "
                        "(suppress with a justification if deliberate)")


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    summary = ("Python branching on runtime array values (not shapes) "
               "inside jitted functions")
    historical = ("PERF.md: recompiles inside the hot path wipe out the "
                  "compile-cache guarantees the serving bucket ladder "
                  "and scan pipeline are built on")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, why in compiled_regions(mod).items():
            if isinstance(fn, ast.Lambda):
                continue
            params = {a.arg for a in list(fn.args.args)
                      + list(fn.args.posonlyargs) + list(fn.args.kwonlyargs)
                      if a.arg not in ("self", "cls")}
            for node in walk_region(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                if not self._references_params(test, params):
                    continue
                if self._static_test(test):
                    continue
                yield self.finding(
                    mod, node,
                    f"Python branch on a traced value inside a compiled "
                    f"region ({why}) — trace-time crash or a recompile "
                    "per value; use lax.cond/jnp.where, or branch on "
                    ".shape/.ndim/.dtype (static under tracing)")

    @staticmethod
    def _references_params(test: ast.AST, params: Set[str]) -> bool:
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in ast.walk(test))

    @staticmethod
    def _static_test(test: ast.AST) -> bool:
        """Shape/dtype reads, None-ness, isinstance — static facts."""
        if _mentions_static_only(test):
            return True
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                return True
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in ("isinstance", "callable", "hasattr",
                                      "getattr", "len"):
                return True
        return False
