"""Rule: blocking-under-lock — the PR-8 supervisor-freeze class.

A blocking call lexically inside a ``with <lock>:`` body holds the lock
for the call's full duration: one hung replica launch froze probing of
the WHOLE fleet and deadlocked supervisor.stop (fixed twice in PR 8
review — relaunches, then probes, moved off the tick lock). The rule
flags calls that can block unboundedly — subprocess spawns,
socket/HTTP IO, sleeps, thread joins, launch-family calls — while a
lock-ish context is held.

Precision notes:

- lock-ish = a `with` context whose expression's last name segment
  contains ``lock``/``mutex`` (``self._tick_lock``, ``_swap_lock``,
  ``REGISTRY._lock`` ...). Condition variables are deliberately NOT
  lock-ish (``with cv: cv.wait()`` is the correct idiom).
- nested function definitions inside the body do not RUN under the
  lock — they are skipped (the PR-8 fix moved launches into exactly
  such spawn threads).
- ``.join``: a thread/process/queue join blocks; ``str.join`` doesn't.
  A join with no args, a numeric timeout, or a timeout kwarg is the
  blocking kind; ``sep.join(iterable)`` is exempt.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

_BLOCKING_ATTRS = {"connect", "accept", "recv", "recv_into", "sendall",
                   "getresponse", "urlopen"}
_LAUNCH_HINTS = ("launch", "relaunch")


def _lockish(mod: ModuleInfo, item: ast.withitem) -> Optional[str]:
    expr = item.context_expr
    if isinstance(expr, ast.Call):     # `with self._lock_for(x):` style
        expr = expr.func
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    if name is None:
        return None
    low = name.lower()
    if "lock" in low or "mutex" in low:
        return name
    return None


def _blocking_kind(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    name = mod.call_name(call) or ""
    base = name.split(".")[-1]
    if name == "time.sleep" or base == "sleep":
        return "sleep"
    if name.startswith("subprocess."):
        return name
    if name.startswith("requests.") or base == "urlopen":
        return "HTTP request"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _BLOCKING_ATTRS:
            return f"socket/HTTP .{attr}()"
        if attr == "join":
            if not call.args and not call.keywords:
                return "thread/process join"
            if any(kw.arg == "timeout" for kw in call.keywords):
                return "thread/process join"
            if len(call.args) == 1 and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, (int, float)):
                return "thread/process join"
            return None
        if attr in ("get", "put") and any(
                kw.arg == "timeout" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value in (0, 0.0))
                for kw in call.keywords):
            return f"queue .{attr}(timeout=...)"
    if any(h in base.lower() for h in _LAUNCH_HINTS):
        return f"{base}() (launch-family)"
    return None


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    summary = ("subprocess/socket/HTTP/sleep/join/launch-family calls "
               "lexically inside a `with <lock>` body")
    historical = ("PR 8: a hung SubprocessReplica relaunch under the "
                  "supervisor tick lock froze probing of the whole fleet "
                  "and deadlocked stop(); fixed twice in review")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_name = None
            for item in node.items:
                lock_name = _lockish(mod, item)
                if lock_name:
                    break
            if not lock_name:
                continue
            for stmt in node.body:
                yield from self._scan(mod, stmt, lock_name)

    def _scan(self, mod: ModuleInfo, node: ast.AST, lock_name: str
              ) -> Iterable[Finding]:
        # code inside nested defs does not run while the lock is held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        # a nested lock-ish `with` gets its own visit from check()'s
        # outer walk — recursing into it here would double-report every
        # blocking call once per enclosing lock
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                _lockish(mod, item) for item in node.items):
            return
        if isinstance(node, ast.Call):
            kind = _blocking_kind(mod, node)
            if kind:
                yield self.finding(
                    mod, node,
                    f"{kind} while holding {lock_name!r} — every other "
                    "thread contending on the lock stalls for the call's "
                    "full duration (the PR-8 fleet-freeze class); move "
                    "the blocking work outside the critical section")
        for child in ast.iter_child_nodes(node):
            yield from self._scan(mod, child, lock_name)
