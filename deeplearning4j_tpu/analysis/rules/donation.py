"""Rule: donated-aliasing — the serde-resume segfault class (PR 3).

``jnp.asarray`` on a numpy array can be ZERO-COPY on CPU backends: the
jax array aliases numpy-owned memory. DONATING that buffer into a jitted
step (``donate_argnums``) lets XLA free/reuse memory it does not own —
heap corruption that surfaces as garbage params or a segfault at a
random later point. The historical crash: checkpoint-restored
(deserialized, numpy-backed) params donated by the first train step
after resume. The fix is `util/params.own_tree` (copy into XLA-owned
buffers) at every fit entry.

Two checks, both from the AST:

1. **Module contract**: a module that creates donating programs
   (``jit(..., donate_argnums=...)`` / ``device_put(..., donate=...)``)
   must reference `own_tree`/`owned_leaf` somewhere — the laundering
   step has to live next to the donation, not in tribal memory.
2. **Lightweight dataflow** (the PR-3 shape): inside one function,
   values produced by numpy / deserialization (``np.*``, ``*.from_bytes``,
   ``np.load``, ``pickle.load(s)``) and *assigned* (incl. to
   ``self.<attr>``) are host-tainted; simple-name propagation follows
   ``x = y``; passing through `own_tree`/`owned_leaf`/
   ``jnp.array(..., copy=True)`` clears the taint. A call of a
   known-donating callable (a name bound to a donating `jit` in the
   same module) with a tainted argument in a donated position is
   flagged even when the module passes check 1.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from deeplearning4j_tpu.analysis.core import Finding, ModuleInfo, Rule

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_DEVICE_PUT = {"jax.device_put", "device_put"}
_OWNING = {"own_tree", "owned_leaf"}
_TAINT_CALLS_SUFFIX = (".from_bytes",)
_TAINT_CALLS = {"numpy.load", "np.load", "pickle.load", "pickle.loads"}


def _is_donating_jit(mod: ModuleInfo, call: ast.Call) -> bool:
    name = mod.call_name(call)
    if name not in _JIT_NAMES:
        return False
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call.keywords)


def _is_donating_device_put(mod: ModuleInfo, call: ast.Call) -> bool:
    name = mod.call_name(call)
    if name not in _DEVICE_PUT:
        return False
    return any(kw.arg in ("donate", "donate_argnums") for kw in call.keywords)


def _donated_argnums(call: ast.Call) -> Optional[Set[int]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        out.add(el.value)
                return out
    return None   # donate_argnames / non-literal: treat every arg as donated


def _target_name(t: ast.AST) -> Optional[str]:
    """`x` or `self.params` as a taint key; None for complex targets."""
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
        return f"{t.value.id}.{t.attr}"
    return None


class DonatedAliasingRule(Rule):
    name = "donated-aliasing"
    summary = ("donated buffers must be XLA-owned: numpy-backed or "
               "deserialized leaves reach donate_argnums without "
               "util/params.own_tree")
    historical = ("PR 3: checkpoint-restored numpy-aliased params were "
                  "donated by the first post-resume train step — heap "
                  "corruption, the serde-resume segfault")

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        donation_sites: List[ast.Call] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and (
                    _is_donating_jit(mod, node)
                    or _is_donating_device_put(mod, node)):
                donation_sites.append(node)
        if not donation_sites:
            return
        # AST-based reference check: a docstring MENTIONING own_tree is
        # not laundering — only a real Name/Attribute reference counts
        launders = any(
            (isinstance(n, ast.Name) and n.id in _OWNING)
            or (isinstance(n, ast.Attribute) and n.attr in _OWNING)
            for n in ast.walk(mod.tree))
        if not launders:
            for site in donation_sites:
                yield self.finding(
                    mod, site,
                    "donating program in a module that never launders "
                    "host buffers through util/params.own_tree/owned_leaf "
                    "— restored/numpy-backed leaves donated here corrupt "
                    "the heap (the PR-3 serde-resume segfault)")
        # lightweight dataflow, per function scope (and module top level)
        scopes = [mod.tree] + [n for n in ast.walk(mod.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        donating: Dict[str, Optional[Set[int]]] = {}
        for scope in scopes:
            for stmt in ast.walk(scope):
                if isinstance(stmt, ast.Assign) and isinstance(
                        stmt.value, ast.Call) and _is_donating_jit(
                            mod, stmt.value):
                    for t in stmt.targets:
                        tn = _target_name(t)
                        if tn:
                            donating[tn] = _donated_argnums(stmt.value)
        for scope in scopes:
            yield from self._scope_taint(mod, scope, donating)

    def _scope_taint(self, mod: ModuleInfo, scope: ast.AST,
                     donating: Dict[str, Optional[Set[int]]]
                     ) -> Iterable[Finding]:
        tainted: Set[str] = set()
        body = scope.body if hasattr(scope, "body") else []
        for stmt in body:
            yield from self._walk_stmt(mod, stmt, tainted, donating)

    def _walk_stmt(self, mod: ModuleInfo, stmt: ast.AST, tainted: Set[str],
                   donating: Dict[str, Optional[Set[int]]]
                   ) -> Iterable[Finding]:
        # nested defs are their own scope — visited via `scopes`
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            taints = self._expr_taints(mod, stmt.value, tainted)
            for t in stmt.targets:
                tn = _target_name(t)
                if tn is not None:
                    (tainted.add if taints else tainted.discard)(tn)
        # check calls in this statement's own expressions (not in nested
        # statements — recursion below visits those exactly once)
        for expr in self._own_exprs(stmt):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    yield from self._check_donating_call(
                        mod, node, tainted, donating)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                yield from self._walk_stmt(mod, child, tainted, donating)

    @staticmethod
    def _own_exprs(stmt: ast.AST) -> Iterable[ast.expr]:
        """The statement's direct expression children (a compound
        statement's nested statement bodies are excluded)."""
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                yield child
            elif isinstance(child, (ast.withitem, ast.keyword)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        yield sub

    def _expr_taints(self, mod: ModuleInfo, expr: ast.AST,
                     tainted: Set[str]) -> bool:
        """Does `expr` produce a host-owned (numpy/deserialized) value
        that has NOT been laundered?"""
        if isinstance(expr, ast.Call):
            name = mod.call_name(expr) or ""
            base = name.split(".")[-1]
            if base in _OWNING:
                return False
            if name in ("jax.numpy.array", "jnp.array",
                        "jax.numpy.asarray", "jnp.asarray"):
                copy_kw = next((kw.value.value for kw in expr.keywords
                                if kw.arg == "copy"
                                and isinstance(kw.value, ast.Constant)),
                               None)
                # jnp.array defaults to copy=True (XLA-owned) — clears
                # taint unless copy=False; jnp.asarray on numpy is
                # ZERO-COPY on CPU (the PR-3 alias) — it TRANSPORTS
                # taint unless forced to copy
                copies = (copy_kw is True
                          or (base == "array" and copy_kw is None))
                if copies:
                    return False
                return bool(expr.args) and self._expr_taints(
                    mod, expr.args[0], tainted)
            if (name.startswith("numpy.") or name.startswith("np.")
                    or name in _TAINT_CALLS
                    or name.endswith(_TAINT_CALLS_SUFFIX)):
                return True
            # a call we can't see through clears nothing but produces a
            # fresh value: conservatively untainted
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            return f"{expr.value.id}.{expr.attr}" in tainted
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._expr_taints(mod, e, tainted) for e in expr.elts)
        return False

    def _check_donating_call(self, mod: ModuleInfo, call: ast.Call,
                             tainted: Set[str],
                             donating: Dict[str, Optional[Set[int]]]
                             ) -> Iterable[Finding]:
        fname = _target_name(call.func) if isinstance(
            call.func, (ast.Name, ast.Attribute)) else None
        if fname is None or fname not in donating:
            return
        argnums = donating[fname]
        for i, arg in enumerate(call.args):
            if argnums is not None and i not in argnums:
                continue
            if self._expr_taints(mod, arg, tainted):
                yield self.finding(
                    mod, call,
                    f"argument {i} of donating call {fname!r} is "
                    "numpy-backed/deserialized and was never passed "
                    "through own_tree — XLA will free memory it does "
                    "not own (the PR-3 segfault shape)")
