"""Concurrency model: lock regions, may-block facts, thread entry points.

The shared fact base for the four interprocedural concurrency rules
(rules/lockorder.py, rules/threads.py, rules/pairing.py) and the
``--lock-graph`` CLI export. Built once per graftlint run from the
parsed modules + the package call graph (callgraph.py).

Per function it records:

- **lock acquisition regions** — every lock-ish ``with`` (the PR-9
  blocking-under-lock notion: last name segment contains ``lock`` /
  ``mutex``; condition variables deliberately excluded) with a
  cross-module *lock identity* (below);
- **may-block facts** — direct blocking operations (subprocess, socket/
  HTTP IO, sleeps, thread joins, launch-family calls — the PR-9
  ``_blocking_kind`` table), excluding code inside nested defs, which
  runs on its own activation;
- **thread entry points** — ``threading.Thread(target=...)`` sites plus
  ``spawn``-family indirections (``self._spawn(lambda: f(), name)``),
  with daemon/name/join bookkeeping for the thread-lifecycle rule.

Lock identity
-------------
A lock is named by *where it lives*, so the acquisition-order graph can
join acquisitions from different modules:

- ``with self._lock`` in class ``C`` of module ``m`` -> ``m.C._lock``
- module-global ``with _lock`` in ``m``              -> ``m._lock``
- a local ``lock = threading.Lock()``                -> ``m.f.<local>lock``
  (function-scoped: never shared, never merges across functions)
- an import-resolved dotted chain (``REGISTRY._lock``) keeps the
  resolved dotted text.

The acquisition-order graph has an edge ``A -> B`` when B is acquired
while A is held: lexically nested ``with``s, or a call chain of at most
`depth` edges from inside A's region reaching a function that acquires
B. Cycles in that graph are lock-order inversions (two threads taking
the same pair in opposite orders can deadlock) — the runtime witness
(util/locks.DiagnosedLock) records the same edges from live executions
so tests can cross-check the model.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.callgraph import (
    CallGraph, FunctionInfo, module_dotted,
)
from deeplearning4j_tpu.analysis.core import ModuleInfo
from deeplearning4j_tpu.analysis.rules.locks import _blocking_kind, _lockish

#: default interprocedural horizon: how many call edges a rule follows
#: out of a lock region / toward another acquisition
DEFAULT_DEPTH = 4


@dataclasses.dataclass
class LockRegion:
    lock_id: str                 # cross-module lock identity
    lock_name: str               # the lexical name (`_tick_lock`)
    node: ast.AST                # the `with` statement


@dataclasses.dataclass
class BlockFact:
    node: ast.AST
    kind: str                    # human description from _blocking_kind


@dataclasses.dataclass
class ThreadSpawn:
    node: ast.Call               # the Thread(...)/spawn(...) call
    module: ModuleInfo
    owner: FunctionInfo          # function containing the spawn
    target_qual: Optional[str]   # resolved entry point (None = opaque)
    target_text: str             # source text of the target expression
    daemon: Optional[bool]       # constant daemon= value, None if absent/dynamic
    named: bool                  # has a name= kwarg
    assigned_attr: Optional[str]  # "self.<attr>" the Thread is stored to


class FunctionConcurrency:
    __slots__ = ("info", "regions", "blocks", "acquired_ids")

    def __init__(self, info: FunctionInfo):
        self.info = info
        self.regions: List[LockRegion] = []
        self.blocks: List[BlockFact] = []
        self.acquired_ids: Set[str] = set()


def _unwrap_with_expr(item: ast.withitem) -> ast.AST:
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
    return expr


def lock_identity(mod: ModuleInfo, fi: Optional[FunctionInfo],
                  expr: ast.AST) -> str:
    """Cross-module identity for a lock expression (module docstring)."""
    base = module_dotted(mod.path)
    dotted = mod.dotted(expr)
    if dotted is None:
        return f"{base}.<expr>"
    if dotted.startswith("self.") or dotted.startswith("cls."):
        attr = dotted.split(".", 1)[1]
        if fi is not None and fi.cls:
            return f"{fi.cls}.{attr}"
        return f"{base}.{attr}"
    if "." not in dotted:
        # module-global vs function-local: a name assigned at module
        # level is shared state; anything else is function-scoped
        if fi is not None and not _is_module_global(mod, dotted):
            return f"{fi.qual}.<local>{dotted}"
        return f"{base}.{dotted}"
    return dotted


def _is_module_global(mod: ModuleInfo, name: str) -> bool:
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(stmt, ast.AnnAssign):
            if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                return True
    return False


#: call names (dotted suffixes) that construct a lock object — the graph
#: counts every one of these as a node even before any edge touches it
_LOCK_CTORS = ("threading.Lock", "threading.RLock",
               "locks.DiagnosedLock", "DiagnosedLock",
               "multiprocessing.Lock", "multiprocessing.RLock")

#: cheap prefilter before the (dotted-resolution) _blocking_kind test:
#: every blocking shape ends in one of these attribute/name segments, or
#: hangs off a subprocess/requests import — checked via dict lookups so
#: the model doesn't pay a dotted-chain walk for every call in the tree
_MAYBE_BLOCKING_TAILS = frozenset(
    {"connect", "accept", "recv", "recv_into", "sendall", "getresponse",
     "urlopen", "sleep", "join", "get", "put"})


def _maybe_blocking(mod: ModuleInfo, call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _MAYBE_BLOCKING_TAILS or "launch" in func.attr.lower():
            return True
        base = func.value
        while isinstance(base, ast.Attribute):
            base = base.value
        if isinstance(base, ast.Name):
            origin = mod.imports.get(base.id, base.id)
            return origin.split(".")[0] in ("subprocess", "requests")
        return False
    if isinstance(func, ast.Name):
        origin = mod.imports.get(func.id, func.id)
        tail = origin.split(".")[-1]
        return (tail in _MAYBE_BLOCKING_TAILS
                or "launch" in func.id.lower()
                or origin.split(".")[0] in ("subprocess", "requests"))
    return False


@dataclasses.dataclass
class OrderEdge:
    src: str                      # held lock id
    dst: str                      # acquired-while-held lock id
    module: ModuleInfo            # where the evidence starts
    node: ast.AST                 # the inner acquisition or the call site
    via: Tuple[str, ...]          # call chain quals ([] = lexical nesting)


class ConcurrencyModel:
    """All concurrency facts for one analyzed tree."""

    def __init__(self, modules: Sequence[ModuleInfo],
                 graph: Optional[CallGraph] = None,
                 depth: int = DEFAULT_DEPTH):
        self.modules = list(modules)
        self.graph = graph if graph is not None else CallGraph(self.modules)
        self.depth = int(depth)
        self.functions: Dict[str, FunctionConcurrency] = {}
        #: every lock the tree declares or acquires (graph nodes)
        self.locks: Dict[str, Tuple[str, int]] = {}     # id -> (path, line)
        self.spawns: List[ThreadSpawn] = []
        self._by_node: Dict[int, FunctionInfo] = {
            id(fi.node): fi for fi in self.graph.functions.values()}
        self._chain_cache: Dict[str, Dict[str, List[str]]] = {}
        #: a->b edges from `with lock_a, lock_b:` co-items (semantically
        #: identical to nesting: items acquire left to right)
        self._co_item_edges: List[OrderEdge] = []
        for fi in self.graph.functions.values():
            self.functions[fi.qual] = self._analyze_function(fi)
        for mod in self.modules:
            self._collect_module_facts(mod)
        self.order_edges: List[OrderEdge] = self._build_order_edges()

    # ------------------------------------------------------- per-function
    def _analyze_function(self, fi: FunctionInfo) -> FunctionConcurrency:
        fc = FunctionConcurrency(fi)
        for node in self.graph._own_nodes(fi):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held_here: List[str] = []
                for item in node.items:
                    lock_name = _lockish(fi.module, item)
                    if lock_name:
                        lid = lock_identity(fi.module, fi,
                                            _unwrap_with_expr(item))
                        fc.regions.append(LockRegion(lid, lock_name, node))
                        fc.acquired_ids.add(lid)
                        self._note_lock(lid, fi.module, node)
                        # `with a, b:` acquires left to right — exactly
                        # nested semantics, so earlier co-items order
                        # before later ones
                        for prior in held_here:
                            if prior != lid:
                                self._co_item_edges.append(OrderEdge(
                                    prior, lid, fi.module, node, ()))
                        held_here.append(lid)
            elif isinstance(node, ast.Call) and _maybe_blocking(
                    fi.module, node):
                kind = _blocking_kind(fi.module, node)
                if kind:
                    fc.blocks.append(BlockFact(node, kind))
        return fc

    def _note_lock(self, lid: str, mod: ModuleInfo, node: ast.AST):
        self.locks.setdefault(
            lid, (mod.path, getattr(node, "lineno", 1)))

    def _collect_module_facts(self, mod: ModuleInfo):
        """One walk per module for both remaining fact families:
        declared locks (graph nodes even when never seen acquired — the
        --lock-graph artifact must name the fleet's full lock
        population, not just the contended ones) and thread spawns."""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                name = mod.call_name(node.value) or ""
                if any(name == c or name.endswith("." + c)
                       for c in _LOCK_CTORS):
                    for t in node.targets:
                        fn = mod.enclosing_function(node)
                        fi = self._owning_info(mod, fn)
                        self._note_lock(lock_identity(mod, fi, t),
                                        mod, node)
            if isinstance(node, ast.Call):
                self._maybe_spawn(mod, node)

    def _owning_info(self, mod: ModuleInfo,
                     fn_node: Optional[ast.AST]) -> Optional[FunctionInfo]:
        if fn_node is None:
            return None
        return self._by_node.get(id(fn_node))

    # ------------------------------------------------------ thread spawns
    def _maybe_spawn(self, mod: ModuleInfo, node: ast.Call):
        name = mod.call_name(node) or ""
        short = name.split(".")[-1]
        is_thread = name.endswith("threading.Thread") or short == "Thread"
        # spawn-helper indirection (fleet's `self._spawn(fn, name)` /
        # `_threaded_spawn`): exact names only — a fuzzy "contains
        # spawn" match would swallow unrelated helpers
        is_spawn = (not is_thread
                    and short.lower() in ("spawn", "_spawn", "spawn_fn",
                                          "_threaded_spawn",
                                          "threaded_spawn",
                                          "spawn_thread")
                    and (node.args or any(k.arg == "target"
                                          for k in node.keywords)))
        if not (is_thread or is_spawn):
            return
        fn_node = mod.enclosing_function(node)
        fi = self._owning_info(mod, fn_node)
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
                break
        if target is None and is_spawn and node.args:
            target = node.args[0]
        if target is None:
            return                            # Thread subclass/opaque use
        tq = self._resolve_target(mod, fi, target)
        daemon: Optional[bool] = None
        named = False
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg == "name":
                named = True
        if is_spawn and not named:
            # spawn helpers take the name positionally (fleet's
            # `_threaded_spawn(fn, name)`): 2+ args = named
            named = len(node.args) >= 2
        owner = fi if fi is not None else _ModuleLevel(mod)
        self.spawns.append(ThreadSpawn(
            node=node, module=mod, owner=owner, target_qual=tq,
            target_text=_expr_text(mod, target),
            daemon=daemon, named=named,
            assigned_attr=self._assigned_attr(mod, node)))

    def _resolve_target(self, mod: ModuleInfo, fi: Optional[FunctionInfo],
                        target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Lambda):
            # `lambda: self._relaunch(r)` — resolve the single call body
            body = target.body
            if isinstance(body, ast.Call):
                target = body.func
            else:
                return None
        if fi is None:
            # module-level spawn: resolve against a synthetic module fn
            dotted = mod.dotted(target)
            if dotted and dotted in self.graph.functions:
                return dotted
            if dotted and "." not in dotted:
                cand = f"{module_dotted(mod.path)}.{dotted}"
                if cand in self.graph.functions:
                    return cand
            return None
        return self.graph.resolve(fi, target)

    @staticmethod
    def _assigned_attr(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
        parent = mod.parent(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                return t.attr
        return None

    # ------------------------------------------------- acquisition ordering
    def _build_order_edges(self) -> List[OrderEdge]:
        edges: List[OrderEdge] = []
        seen: Set[Tuple[str, str, int]] = set()
        for e in self._co_item_edges:
            key = (e.src, e.dst, getattr(e.node, "lineno", 0))
            if key not in seen:
                seen.add(key)
                edges.append(e)
        for fc in self.functions.values():
            for region in fc.regions:
                self._edges_from_region(fc, region, edges, seen)
        return edges

    def _edges_from_region(self, fc: FunctionConcurrency, region: LockRegion,
                           edges: List[OrderEdge],
                           seen: Set[Tuple[str, str, int]]):
        mod = fc.info.module
        held = region.lock_id

        def note(dst: str, node: ast.AST, via: Tuple[str, ...]):
            if dst == held:
                return               # re-entrant self-acquire: not an order
            key = (held, dst, getattr(node, "lineno", 0))
            if key in seen:
                return
            seen.add(key)
            edges.append(OrderEdge(held, dst, mod, node, via))

        # lexical scan of the region body (nested defs skipped: they run
        # on their own activation, usually another thread)
        for stmt in getattr(region.node, "body", []):
            for node in _region_walk(stmt):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if _lockish(mod, item):
                            note(lock_identity(mod, fc.info,
                                               _unwrap_with_expr(item)),
                                 node, ())
                elif isinstance(node, ast.Call):
                    tq = self.graph.resolve(fc.info, node.func)
                    if tq is None:
                        continue
                    if tq not in self._chain_cache:
                        self._chain_cache[tq] = self.graph.reach_chains(
                            tq, self.depth - 1)
                    for reached, chain in self._chain_cache[tq].items():
                        rfc = self.functions.get(reached)
                        if rfc is None:
                            continue
                        for lid in sorted(rfc.acquired_ids):
                            note(lid, node, tuple(chain))

    # ------------------------------------------------------------- queries
    def cycles(self) -> List[List[str]]:
        """Strongly-connected components of the acquisition-order graph
        with more than one lock — each is a potential deadlock (two
        threads can take the pair in opposite orders)."""
        return find_cycles((e.src, e.dst) for e in self.order_edges)

    # ----------------------------------------------------------- artifact
    def lock_graph_doc(self) -> dict:
        """The --lock-graph JSON artifact (docs/STATIC_ANALYSIS.md)."""
        from deeplearning4j_tpu.analysis.core import _portable
        return {
            "version": 1,
            "locks": {
                lid: {"declared_at": f"{_portable(p)}:{line}"}
                for lid, (p, line) in sorted(self.locks.items())},
            "edges": [
                {"from": e.src, "to": e.dst,
                 "site": f"{_portable(e.module.path)}:"
                         f"{getattr(e.node, 'lineno', 0)}",
                 "via": list(e.via)}
                for e in sorted(self.order_edges,
                                key=lambda e: (e.src, e.dst))],
            "cycles": self.cycles(),
        }


def find_cycles(edge_pairs) -> List[List[str]]:
    """SCCs with more than one node over (src, dst) pairs — shared by
    the static rule and the runtime-witness cross-check (which runs it
    over static ∪ observed edges: the combined graph must stay
    acyclic)."""
    adj: Dict[str, Set[str]] = {}
    for src, dst in edge_pairs:
        adj.setdefault(src, set()).add(dst)
        adj.setdefault(dst, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan (the lock graph is small, but recursion
        # limits are not a failure mode a linter should have)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return out


class _ModuleLevel:
    """Placeholder owner for spawns outside any function."""

    cls = None

    def __init__(self, mod: ModuleInfo):
        self.module = mod
        self.qual = module_dotted(mod.path) + ".<module>"
        self.node = mod.tree
        self.name = "<module>"


def _region_walk(stmt: ast.AST) -> Iterable[ast.AST]:
    """Yield `stmt` and descendants, skipping nested def/class bodies."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _expr_text(mod: ModuleInfo, expr: ast.AST) -> str:
    try:
        return ast.unparse(expr)
    except Exception:                         # pragma: no cover
        return "<expr>"
