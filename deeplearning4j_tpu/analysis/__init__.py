"""graftlint — project-native static analysis for the bug classes this
repo actually shipped.

Six PRs of review rounds kept finding the same defect families: donated
numpy-aliased buffers (the PR-3 serde-resume segfault), hand-rolled env
kill-switch truthiness (re-fixed in PRs 5/7/8), blocking calls held
under supervisor/router locks (fixed twice in PR 8), host syncs and
recompile hazards inside the compiled step (the PERF.md tax). Every one
is visible in the AST — this package turns that review knowledge into a
machine-enforced invariant.

Entry points:

- CLI: ``python tools/graftlint.py deeplearning4j_tpu tools bench.py``
  (human, ``--json``, ``--baseline`` burn-down; exit 2 on unsuppressed
  findings) — wired into tier-1 via tests/test_lint.py.
- Library: `run(paths)` -> RunResult; `ALL_RULES`;
  `extract_metric_families` (shared with tools/telemetry_smoke.py).
- Suppression: ``# graftlint: disable=<rule> -- <justification>`` —
  the justification is mandatory and checked.

Rule catalog + how to add a rule: docs/STATIC_ANALYSIS.md.
"""
from deeplearning4j_tpu.analysis.core import (
    Finding, ModuleInfo, PRAGMA_RULE, Project, ProjectRule, Rule,
    RunResult, apply_baseline, iter_py_files, load_module, run as _run,
    write_baseline,
)
from deeplearning4j_tpu.analysis.rules import ALL_RULES
from deeplearning4j_tpu.analysis.rules.telemetry import (
    extract_metric_families, metric_families_in,
)


def run(paths, rules=None, select=None, module_findings=None) -> RunResult:
    """Run the full registered suite (or `rules`) over `paths`.
    `module_findings` feeds the CLI's multiprocess per-module pass
    (core.run docstring)."""
    return _run(paths, ALL_RULES if rules is None else rules,
                select=select, module_findings=module_findings)


__all__ = [
    "ALL_RULES", "Finding", "ModuleInfo", "PRAGMA_RULE", "Project",
    "ProjectRule", "Rule", "RunResult", "apply_baseline",
    "extract_metric_families", "iter_py_files", "load_module",
    "metric_families_in", "run", "write_baseline",
]
