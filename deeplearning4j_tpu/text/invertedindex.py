"""In-memory inverted index.

Parity: DL4J `text/invertedindex/InvertedIndex` + its in-memory
implementation — term -> postings used by the text vectorizers for document
frequencies and by retrieval-style lookups. Host-side structure, plain
Python by design.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Sequence, Set


class InMemoryInvertedIndex:
    """term -> sorted list of (doc_id, positions); also tracks per-document
    token lists so vectorizers can re-iterate the corpus."""

    def __init__(self):
        self._postings: Dict[str, Dict[int, List[int]]] = defaultdict(dict)
        self._docs: Dict[int, List[str]] = {}

    # -------------------------------------------------------------- build
    def add_doc(self, doc_id: int, tokens: Sequence[str]):
        if doc_id in self._docs:
            raise ValueError(f"doc {doc_id} already indexed")
        self._docs[doc_id] = list(tokens)
        for pos, tok in enumerate(tokens):
            self._postings[tok].setdefault(doc_id, []).append(pos)

    # -------------------------------------------------------------- stats
    def num_documents(self) -> int:
        return len(self._docs)

    def document(self, doc_id: int) -> List[str]:
        return self._docs[doc_id]

    def documents(self) -> Iterable[int]:
        return self._docs.keys()

    def doc_appeared_in(self, word: str) -> int:
        """Document frequency (DL4J VocabCache.docAppearedIn)."""
        return len(self._postings.get(word, ()))

    def term_frequency(self, word: str, doc_id: int) -> int:
        return len(self._postings.get(word, {}).get(doc_id, ()))

    def total_term_frequency(self, word: str) -> int:
        return sum(len(p) for p in self._postings.get(word, {}).values())

    def vocabulary(self) -> List[str]:
        return list(self._postings.keys())

    # ------------------------------------------------------------- search
    def docs_containing(self, word: str) -> Set[int]:
        return set(self._postings.get(word, ()))

    def search(self, *words: str) -> List[int]:
        """Conjunctive search: sorted doc ids containing ALL words."""
        if not words:
            return []
        acc = self.docs_containing(words[0])
        for w in words[1:]:
            acc &= self.docs_containing(w)
        return sorted(acc)
