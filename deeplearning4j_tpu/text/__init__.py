"""Text pipeline (DL4J deeplearning4j-nlp text/ parity).

Reference: `deeplearning4j-nlp-parent/deeplearning4j-nlp/.../text/`
{tokenization, sentenceiterator, documentiterator, stopwords}. Host-side
string processing stays host-side (SURVEY.md §7 hard parts: HogWild-class
algorithms don't belong on TPU); devices only see tokenized id batches.
"""
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, NGramTokenizerFactory, RegexTokenizerFactory,
    CommonPreprocessor, LowCasePreprocessor,
)
from deeplearning4j_tpu.text.sentenceiterator import (
    BasicLineIterator, CollectionSentenceIterator, FileSentenceIterator,
)
from deeplearning4j_tpu.text.stopwords import STOP_WORDS
from deeplearning4j_tpu.text.documentiterator import (
    BasicLabelAwareIterator, FileLabelAwareIterator,
    FilenamesLabelAwareIterator, LabelAwareIterator, LabelledDocument,
    LabelsSource, SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.text.invertedindex import InMemoryInvertedIndex
from deeplearning4j_tpu.text.cjk import (
    ChineseTokenizerFactory, JapaneseTokenizerFactory,
    KoreanTokenizerFactory,
)
from deeplearning4j_tpu.text.vectorizers import (
    BagOfWordsVectorizer, BaseTextVectorizer, TfidfVectorizer,
)

__all__ = [
    "DefaultTokenizerFactory", "NGramTokenizerFactory",
    "RegexTokenizerFactory", "CommonPreprocessor", "LowCasePreprocessor",
    "BasicLineIterator", "CollectionSentenceIterator",
    "FileSentenceIterator", "STOP_WORDS",
    "LabelledDocument", "LabelsSource", "LabelAwareIterator",
    "SimpleLabelAwareIterator", "BasicLabelAwareIterator",
    "FileLabelAwareIterator", "FilenamesLabelAwareIterator",
    "InMemoryInvertedIndex",
    "ChineseTokenizerFactory", "JapaneseTokenizerFactory",
    "KoreanTokenizerFactory",
    "BaseTextVectorizer", "BagOfWordsVectorizer", "TfidfVectorizer",
]
