"""Native (C++/OpenMP) batch tokenizer — the host-side fast path for the
text pipeline.

The reference tokenizes on the JVM (DefaultTokenizerFactory.java +
CommonPreprocessor.java) and re-tokenizes the corpus every epoch of
Word2Vec / every TF-IDF fit pass; `native/src/tokenizer.cpp` is the C++
analog of that hot path, parallel over documents.

Correctness contract: byte-identical to
`DefaultTokenizerFactory(CommonPreprocessor())` for ASCII text (the
native lowercasing is byte-level). `NativeCorpusEncoder` refuses
non-ASCII input so callers can fall back to the general Python path —
`encode_or_none`/`count_or_none` return None in that case and when no
C++ toolchain is available.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import native


def _available() -> bool:
    return native.available()


class NativeCorpusEncoder:
    """Batch tokenize + vocab-encode a corpus of documents in C++."""

    def __init__(self, common_preprocess: bool = True):
        self.common = common_preprocess

    @staticmethod
    def available() -> bool:
        return _available()

    # -- vocab building ---------------------------------------------------
    def count_or_none(self, docs: List[str]) -> Optional[Dict[str, int]]:
        """Token counts over the corpus (the vocab-building pass), or None
        when the native path can't be used (no toolchain / non-ASCII)."""
        if not _available():
            return None
        text = "\n".join(docs)
        if not text.isascii():
            return None
        lib = native.get_lib()
        raw = text.encode()
        h = lib.dl4j_count_tokens(raw, len(raw), 1 if self.common else 0)
        if not h:
            return None
        try:
            n = lib.dl4j_counts_size(h)
            blob_len = lib.dl4j_counts_blob_len(h)
            blob = ctypes.create_string_buffer(max(blob_len, 1))
            offsets = np.zeros(n + 1, np.int64)
            counts = np.zeros(max(n, 1), np.int64)
            lib.dl4j_counts_export(
                h, blob,
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            raw_blob = blob.raw[:blob_len].decode()
            return {raw_blob[offsets[i]:offsets[i + 1]]: int(counts[i])
                    for i in range(n)}
        finally:
            lib.dl4j_counts_free(h)

    # -- encoding ---------------------------------------------------------
    def encode_or_none(self, docs: List[str], word2id: Dict[str, int],
                       keep_oov: bool = False
                       ) -> Optional[List[np.ndarray]]:
        """Per-document int32 id arrays (OOV dropped, or -1 when
        keep_oov), or None when the native path can't be used."""
        if not _available():
            return None
        if not docs:
            return []
        if any("\n" in d for d in docs):    # '\n' is the doc separator
            return None
        text = "\n".join(docs)
        if not text.isascii():
            return None
        lib = native.get_lib()

        words = list(word2id.keys())
        if any(not w.isascii() for w in words):
            return None
        ids = np.asarray([word2id[w] for w in words], np.int32)
        # vocab ids must map back: C++ assigns position index, so order
        # the blob by position and translate after
        blob = "".join(words).encode()
        offsets = np.zeros(len(words) + 1, np.int64)
        pos = 0
        for i, w in enumerate(words):
            pos += len(w.encode())
            offsets[i + 1] = pos
        vh = lib.dl4j_vocab_create(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(words))
        if not vh:
            return None
        try:
            raw = text.encode()
            # worst case is single-char tokens with single-char gaps
            max_out = max((len(raw) + 1) // 2, 1)
            n_docs = len(docs)
            int64_min = np.iinfo(np.int64).min
            while True:
                out_ids = np.zeros(max_out, np.int32)
                doc_ends = np.zeros(n_docs, np.int64)
                n_docs_out = ctypes.c_int64(0)
                total = lib.dl4j_tokenize_encode(
                    vh, raw, len(raw), 1 if self.common else 0,
                    1 if keep_oov else 0,
                    out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    max_out,
                    doc_ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    n_docs, ctypes.byref(n_docs_out))
                if total == int64_min:      # doc-count overflow, not a
                    return None             # resizable condition
                if total >= 0:
                    break
                max_out = -total            # buffer was too small; resize
            result = []
            start = 0
            for d in range(n_docs_out.value):
                end = int(doc_ends[d])
                seg = out_ids[start:end]
                # translate position index -> caller's ids (keep -1 OOV);
                # empty vocab means every token is OOV
                if ids.size:
                    trans = np.where(seg >= 0, ids[np.maximum(seg, 0)], -1)
                else:
                    trans = np.full(seg.shape, -1, np.int32)
                result.append(trans.astype(np.int32))
                start = end
            # a trailing empty document yields no final '\n' segment in C++
            while len(result) < len(docs):
                result.append(np.zeros(0, np.int32))
            return result
        finally:
            lib.dl4j_vocab_free(vh)
