"""Sentence iterators (DL4J `text/sentenceiterator/` parity)."""
from __future__ import annotations

import os
from typing import Iterable, Iterator


class SentenceIterator:
    def sentences(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self):
        pass

    def __iter__(self):
        return self.sentences()


class CollectionSentenceIterator(SentenceIterator):
    """In-memory sentences (DL4J CollectionSentenceIterator)."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)

    def sentences(self):
        return iter(self._sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (DL4J BasicLineIterator)."""

    def __init__(self, path: str):
        self.path = path

    def sentences(self):
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, one sentence per line (DL4J
    FileSentenceIterator)."""

    def __init__(self, directory: str):
        self.directory = directory

    def sentences(self):
        for root, _, names in os.walk(self.directory):
            for n in sorted(names):
                with open(os.path.join(root, n), encoding="utf-8",
                          errors="ignore") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


class LabelAwareIterator(SentenceIterator):
    """(label, sentence) pairs for ParagraphVectors (DL4J LabelAware
    iterators)."""

    def __init__(self, documents: Iterable):
        """documents: iterable of (label, text)."""
        self._docs = list(documents)

    def documents(self):
        return iter(self._docs)

    def sentences(self):
        return iter(text for _, text in self._docs)
