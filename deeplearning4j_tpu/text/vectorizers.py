"""Bag-of-words and TF-IDF text vectorizers.

Parity: DL4J `bagofwords/vectorizer/{BaseTextVectorizer, BagOfWordsVectorizer,
TfidfVectorizer}.java` with the exact reference weighting
(`clustering/util/MathUtils.java:258-286`):
    tf(word, doc)  = count / doc_length
    idf(word)      = log10(total_docs / docs_containing_word)
    tfidf          = tf * idf
BagOfWords emits raw counts. Vocabulary building honors min_word_frequency
and stop words like BaseTextVectorizer.buildVocab.

The vectorizers are the text-classification on-ramp: fit() over a
LabelAwareIterator, then `vectorize()` yields a DataSet whose rows feed an
OutputLayer classifier directly. Matrix assembly is host-side numpy; the
classifier consumes it on device (host-side text plumbing stays native —
SURVEY.md §7).
"""
from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.text.documentiterator import (
    LabelAwareIterator, LabelsSource, SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.text.invertedindex import InMemoryInvertedIndex
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


class BaseTextVectorizer:
    """Vocab construction + corpus scan shared by BoW/TF-IDF
    (DL4J BaseTextVectorizer.buildVocab)."""

    def __init__(self, iterator=None, tokenizer_factory=None,
                 min_word_frequency: int = 1,
                 stop_words: Optional[Iterable[str]] = None,
                 index: Optional[InMemoryInvertedIndex] = None):
        if iterator is not None and not isinstance(iterator,
                                                   LabelAwareIterator):
            iterator = SimpleLabelAwareIterator(iterator)
        self.iterator = iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = max(1, min_word_frequency)
        self.stop_words = set(stop_words or ())
        self.index = index if index is not None else InMemoryInvertedIndex()
        self.labels_source: LabelsSource = (
            iterator.labels_source if iterator is not None else LabelsSource())
        self.vocab: List[str] = []
        self._vocab_index = {}
        self._doc_freq = {}
        self._doc_labels: List[str] = []
        self._fitted = False

    # ---------------------------------------------------------------- fit
    def fit(self):
        """Scan the corpus: tokenize, build the inverted index, then keep
        words with frequency >= min_word_frequency that are not stop words
        (BaseTextVectorizer.buildVocab). Re-runnable: each fit() rebuilds
        the index and per-document bookkeeping from scratch."""
        if self.iterator is None:
            raise ValueError("vectorizer needs a document iterator to fit")
        self.index = InMemoryInvertedIndex()
        self._doc_labels = []
        counts = Counter()
        doc_id = 0
        self.iterator.reset()
        for doc in self.iterator:
            tokens = [t for t in self.tokenizer_factory.tokenize(doc.content)
                      if t not in self.stop_words]
            self.index.add_doc(doc_id, tokens)
            counts.update(tokens)
            self._doc_labels.append(doc.label)
            doc_id += 1
        self.vocab = sorted(w for w, c in counts.items()
                            if c >= self.min_word_frequency)
        self._vocab_index = {w: i for i, w in enumerate(self.vocab)}
        self._doc_freq = {w: self.index.doc_appeared_in(w)
                          for w in self.vocab}
        self._fitted = True
        return self

    def _require_fit(self):
        if not self._fitted:
            raise RuntimeError("call fit() first")

    def num_words(self) -> int:
        self._require_fit()
        return len(self.vocab)

    def index_of(self, word: str) -> int:
        return self._vocab_index.get(word, -1)

    # ---------------------------------------------------------- transform
    def _weights(self, tokens: Sequence[str]) -> np.ndarray:
        raise NotImplementedError

    def transform(self, text_or_tokens) -> np.ndarray:
        """(1, V) weight row for one document (TextVectorizer.transform).
        Stop words are filtered exactly as in fit(), so the same document
        gets the same weights at inference time as it had in the corpus."""
        self._require_fit()
        tokens = (self.tokenizer_factory.tokenize(text_or_tokens)
                  if isinstance(text_or_tokens, str) else list(text_or_tokens))
        tokens = [t for t in tokens if t not in self.stop_words]
        return self._weights(tokens)[None, :]

    def vectorize(self, text: Optional[str] = None,
                  label: Optional[str] = None) -> DataSet:
        """One labelled document -> DataSet row, or (with no args) the whole
        fitted corpus -> (N, V) features + one-hot labels
        (TfidfVectorizer.vectorize)."""
        self._require_fit()
        n_labels = max(1, self.labels_source.size())
        if text is not None:
            x = self.transform(text)
            y = np.zeros((1, n_labels), np.float32)
            li = self.labels_source.index_of(label)
            if li >= 0:
                y[0, li] = 1.0
            return DataSet(x.astype(np.float32), y)
        rows = []
        labels = np.zeros((self.index.num_documents(), n_labels), np.float32)
        for doc_id in sorted(self.index.documents()):
            rows.append(self._weights(self.index.document(doc_id)))
            li = self.labels_source.index_of(self._doc_labels[doc_id])
            if li >= 0:
                labels[doc_id, li] = 1.0
        return DataSet(np.stack(rows).astype(np.float32), labels)


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw in-document word counts (DL4J BagOfWordsVectorizer)."""

    def _weights(self, tokens: Sequence[str]) -> np.ndarray:
        row = np.zeros((len(self.vocab),), np.float32)
        for tok, c in Counter(tokens).items():
            i = self._vocab_index.get(tok, -1)
            if i >= 0:
                row[i] = float(c)
        return row


class TfidfVectorizer(BaseTextVectorizer):
    """tf * idf weights with the reference formulas
    (TfidfVectorizer.tfidfWord, MathUtils.idf/tf)."""

    def idf(self, word: str) -> float:
        self._require_fit()
        total = self.index.num_documents()
        df = self._doc_freq.get(word, 0)
        if total == 0 or df == 0:
            return 0.0
        return math.log10(total / df)

    def _weights(self, tokens: Sequence[str]) -> np.ndarray:
        row = np.zeros((len(self.vocab),), np.float32)
        if not tokens:
            return row
        n = len(tokens)
        for tok, c in Counter(tokens).items():
            i = self._vocab_index.get(tok, -1)
            if i >= 0:
                row[i] = (c / n) * self.idf(tok)
        return row
