"""Label-aware document iterators.

Parity: DL4J `text/documentiterator/` — `LabelledDocument`, `LabelsSource`,
`SimpleLabelAwareIterator`, `BasicLabelAwareIterator`,
`FileLabelAwareIterator` (one subdirectory per label),
`FilenamesLabelAwareIterator`. These feed the bag-of-words/TF-IDF
vectorizers and ParagraphVectors; they are host-side text plumbing, so they
stay plain Python (SURVEY.md §7: host-side algorithms do not belong on TPU).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Iterator, List, Optional, Tuple


@dataclasses.dataclass
class LabelledDocument:
    """One document + its label(s) (DL4J LabelledDocument)."""
    content: str
    labels: List[str] = dataclasses.field(default_factory=list)

    @property
    def label(self) -> Optional[str]:
        return self.labels[0] if self.labels else None


class LabelsSource:
    """Ordered registry of the labels seen (DL4J LabelsSource): stable
    index per label, used to build one-hot label rows."""

    def __init__(self, template: str = "DOC_%d"):
        self.template = template
        self._labels: List[str] = []
        self._index = {}

    def store_label(self, label: str) -> int:
        if label not in self._index:
            self._index[label] = len(self._labels)
            self._labels.append(label)
        return self._index[label]

    def next_label(self) -> str:
        label = self.template % len(self._labels)
        self.store_label(label)
        return label

    def index_of(self, label: str) -> int:
        return self._index.get(label, -1)

    def size(self) -> int:
        return len(self._labels)

    def get_labels(self) -> List[str]:
        return list(self._labels)


class LabelAwareIterator:
    """Iterator of LabelledDocuments (DL4J LabelAwareIterator)."""

    labels_source: LabelsSource

    def documents(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self):
        pass

    def __iter__(self):
        return self.documents()


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wraps an in-memory collection of (text, label) pairs or
    LabelledDocuments (DL4J SimpleLabelAwareIterator)."""

    def __init__(self, documents: Iterable):
        self._docs: List[LabelledDocument] = []
        self.labels_source = LabelsSource()
        for d in documents:
            if isinstance(d, LabelledDocument):
                doc = d
            else:
                text, label = d
                doc = LabelledDocument(text, [label])
            for lab in doc.labels:
                self.labels_source.store_label(lab)
            self._docs.append(doc)

    def documents(self):
        return iter(self._docs)


class BasicLabelAwareIterator(LabelAwareIterator):
    """Wraps a plain sentence iterator, generating synthetic labels
    DOC_0, DOC_1, ... (DL4J BasicLabelAwareIterator)."""

    def __init__(self, sentences: Iterable[str], template: str = "DOC_%d"):
        self.labels_source = LabelsSource(template)
        self._docs = []
        for s in sentences:
            label = self.labels_source.next_label()
            self._docs.append(LabelledDocument(s, [label]))

    def documents(self):
        return iter(self._docs)


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory tree where each SUBDIRECTORY is a label and each file in
    it a document (DL4J FileLabelAwareIterator)."""

    def __init__(self, root: str):
        self.root = root
        self.labels_source = LabelsSource()
        self._files: List[Tuple[str, str]] = []
        for label in sorted(os.listdir(root)):
            d = os.path.join(root, label)
            if not os.path.isdir(d):
                continue
            self.labels_source.store_label(label)
            for fname in sorted(os.listdir(d)):
                path = os.path.join(d, fname)
                if os.path.isfile(path):
                    self._files.append((path, label))

    def documents(self):
        for path, label in self._files:
            with open(path, encoding="utf-8") as f:
                yield LabelledDocument(f.read(), [label])


class FilenamesLabelAwareIterator(LabelAwareIterator):
    """Flat directory: every file is a document, its filename the label
    (DL4J FilenamesLabelAwareIterator)."""

    def __init__(self, root: str):
        self.root = root
        self.labels_source = LabelsSource()
        self._files = []
        for fname in sorted(os.listdir(root)):
            path = os.path.join(root, fname)
            if os.path.isfile(path):
                self.labels_source.store_label(fname)
                self._files.append((path, fname))

    def documents(self):
        for path, label in self._files:
            with open(path, encoding="utf-8") as f:
                yield LabelledDocument(f.read(), [label])
