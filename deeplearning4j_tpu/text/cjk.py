"""CJK tokenizer packs (Chinese / Japanese / Korean).

Parity: DL4J `deeplearning4j-nlp-{chinese,japanese,korean}/` — which wrap
external morphological analyzers (ansj, kuromoji, the Korean twitter
tokenizer). Those dictionaries cannot ship here (zero egress, and the
reference itself treats them as external artifacts); the TPU-framework
equivalents are self-contained segmenters with the same factory interface:

- script-aware run splitting (han / hiragana / katakana / hangul / latin /
  digits each form separate runs);
- optional user LEXICON with greedy longest-match segmentation inside han
  runs (how dictionary segmenters behave on in-vocabulary text);
- han text without a lexicon falls back to unigram+bigram emission (the
  standard dictionary-free CJK IR baseline);
- Korean particle stripping for the most common postpositions.

Factories satisfy the same `tokenize(text) -> List[str]` contract as
tokenization.DefaultTokenizerFactory, so every vectorizer/embedding
pipeline accepts them unchanged.
"""
from __future__ import annotations

from typing import Iterable, List, Optional


def _script(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "han"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or o == 0x30FC:
        return "katakana"
    if 0xAC00 <= o <= 0xD7AF:
        return "hangul"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "other"


def _runs(text: str):
    """Yield (script, run) with consecutive same-script chars grouped."""
    cur, cur_script = [], None
    for ch in text:
        s = _script(ch)
        if s != cur_script and cur:
            yield cur_script, "".join(cur)
            cur = []
        cur_script = s
        cur.append(ch)
    if cur:
        yield cur_script, "".join(cur)


def _greedy_lexicon_segment(run: str, lexicon, max_len: int) -> List[str]:
    out = []
    i = 0
    n = len(run)
    while i < n:
        match = None
        for L in range(min(max_len, n - i), 1, -1):
            if run[i:i + L] in lexicon:
                match = run[i:i + L]
                break
        if match:
            out.append(match)
            i += len(match)
        else:
            out.append(run[i])
            i += 1
    return out


class ChineseTokenizerFactory:
    """Han segmentation: lexicon longest-match when given, else
    unigram+bigram emission; latin/digit runs pass through whole
    (deeplearning4j-nlp-chinese's ChineseTokenizer role)."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 emit_bigrams: bool = True, preprocessor=None):
        self.lexicon = frozenset(lexicon or ())
        self.max_word = max((len(w) for w in self.lexicon), default=1)
        self.emit_bigrams = emit_bigrams
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        toks: List[str] = []
        for script, run in _runs(text):
            if script in ("space", "other"):
                continue
            if script == "han":
                if self.lexicon:
                    toks.extend(_greedy_lexicon_segment(
                        run, self.lexicon, self.max_word))
                else:
                    toks.extend(run)            # unigrams
                    if self.emit_bigrams:
                        toks.extend(run[i:i + 2]
                                    for i in range(len(run) - 1))
            else:
                toks.append(run)
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]

    create = tokenize


class JapaneseTokenizerFactory:
    """Script-boundary segmentation (kanji/hiragana/katakana/latin runs
    split like a coarse morphological analyzer; kuromoji's role in
    deeplearning4j-nlp-japanese). Hiragana runs are kept whole (mostly
    particles/inflections); kanji runs segment via the optional lexicon
    like the Chinese factory."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 preprocessor=None):
        self.lexicon = frozenset(lexicon or ())
        self.max_word = max((len(w) for w in self.lexicon), default=1)
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        toks: List[str] = []
        for script, run in _runs(text):
            if script in ("space", "other"):
                continue
            if script == "han" and self.lexicon:
                toks.extend(_greedy_lexicon_segment(
                    run, self.lexicon, self.max_word))
            elif script == "han" and len(run) > 2:
                toks.extend(run[i:i + 2] for i in range(0, len(run), 2))
            else:
                toks.append(run)
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]

    create = tokenize


# the most frequent Korean postpositional particles (josa); stripping them
# merges inflected forms of the same noun, the role the twitter-korean
# tokenizer's stemming plays in deeplearning4j-nlp-korean
_KO_PARTICLES = ("은", "는", "이", "가", "을", "를", "의", "에", "에서",
                 "으로", "로", "와", "과", "도", "만", "까지", "부터",
                 "에게", "한테", "처럼")


class KoreanTokenizerFactory:
    """Whitespace/script tokenization with particle handling. Without a
    morphological dictionary a bare noun ending in a particle syllable is
    indistinguishable from noun+particle (고양이 'cat' ends in the
    subject-particle syllable 이), so stripping single-syllable particles
    emits BOTH surface and stripped forms — 고양이 and 고양이가 then share
    the token 고양이, which is the form merging the feature exists for.
    Multi-syllable particles (에서, 으로...) are unambiguous enough to
    strip outright."""

    def __init__(self, strip_particles: bool = True, preprocessor=None):
        self.strip_particles = strip_particles
        self.preprocessor = preprocessor

    def _hangul_tokens(self, run: str) -> List[str]:
        if not self.strip_particles or len(run) < 2:
            return [run]
        for p in sorted(_KO_PARTICLES, key=len, reverse=True):
            if run.endswith(p) and len(run) > len(p):
                stem = run[:-len(p)]
                if len(p) >= 2:
                    return [stem]
                return [run, stem]      # ambiguous: keep both forms
        return [run]

    def tokenize(self, text: str) -> List[str]:
        toks: List[str] = []
        for script, run in _runs(text):
            if script in ("space", "other"):
                continue
            if script == "hangul":
                toks.extend(self._hangul_tokens(run))
            else:
                toks.append(run)
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]

    create = tokenize
