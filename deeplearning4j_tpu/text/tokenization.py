"""Tokenizers + token preprocessors.

Parity: DL4J `text/tokenization/tokenizerfactory/DefaultTokenizerFactory`,
`NGramTokenizerFactory`, and `tokenization/tokenizer/preprocessor/
{CommonPreprocessor,LowCasePreprocessor}` — the pieces Word2Vec's pipeline
actually exercises. A factory produces a `tokenize(str) -> list[str]`
callable; preprocessors normalize each token.
"""
from __future__ import annotations

import re
from typing import List


class CommonPreprocessor:
    """Strip punctuation + lowercase (DL4J CommonPreprocessor)."""
    _PUNCT = re.compile(r"[\d.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreprocessor:
    def pre_process(self, token: str) -> str:
        return token.lower()


class DefaultTokenizerFactory:
    """Whitespace tokenizer with optional preprocessor (DL4J
    DefaultTokenizerFactory wraps a StreamTokenizer; whitespace split is the
    observable behavior for plain text)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor

    def set_token_pre_processor(self, p):
        self.preprocessor = p

    def tokenize(self, text: str) -> List[str]:
        toks = text.split()
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]

    def create(self, text: str):
        return self.tokenize(text)


class RegexTokenizerFactory:
    def __init__(self, pattern: str = r"\w+", preprocessor=None):
        self.pattern = re.compile(pattern)
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        toks = self.pattern.findall(text)
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return [t for t in toks if t]


class NGramTokenizerFactory:
    """Emit n-grams of an underlying tokenizer (DL4J NGramTokenizerFactory)."""

    def __init__(self, base=None, min_n: int = 1, max_n: int = 2,
                 joiner: str = " "):
        self.base = base or DefaultTokenizerFactory()
        self.min_n = min_n
        self.max_n = max_n
        self.joiner = joiner

    def tokenize(self, text: str) -> List[str]:
        toks = self.base.tokenize(text)
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(toks) - n + 1):
                out.append(self.joiner.join(toks[i:i + n]))
        return out
