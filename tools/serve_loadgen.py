#!/usr/bin/env python
"""Load generator for the model-serving HTTP surface (docs/SERVING.md).

Closed loop (default): N worker threads each keep one request in flight —
measures the server's saturated throughput and latency under a fixed
concurrency. Open loop: requests fire on a fixed arrival schedule
regardless of completions (the honest way to measure tail latency at a
target offered rate — a closed loop self-throttles when the server slows,
hiding queueing collapse).

    python tools/serve_loadgen.py --url http://127.0.0.1:8500 \
        --model lenet --requests 500 --concurrency 8 [--rate 200]

Reports p50/p90/p99 latency, goodput (2xx/sec over the wall clock), and a
status-code histogram as JSON on stdout. Exit 0 iff every request
succeeded (2xx), so CI can use it as an assertion.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np


def percentile(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(p / 100 * (len(xs) - 1))))
    return xs[i]


class LoadGen:
    def __init__(self, args, input_shape):
        self.args = args
        self.input_shape = tuple(input_shape)
        self.url = (f"{args.url}/v1/models/{args.model}/predict"
                    + (f"?deadline_ms={args.deadline_ms}"
                       if args.deadline_ms else ""))
        self.lock = threading.Lock()
        self.latencies = []             # seconds, successful only
        self.codes = {}
        self.rs = np.random.RandomState(args.seed)
        self.bodies = [
            json.dumps({"inputs": self.rs.rand(
                b, *self.input_shape).astype("float32").tolist()}).encode()
            for b in (args.batch_sizes or [1])
        ]

    def one(self, i: int):
        body = self.bodies[i % len(self.bodies)]
        t0 = time.perf_counter()
        try:
            r = urllib.request.urlopen(urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"}),
                timeout=self.args.timeout_s)
            code = r.status
            r.read()
        except urllib.error.HTTPError as e:
            code = e.code
            e.read()
        except Exception:               # connection refused/reset, timeout
            code = 0
        dt = time.perf_counter() - t0
        with self.lock:
            self.codes[code] = self.codes.get(code, 0) + 1
            if 200 <= code < 300:
                self.latencies.append(dt)

    def run_closed(self):
        n = self.args.requests
        counter = iter(range(n))
        counter_lock = threading.Lock()

        def worker():
            while True:
                with counter_lock:
                    i = next(counter, None)
                if i is None:
                    return
                self.one(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.args.concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    def run_open(self):
        period = 1.0 / self.args.rate
        threads = []
        t0 = time.perf_counter()
        for i in range(self.args.requests):
            target = t0 + i * period
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=self.one, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.args.timeout_s + 5)
        return time.perf_counter() - t0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--url", default="http://127.0.0.1:8500")
    p.add_argument("--model", default="model")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop worker threads")
    p.add_argument("--rate", type=float, default=None,
                   help="open-loop offered rate (req/s); omit = closed loop")
    p.add_argument("--input-shape", default=None,
                   help="comma ints; default: ask GET /v1/models/{name}")
    p.add_argument("--batch-sizes", default="1,2,4",
                   help="cycle of per-request batch sizes")
    p.add_argument("--deadline-ms", type=float, default=None)
    p.add_argument("--timeout-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    args.batch_sizes = [int(b) for b in str(args.batch_sizes).split(",") if b]

    if args.input_shape:
        shape = tuple(int(s) for s in args.input_shape.split(",") if s)
    else:
        meta = json.loads(urllib.request.urlopen(
            f"{args.url}/v1/models/{args.model}", timeout=10).read())
        shape = tuple(meta["input_shape"])

    gen = LoadGen(args, shape)
    wall = gen.run_open() if args.rate else gen.run_closed()
    ok = sum(n for c, n in gen.codes.items() if 200 <= c < 300)
    lat_ms = [l * 1e3 for l in gen.latencies]
    report = {
        "mode": "open" if args.rate else "closed",
        "requests": args.requests,
        "ok": ok,
        "errors": args.requests - ok,
        "codes": {str(k): v for k, v in sorted(gen.codes.items())},
        "wall_s": round(wall, 3),
        "goodput_rps": round(ok / wall, 2) if wall > 0 else None,
        "latency_ms": {
            "p50": round(percentile(lat_ms, 50), 3) if lat_ms else None,
            "p90": round(percentile(lat_ms, 90), 3) if lat_ms else None,
            "p99": round(percentile(lat_ms, 99), 3) if lat_ms else None,
            "max": round(max(lat_ms), 3) if lat_ms else None,
        },
    }
    print(json.dumps(report, indent=1))
    return 0 if ok == args.requests else 1


if __name__ == "__main__":
    sys.exit(main())
