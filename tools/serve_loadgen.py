#!/usr/bin/env python
"""Load generator for the model-serving HTTP surface (docs/SERVING.md).

Closed loop (default): N worker threads each keep one request in flight —
measures the server's saturated throughput and latency under a fixed
concurrency. A closed-loop client is a *polite* client: on 429/503 it
honors the server's ``Retry-After`` hint (jittered server-side exactly so
shed clients don't stampede back in sync) before retrying, up to
``--max-retries`` per logical request. Open loop: requests fire on a fixed
arrival schedule regardless of completions (the honest way to measure tail
latency at a target offered rate — a closed loop self-throttles when the
server slows, hiding queueing collapse); open loop never retries, an
offered request is an offered request.

Priority classes: ``--priority-mix interactive=3,batch=1`` tags requests
with ``X-Priority`` headers in a deterministic weighted cycle and reports
latency percentiles and an error breakdown *per class* — the view that
shows shedding hitting the batch tier while interactive p99 holds.

Decode mode: ``--mode decode`` drives the token-streaming generate
surface instead (docs/SERVING.md "LLM decode"): each logical request is
one SSE stream, consumed token-by-token, and the report adds TTFT
p50/p99, inter-token p99, and tokens/sec goodput — overall and per
priority class. The closed loop honors Retry-After on shed (429/503)
streams exactly as for predicts; a stream truncated before its ``done``
event counts as a transport failure, never as success.

    python tools/serve_loadgen.py --url http://127.0.0.1:8500 \
        --model lenet --requests 500 --concurrency 8 [--rate 200] \
        [--priority-mix interactive=3,batch=1]

Reports p50/p90/p99 latency, goodput (2xx/sec over the wall clock), a
status-code histogram, and an error-class taxonomy (429 shed / 503
unavailable / 504 deadline / 5xx server / transport) as JSON on stdout.
Exit 0 iff every request ultimately succeeded (2xx; shed-then-retried-ok
counts as ok), so CI can use it as an assertion.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np


def mint_traceparent():
    """(trace_id, traceparent header) minted client-side — the origin of
    the request's cross-process trace. No library import needed: the
    header is just the W3C wire shape the serving ingress adopts."""
    trace_id = os.urandom(16).hex()
    return trace_id, f"00-{trace_id}-{os.urandom(8).hex()}-01"


def percentile(xs, p):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(p / 100 * (len(xs) - 1))))
    return xs[i]


def classify(code) -> str:
    """Error taxonomy: what *kind* of failure (or backpressure) was it."""
    if isinstance(code, int):
        if 200 <= code < 300:
            return "ok"
        if code == 429:
            return "shed_429"
        if code == 503:
            return "unavailable_503"
        if code == 504:
            return "deadline_504"
        if 500 <= code < 600:
            return "server_5xx"
        return f"client_{code}"
    return "transport"


def _latency_stats(lat_s):
    ms = [v * 1e3 for v in lat_s]
    return {
        "p50": round(percentile(ms, 50), 3) if ms else None,
        "p90": round(percentile(ms, 90), 3) if ms else None,
        "p99": round(percentile(ms, 99), 3) if ms else None,
        "max": round(max(ms), 3) if ms else None,
    }


def _spec_cols(st):
    """Speculative-decoding columns from summed done-event counters:
    acceptance rate (accepted / proposed draft tokens) and tokens per
    verify round (each round emits accepted + 1)."""
    return {
        "spec_streams": st["streams"],
        "spec_acceptance_rate": round(st["accepted"] / st["proposed"], 4)
        if st["proposed"] else None,
        "spec_accepted_per_step": round(
            (st["accepted"] + st["rounds"]) / st["rounds"], 4)
        if st["rounds"] else None,
    }


class LoadGen:
    def __init__(self, args, input_shape):
        self.args = args
        self.mode = getattr(args, "mode", "predict")
        self.input_shape = tuple(input_shape or ())
        verb = "generate" if self.mode == "decode" else "predict"
        self.url = (f"{args.url}/v1/models/{args.model}/{verb}"
                    + (f"?deadline_ms={args.deadline_ms}"
                       if args.deadline_ms else ""))
        self.lock = threading.Lock()
        self.latencies = {}             # class -> [seconds], 2xx only
        self.traced = {}                # class -> [(seconds, trace_id)]
        self.slow_k = int(getattr(args, "slow_k", 3) or 0)
        self.codes = {}
        self.class_codes = {}           # class -> {taxonomy: count}
        self.retries = 0
        self.retry_wait_s = 0.0
        self.issued = 0        # logical requests, across every run_* call
        self.rs = np.random.RandomState(args.seed)
        if self.mode == "decode":
            self.vocab = int(getattr(args, "vocab", None) or 0)
            if self.vocab < 2:
                raise SystemExit("--mode decode needs --vocab (or a "
                                 "servable describing vocab_size)")
            # shared/unique-prefix workload: a deterministic weighted
            # cycle of prefix classes; "shared" prompts open with ONE
            # common prefix (the system-prompt shape the server's KV
            # prefix cache exists for) + a per-request unique suffix,
            # every other class gets a fully unique prompt
            self.prefix_mix = dict(getattr(args, "prefix_mix", None)
                                   or {})
            self.prefix_cycle = [c for c, w in sorted(
                self.prefix_mix.items()) for _ in range(w)] or [None]
            shared_len = int(getattr(args, "shared_prefix_len", None)
                             or (2 * args.prompt_len) // 3)
            if self.prefix_mix:
                if not 0 < shared_len < args.prompt_len:
                    raise SystemExit(
                        f"--shared-prefix-len must be in (0, "
                        f"{args.prompt_len}); got {shared_len}")
                shared_prefix = self.rs.randint(
                    0, self.vocab, shared_len).tolist()

                def prompt_for(i):
                    if self.prefix_cycle[i % len(self.prefix_cycle)] \
                            == "shared":
                        return shared_prefix + self.rs.randint(
                            0, self.vocab,
                            args.prompt_len - shared_len).tolist()
                    return self.rs.randint(
                        0, self.vocab, args.prompt_len).tolist()

                n_bodies = args.requests
            else:
                def prompt_for(i):
                    return self.rs.randint(
                        0, self.vocab, args.prompt_len).tolist()

                n_bodies = 16           # a cycle of distinct prompts
            self.bodies = [
                json.dumps({
                    "prompt": prompt_for(i),
                    "max_tokens": args.max_new_tokens,
                    "temperature": args.temperature,
                    "top_k": args.top_k,
                    "stream": True,
                }).encode()
                for i in range(n_bodies)
            ]
            self.ttfts = {}             # class -> [seconds]
            self.itls = {}              # class -> [seconds] between tokens
            self.tokens = 0
            self.prefix_stats = {}      # prefix class -> counters/ttfts
            self.replica_stats = {}     # X-Served-By -> requests/hits
            # speculative-decoding counters per class, read off the done
            # event (0/0/0 streams on a plain servable stay comparable)
            self.spec_stats = {}        # class -> proposed/accepted/rounds
        else:
            self.bodies = [
                json.dumps({"inputs": self.rs.rand(
                    b, *self.input_shape).astype(
                    "float32").tolist()}).encode()
                for b in (args.batch_sizes or [1])
            ]
        # deterministic weighted cycle of priority classes (None = no
        # header) so runs are reproducible request-for-request
        mix = args.priority_mix or {}
        self.class_cycle = [c for c, w in sorted(mix.items())
                            for _ in range(w)] or [None]

    def _class_of(self, i: int):
        return self.class_cycle[i % len(self.class_cycle)]

    def _send(self, i: int, traceparent=None):
        """One HTTP attempt: (code_or_'transport', latency_s,
        retry_after_s_or_None)."""
        body = self.bodies[i % len(self.bodies)]
        headers = {"Content-Type": "application/json"}
        cls = self._class_of(i)
        if cls is not None:
            headers["X-Priority"] = cls
        if traceparent is not None:
            headers["traceparent"] = traceparent
        t0 = time.perf_counter()
        retry_after = None
        try:
            r = urllib.request.urlopen(urllib.request.Request(
                self.url, data=body, headers=headers),
                timeout=self.args.timeout_s)
            code = r.status
            r.read()
        except urllib.error.HTTPError as e:
            code = e.code
            retry_after = e.headers.get("Retry-After")
            e.read()
        except Exception:               # connection refused/reset, timeout
            code = 0
        return code, time.perf_counter() - t0, retry_after

    def _send_decode(self, i: int, traceparent=None):
        """One token-stream attempt: consume the SSE response as tokens
        arrive, measuring TTFT and every inter-token gap. A stream that
        never reaches its ``done`` event counts as a transport failure —
        truncated generations must not read as success."""
        body = self.bodies[i % len(self.bodies)]
        headers = {"Content-Type": "application/json"}
        cls = self._class_of(i)
        if cls is not None:
            headers["X-Priority"] = cls
        if traceparent is not None:
            headers["traceparent"] = traceparent
        t0 = time.perf_counter()
        retry_after = None
        ttft, itls, ntok, last, done = None, [], 0, None, False
        cached = spec = served = None
        try:
            r = urllib.request.urlopen(urllib.request.Request(
                self.url, data=body, headers=headers),
                timeout=self.args.timeout_s)
            # fleet mode: the router names the replica that took the
            # stream — the per-replica cache-hit split keys off it
            served = r.headers.get("X-Served-By")
            for line in r:
                if not line.startswith(b"data: "):
                    continue
                ev = json.loads(line[6:])
                now = time.perf_counter()
                if "token" in ev:
                    ntok += 1
                    if ttft is None:
                        ttft = now - t0
                    else:
                        itls.append(now - last)
                    last = now
                elif ev.get("done"):
                    done = True
                    cached = ev.get("cached_tokens")
                    if ev.get("spec_rounds") is not None:
                        spec = (int(ev.get("spec_proposed") or 0),
                                int(ev.get("spec_accepted") or 0),
                                int(ev.get("spec_rounds") or 0))
                elif "error" in ev:
                    break
            code = r.status if done else 0
        except urllib.error.HTTPError as e:
            code = e.code
            retry_after = e.headers.get("Retry-After")
            e.read()
        except Exception:               # connection refused/reset, timeout
            code = 0
        return (code, time.perf_counter() - t0, retry_after, ttft, itls,
                ntok, cached, spec, served)

    def _record(self, i: int, code, dt: float, ttft=None, itls=(),
                ntok: int = 0, trace_id=None, cached=None, spec=None,
                served=None):
        cls = self._class_of(i) or "default"
        kind = classify(code if code != 0 else "transport")
        with self.lock:
            key = code if code != 0 else "transport"
            self.codes[key] = self.codes.get(key, 0) + 1
            self.class_codes.setdefault(cls, {})
            self.class_codes[cls][kind] = \
                self.class_codes[cls].get(kind, 0) + 1
            if isinstance(code, int) and 200 <= code < 300:
                self.latencies.setdefault(cls, []).append(dt)
                if trace_id is not None:
                    self.traced.setdefault(cls, []).append((dt, trace_id))
                if self.mode == "decode":
                    self.tokens += ntok
                    if ttft is not None:
                        self.ttfts.setdefault(cls, []).append(ttft)
                    if itls:
                        self.itls.setdefault(cls, []).extend(itls)
                    if spec is not None:
                        st = self.spec_stats.setdefault(
                            cls, {"streams": 0, "proposed": 0,
                                  "accepted": 0, "rounds": 0})
                        st["streams"] += 1
                        st["proposed"] += spec[0]
                        st["accepted"] += spec[1]
                        st["rounds"] += spec[2]
                    if self.prefix_mix and cached is not None:
                        # hot = the server's prefix cache served >= one
                        # full page of this prompt's KV; split TTFT by
                        # it so the report shows what a cache hit buys
                        pcls = self.prefix_cycle[
                            i % len(self.prefix_cycle)] or "unique"
                        st = self.prefix_stats.setdefault(
                            pcls, {"requests": 0, "hits": 0,
                                   "ttft_hot": [], "ttft_cold": []})
                        st["requests"] += 1
                        hot = cached > 0
                        st["hits"] += int(hot)
                        if ttft is not None:
                            st["ttft_hot" if hot
                               else "ttft_cold"].append(ttft)
                        if served is not None:
                            # fleet view: WHERE did the hits land —
                            # prefix-affinity routing concentrates the
                            # shared class's hits on the owner replica
                            rst = self.replica_stats.setdefault(
                                served, {"requests": 0, "hits": 0})
                            rst["requests"] += 1
                            rst["hits"] += int(hot)

    def _attempt(self, i: int, traceparent=None, trace_id=None):
        """One wire attempt in the configured workload; returns
        (code, retry_after)."""
        if self.mode == "decode":
            (code, dt, retry_after, ttft, itls, ntok, cached,
             spec, served) = self._send_decode(i, traceparent)
            self._record(i, code, dt, ttft=ttft, itls=itls, ntok=ntok,
                         trace_id=trace_id, cached=cached, spec=spec,
                         served=served)
        else:
            code, dt, retry_after = self._send(i, traceparent)
            self._record(i, code, dt, trace_id=trace_id)
        return code, retry_after

    def one_closed(self, i: int) -> bool:
        """One logical request, honoring Retry-After backpressure. Every
        ATTEMPT is recorded in the code histogram; returns True iff the
        request ultimately succeeded. All attempts of one logical
        request share ONE client-minted trace id, so a retried-then-slow
        request reads as one story server-side."""
        with self.lock:
            self.issued += 1
        trace_id, traceparent = mint_traceparent()
        attempts = 0
        while True:
            code, retry_after = self._attempt(i, traceparent, trace_id)
            if isinstance(code, int) and 200 <= code < 300:
                return True
            if code not in (429, 503) or attempts >= self.args.max_retries:
                return False
            attempts += 1
            try:
                wait = min(float(retry_after), self.args.retry_cap_s) \
                    if retry_after else 0.1
            except ValueError:
                wait = 0.1
            with self.lock:
                self.retries += 1
                self.retry_wait_s += wait
            time.sleep(wait)

    def one_open(self, i: int) -> bool:
        with self.lock:
            self.issued += 1
        trace_id, traceparent = mint_traceparent()
        code, _ = self._attempt(i, traceparent, trace_id)
        return isinstance(code, int) and 200 <= code < 300

    def run_closed(self):
        n = self.args.requests
        counter = iter(range(n))
        counter_lock = threading.Lock()
        ok = [0]

        def worker():
            try:
                while True:
                    with counter_lock:
                        i = next(counter, None)
                    if i is None:
                        return
                    if self.one_closed(i):
                        with self.lock:
                            ok[0] += 1
            except Exception as e:          # noqa: BLE001 — fail loud:
                # a crashed worker silently shrinks concurrency and
                # undercounts; the report must say why
                print(f"serve_loadgen: worker crashed: {e!r}",
                      file=sys.stderr)
                raise

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"loadgen-worker-{w}")
                   for w in range(self.args.concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, ok[0]

    def run_ramp(self, steps, fleet_url=None, sample_interval_s=1.0):
        """Stepped open-loop schedule: ``steps`` is [(rate_rps, secs),
        ...]; each step fires at its own fixed arrival rate. When
        ``fleet_url`` is given, a sampler thread polls ``/v1/fleet``
        alongside the schedule and records ready-replica count over
        time — the autoscaling drill's evidence that the fleet tracked
        the offered load."""
        threads = []
        ok = [0]
        samples = []
        current_rate = [0.0]
        stop = threading.Event()

        def fire(i):
            try:
                if self.one_open(i):
                    with self.lock:
                        ok[0] += 1
            except Exception as e:          # noqa: BLE001 — fail loud
                print(f"serve_loadgen: ramp request {i} crashed: {e!r}",
                      file=sys.stderr)
                raise

        t0 = time.perf_counter()

        def sample_fleet():
            while not stop.wait(sample_interval_s):
                doc = {}
                try:
                    doc = json.loads(urllib.request.urlopen(
                        f"{fleet_url}/v1/fleet", timeout=5).read())
                except Exception as e:      # noqa: BLE001 — a missed
                    # sample is a gap in the chart, not a run failure
                    print(f"serve_loadgen: fleet sample failed: {e!r}",
                          file=sys.stderr)
                reps = doc.get("replicas", [])
                samples.append({
                    "t_s": round(time.perf_counter() - t0, 1),
                    "offered_rps": current_rate[0],
                    "ready": sum(1 for r in reps
                                 if r.get("state") == "ready"),
                    "draining": sum(1 for r in reps
                                    if r.get("state") == "draining"),
                    "replicas": len(reps)})

        sampler = None
        if fleet_url:
            sampler = threading.Thread(target=sample_fleet, daemon=True,
                                       name="loadgen-fleet-sampler")
            sampler.start()
        i = 0
        for rate, dur in steps:
            current_rate[0] = rate
            period = 1.0 / rate
            step_start = time.perf_counter()
            for k in range(max(1, int(rate * dur))):
                target = step_start + k * period
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t = threading.Thread(target=fire, args=(i,), daemon=True,
                                     name=f"loadgen-ramp-{i}")
                t.start()
                threads.append(t)
                i += 1
        for t in threads:
            t.join(timeout=self.args.timeout_s + 5)
        stop.set()
        if sampler is not None:
            sampler.join(timeout=sample_interval_s + 5)
        self.replica_samples = samples
        self.ramp_steps = [{"rate_rps": r, "seconds": d}
                           for r, d in steps]
        return time.perf_counter() - t0, ok[0]

    def run_open(self):
        period = 1.0 / self.args.rate
        threads = []
        ok = [0]

        def fire(i):
            try:
                if self.one_open(i):
                    with self.lock:
                        ok[0] += 1
            except Exception as e:          # noqa: BLE001 — fail loud
                print(f"serve_loadgen: open-loop request {i} crashed: "
                      f"{e!r}", file=sys.stderr)
                raise

        t0 = time.perf_counter()
        for i in range(self.args.requests):
            target = t0 + i * period
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=fire, args=(i,), daemon=True,
                                 name=f"loadgen-fire-{i}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.args.timeout_s + 5)
        return time.perf_counter() - t0, ok[0]

    def report(self, wall: float, ok: int) -> dict:
        all_lat = [v for lats in self.latencies.values() for v in lats]
        taxonomy = {}
        for cls_counts in self.class_codes.values():
            for kind, cnt in cls_counts.items():
                taxonomy[kind] = taxonomy.get(kind, 0) + cnt
        ramp = getattr(self, "ramp_steps", None)
        rep = {
            "mode": "ramp" if ramp
            else ("open" if self.args.rate else "closed"),
            "workload": self.mode,
            # issued, not args.requests: callers (serve_chaos) accumulate
            # several run_closed() passes into one LoadGen/report
            "requests": self.issued,
            "ok": ok,
            "errors": self.issued - ok,
            "codes": {str(k): v for k, v in sorted(
                self.codes.items(), key=lambda kv: str(kv[0]))},
            "error_classes": dict(sorted(taxonomy.items())),
            "retries": self.retries,
            "retry_wait_s": round(self.retry_wait_s, 3),
            "wall_s": round(wall, 3),
            "goodput_rps": round(ok / wall, 2) if wall > 0 else None,
            "latency_ms": _latency_stats(all_lat),
        }
        if ramp:
            # replica count over time rides next to goodput: the chart
            # that shows the autoscaler tracking the offered-rate steps
            rep["ramp"] = ramp
            rep["replicas_over_time"] = getattr(self, "replica_samples",
                                                [])
        if self.slow_k > 0:
            # the K slowest successful requests per class, by trace_id:
            # a banked percentile now points at reproducible traces
            # (histogram exemplars server-side carry the same ids)
            rep["slowest"] = {
                cls: [{"trace_id": t, "ms": round(l * 1e3, 3)}
                      for l, t in sorted(pairs, reverse=True)
                      [:self.slow_k]]
                for cls, pairs in sorted(self.traced.items())}
        if self.mode == "decode":
            all_ttft = [v for xs in self.ttfts.values() for v in xs]
            all_itl = [v for xs in self.itls.values() for v in xs]
            rep["decode"] = {
                "streams_ok": ok,
                "tokens": self.tokens,
                # goodput in the unit decode is bought for: generated
                # tokens per wall second across all concurrent streams
                "decode_tokens_sec": round(self.tokens / wall, 2)
                if wall > 0 else None,
                "ttft_ms": _latency_stats(all_ttft),
                "inter_token_ms": _latency_stats(all_itl),
            }
            if self.spec_stats:
                tot = {"streams": 0, "proposed": 0, "accepted": 0,
                       "rounds": 0}
                for st in self.spec_stats.values():
                    for key in tot:
                        tot[key] += st[key]
                rep["decode"].update(_spec_cols(tot))
            if self.prefix_mix:
                total = sum(s["requests"]
                            for s in self.prefix_stats.values())
                hits = sum(s["hits"] for s in self.prefix_stats.values())
                hot = [t for s in self.prefix_stats.values()
                       for t in s["ttft_hot"]]
                cold = [t for s in self.prefix_stats.values()
                        for t in s["ttft_cold"]]
                rep["prefix"] = {
                    "cache_hit_rate": round(hits / total, 4)
                    if total else None,
                    "ttft_hot_ms": _latency_stats(hot),
                    "ttft_cold_ms": _latency_stats(cold),
                    "per_class": {
                        pcls: {
                            "requests": s["requests"],
                            "cache_hit_rate": round(
                                s["hits"] / s["requests"], 4)
                            if s["requests"] else None,
                            "ttft_hot_ms": _latency_stats(s["ttft_hot"]),
                            "ttft_cold_ms": _latency_stats(
                                s["ttft_cold"]),
                        } for pcls, s in sorted(
                            self.prefix_stats.items())},
                }
                if self.replica_stats:
                    # which replica the hits landed on (fleet runs via
                    # the router's X-Served-By header): affinity routing
                    # shows up as hit rates concentrated on owners
                    rep["prefix"]["per_replica"] = {
                        name: {"requests": s["requests"],
                               "cache_hit_rate": round(
                                   s["hits"] / s["requests"], 4)
                               if s["requests"] else None}
                        for name, s in sorted(self.replica_stats.items())}
        if len(self.class_cycle) > 1 or self.class_cycle[0] is not None:
            rep["per_class"] = {
                cls: {"latency_ms": _latency_stats(
                          self.latencies.get(cls, [])),
                      "outcomes": dict(sorted(counts.items()))}
                for cls, counts in sorted(self.class_codes.items())}
            if self.mode == "decode":
                for cls, sub in rep["per_class"].items():
                    sub["ttft_ms"] = _latency_stats(
                        self.ttfts.get(cls, []))
                    sub["inter_token_ms"] = _latency_stats(
                        self.itls.get(cls, []))
                    if cls in self.spec_stats:
                        sub.update(_spec_cols(self.spec_stats[cls]))
        return rep


def parse_ramp(spec):
    """``5:10,20:15,5:10`` -> [(5.0, 10.0), (20.0, 15.0), (5.0, 10.0)]
    (offered rate req/s : step duration seconds)."""
    steps = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        rate, sep, dur = part.partition(":")
        try:
            if not sep:
                raise ValueError("missing ':'")
            step = (float(rate), float(dur))
        except ValueError:
            raise SystemExit(
                f"--ramp expects RATE:SECONDS steps, got {part!r}")
        if step[0] <= 0 or step[1] <= 0:
            raise SystemExit(f"--ramp rates and durations must be > 0: "
                             f"{part!r}")
        steps.append(step)
    if not steps:
        raise SystemExit("--ramp needs at least one RATE:SECONDS step")
    return steps


def parse_priority_mix(spec):
    """``interactive=3,batch=1`` -> {"interactive": 3, "batch": 1}."""
    if not spec:
        return {}
    mix = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.partition("=")
        try:
            mix[name.strip()] = int(w) if sep else 1
        except ValueError:
            raise SystemExit(
                f"--priority-mix expects CLASS=WEIGHT, got {part!r}")
        if mix[name.strip()] < 1:
            raise SystemExit(f"--priority-mix weight must be >= 1: {part!r}")
    return mix


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--url", default="http://127.0.0.1:8500")
    p.add_argument("--model", default="model")
    p.add_argument("--mode", choices=("predict", "decode"),
                   default="predict",
                   help="predict = HTTP predicts; decode = streaming "
                        "token generation (SSE) with TTFT / inter-token "
                        "/ tokens-per-second stats")
    p.add_argument("--prompt-len", type=int, default=16,
                   help="decode mode: random-prompt token count")
    p.add_argument("--max-new-tokens", type=int, default=32,
                   help="decode mode: tokens requested per stream")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--vocab", type=int, default=None,
                   help="decode mode: prompt id range; default asks "
                        "GET /v1/models/{name} for vocab_size")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=8,
                   help="closed-loop worker threads")
    p.add_argument("--rate", type=float, default=None,
                   help="open-loop offered rate (req/s); omit = closed loop")
    p.add_argument("--ramp", default=None, metavar="R:S,R:S,...",
                   help="stepped open-loop schedule (RATE:SECONDS steps, "
                        "e.g. 5:10,20:15,5:10) — overrides --rate/"
                        "--requests; the report banks replica-count-"
                        "over-time sampled from /v1/fleet next to "
                        "goodput (the autoscaling-drill view)")
    p.add_argument("--fleet-sample-s", type=float, default=1.0,
                   help="--ramp: /v1/fleet sampling interval")
    p.add_argument("--input-shape", default=None,
                   help="comma ints; default: ask GET /v1/models/{name}")
    p.add_argument("--batch-sizes", default="1,2,4",
                   help="cycle of per-request batch sizes")
    p.add_argument("--priority-mix", default=None,
                   help="weighted X-Priority cycle, e.g. "
                        "interactive=3,batch=1 (default: no header)")
    p.add_argument("--prefix-mix", default=None,
                   help="decode mode: weighted prompt-prefix class "
                        "cycle, e.g. shared=3,unique=1 — 'shared' "
                        "prompts open with one common prefix (the KV "
                        "prefix-cache workload), everything else is "
                        "fully unique; the report adds per-class cache "
                        "hit rate and hot/cold TTFT splits")
    p.add_argument("--shared-prefix-len", type=int, default=None,
                   help="token length of the common prefix for the "
                        "'shared' class (default: 2/3 of --prompt-len)")
    p.add_argument("--max-retries", type=int, default=3,
                   help="closed-loop retries of a 429/503 (honoring "
                        "Retry-After) before the request counts failed")
    p.add_argument("--retry-cap-s", type=float, default=5.0,
                   help="cap on a single honored Retry-After wait")
    p.add_argument("--deadline-ms", type=float, default=None)
    p.add_argument("--timeout-s", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slow-k", type=int, default=3,
                   help="report the trace_ids of the K slowest "
                        "successful requests per priority class "
                        "(0 disables)")
    args = p.parse_args(argv)
    args.batch_sizes = [int(b) for b in str(args.batch_sizes).split(",") if b]
    args.priority_mix = parse_priority_mix(args.priority_mix)
    args.prefix_mix = parse_priority_mix(args.prefix_mix)
    if args.prefix_mix and args.mode != "decode":
        raise SystemExit("--prefix-mix is a decode-mode workload knob")

    shape = ()
    if args.mode == "decode":
        if args.vocab is None:
            meta = json.loads(urllib.request.urlopen(
                f"{args.url}/v1/models/{args.model}", timeout=10).read())
            args.vocab = meta.get("vocab_size")
    elif args.input_shape:
        shape = tuple(int(s) for s in args.input_shape.split(",") if s)
    else:
        meta = json.loads(urllib.request.urlopen(
            f"{args.url}/v1/models/{args.model}", timeout=10).read())
        shape = tuple(meta["input_shape"])

    gen = LoadGen(args, shape)
    if args.ramp:
        steps = parse_ramp(args.ramp)
        wall, ok = gen.run_ramp(steps, fleet_url=args.url,
                                sample_interval_s=args.fleet_sample_s)
    elif args.rate:
        wall, ok = gen.run_open()
    else:
        wall, ok = gen.run_closed()
    print(json.dumps(gen.report(wall, ok), indent=1))
    return 0 if ok == gen.issued else 1


if __name__ == "__main__":
    sys.exit(main())
