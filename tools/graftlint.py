#!/usr/bin/env python
"""graftlint CLI — the project-native static-analysis suite.

    python tools/graftlint.py deeplearning4j_tpu tools bench.py
    python tools/graftlint.py --json ... | jq .
    python tools/graftlint.py --list-rules
    python tools/graftlint.py --changed-only            # git-diff scope
    python tools/graftlint.py --lock-graph lock.json    # order-graph dump
    python tools/graftlint.py --jobs 8 ...              # parallel pass
    python tools/graftlint.py --write-baseline lint_baseline.json ...
    python tools/graftlint.py --baseline lint_baseline.json ...

Exit codes: 0 clean (or all findings baselined/suppressed), 2 on
unsuppressed findings, 1 on usage/internal error.

Suppression: ``# graftlint: disable=<rule>[,<rule>] -- <justification>``
on the flagged line (``disable-file=`` near the top of a file for
file-wide). The justification is REQUIRED; empty ones and stale pragmas
are findings themselves.

Baseline workflow (landing a NEW rule without blocking): run with
``--write-baseline lint_baseline.json`` once, commit the burn-down
file, and gate with ``--baseline lint_baseline.json`` — only NEW
findings fail; stale entries are reported so the file shrinks with the
debt. See docs/STATIC_ANALYSIS.md.
"""
import argparse
import concurrent.futures
import json
import os
import subprocess
import sys
import time
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# The analyzer is stdlib-only, but `deeplearning4j_tpu/__init__.py`
# imports the whole framework (jax included). Register a namespace stub
# so `deeplearning4j_tpu.analysis` imports WITHOUT executing the heavy
# package root — the lint must run fast on boxes with no accelerator
# stack warmed up. (No-op when the real package is already imported,
# e.g. under pytest.)
if "deeplearning4j_tpu" not in sys.modules:
    _pkg = types.ModuleType("deeplearning4j_tpu")
    _pkg.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu")]
    sys.modules["deeplearning4j_tpu"] = _pkg

from deeplearning4j_tpu import analysis  # noqa: E402
from deeplearning4j_tpu.analysis import core as _core  # noqa: E402


def _worker(chunk, select_list):
    """Per-module rule pass over one chunk of files — runs in a pool
    worker. Project-wide rules, pragmas and parse-error reporting stay
    in the parent (core.run); workers return plain Finding lists, which
    pickle (no AST attached). Fork inherits this process's package STUB,
    and under spawn the re-imported ``__mp_main__`` re-runs the stub
    lines above before any analysis import — either way workers never
    pay the heavy framework import."""
    select = set(select_list) if select_list is not None else None
    rules = [r for r in analysis.ALL_RULES
             if not isinstance(r, analysis.ProjectRule)
             and (select is None or r.name in select)]
    out = []
    for path in chunk:
        mod = _core.load_module(path)
        if mod is None:
            continue              # the parent reports parse errors itself
        findings = []
        for rule in rules:
            findings.extend(rule.check(mod))
        out.append((path, findings))
    return out


def _parallel_module_pass(files, select, jobs):
    """Fan the per-module rules out over `jobs` processes; returns the
    path -> findings map core.run accepts, or None to run serially."""
    if jobs <= 1 or len(files) < 3 * jobs:
        return None
    select_list = sorted(select) if select is not None else None
    chunks = [files[i::jobs] for i in range(jobs)]
    merged = {}
    with concurrent.futures.ProcessPoolExecutor(max_workers=jobs) as ex:
        for result in ex.map(_worker, chunks,
                             [select_list] * len(chunks)):
            for path, findings in result:
                merged[path] = findings
    return merged


def _changed_files():
    """Repo-relative .py files that differ from HEAD (staged, unstaged,
    untracked) — the dev-loop scope for --changed-only."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(args, capture_output=True, text=True,
                              cwd=ROOT, timeout=30)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(args)} failed: {proc.stderr.strip()}")
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(os.path.abspath(os.path.join(ROOT, line)))
    return out


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="project-native static analysis (docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   default=[os.path.join(ROOT, "deeplearning4j_tpu"),
                            os.path.join(ROOT, "tools"),
                            os.path.join(ROOT, "bench.py")],
                   help="files/dirs to lint (default: the shipped tree)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--select", metavar="RULES",
                   help="comma-separated rule names to run (default all)")
    p.add_argument("--baseline", metavar="FILE",
                   help="suppress findings recorded in FILE; only NEW "
                        "findings gate")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="snapshot current unsuppressed findings to FILE "
                        "and exit 0 (the burn-down workflow)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--lock-graph", metavar="PATH",
                   help="export the cross-module lock acquisition-order "
                        "graph (locks, held->acquired edges with call "
                        "chains, cycles) as JSON to PATH")
    p.add_argument("--changed-only", action="store_true",
                   help="lint only files changed vs HEAD (staged + "
                        "unstaged + untracked). Dev-loop scope: the "
                        "interprocedural rules see only the changed "
                        "subset; CI runs the full tree")
    p.add_argument("--jobs", type=int, default=None, metavar="N",
                   help="processes for the per-module rule pass "
                        "(default: min(8, cpu count); 1 = serial)")
    return p


def _list_rules() -> int:
    for rule in analysis.ALL_RULES:
        print(f"{rule.name}")
        print(f"    {rule.summary}")
        print(f"    history: {rule.historical}")
    print(f"{analysis.PRAGMA_RULE}")
    print("    framework check: pragmas need non-empty justifications "
          "and must suppress something")
    print("parse-error")
    print("    framework check: an unreadable/unparseable file is a "
          "finding, never 'clean'")
    return 0


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()
    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        known = {r.name for r in analysis.ALL_RULES}
        bad = select - known
        if bad:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 1
    t0 = time.time()
    paths = list(args.paths)
    if args.changed_only:
        try:
            changed = _changed_files()
        except (OSError, RuntimeError, subprocess.SubprocessError) as e:
            print(f"graftlint: --changed-only needs git: {e}",
                  file=sys.stderr)
            return 1
        paths = [f for f in analysis.iter_py_files(paths) if f in changed]
        if not paths:
            # clean working tree: a no-op scope is legitimately green
            # (unlike a typo'd path, which still errors below)
            print("graftlint: no changed Python files vs HEAD — "
                  "nothing to lint")
            if args.lock_graph:
                # loud, not silent: the requested artifact was NOT
                # (re)written — a consumer must not read a stale graph
                # behind a green exit
                print(f"graftlint: lock graph NOT written to "
                      f"{args.lock_graph} (no files analyzed; run "
                      "without --changed-only for the artifact)",
                      file=sys.stderr)
            return 0
    jobs = args.jobs if args.jobs is not None else min(
        8, os.cpu_count() or 1)
    try:
        files = analysis.iter_py_files(paths)
        module_findings = _parallel_module_pass(files, select, jobs)
        result = analysis.run(paths, select=select,
                              module_findings=module_findings)
    except OSError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 1
    if result.files == 0 and not result.findings:
        # a typo'd path must not read as a clean gate
        print("graftlint: no Python files under "
              f"{', '.join(args.paths)} — nothing was linted",
              file=sys.stderr)
        return 1
    if args.lock_graph:
        doc = result.project.concurrency().lock_graph_doc()
        tmp = args.lock_graph + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, args.lock_graph)
        # stderr under --json: stdout is the machine-readable stream
        print(f"graftlint: lock graph ({len(doc['locks'])} locks, "
              f"{len(doc['edges'])} edges, {len(doc['cycles'])} "
              f"cycle(s)) -> {args.lock_graph}",
              file=sys.stderr if args.json else sys.stdout)

    if args.write_baseline:
        if select is not None:
            print("graftlint: refusing --write-baseline with --select — "
                  "the file would silently drop the other rules' debt",
                  file=sys.stderr)
            return 1
        analysis.write_baseline(args.write_baseline, result)
        n = len(result.all_unsuppressed)
        print(f"graftlint: baselined {n} finding(s) -> "
              f"{args.write_baseline}")
        return 0

    gating = result.all_unsuppressed
    stale = []
    if args.baseline:
        try:
            gating, stale = analysis.apply_baseline(args.baseline, result)
        except (OSError, ValueError, KeyError) as e:
            print(f"graftlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 1
        if select is not None:
            # a rule-filtered run cannot see the other rules' debt —
            # their baseline entries are NOT stale, just out of scope
            stale = []

    elapsed = time.time() - t0
    if args.json:
        payload = {
            "version": 1,
            "files": result.files,
            "elapsed_seconds": round(elapsed, 3),
            "findings": [
                {"rule": f.rule, "path": os.path.relpath(f.path, ROOT),
                 "line": f.line, "message": f.message}
                for f in gating],
            "suppressed": len(result.suppressed),
            "baselined": (len(result.all_unsuppressed) - len(gating)
                          if args.baseline else 0),
            "stale_baseline_entries": stale,
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in gating:
            print(f.render(ROOT))
        for key in stale:
            print(f"stale baseline entry (fixed — rewrite the "
                  f"baseline to bank it): {key}")
        n, s = len(gating), len(result.suppressed)
        print(f"graftlint: {result.files} files, {n} finding(s)"
              + (f", {s} suppressed" if s else "")
              + (f", {len(result.all_unsuppressed) - n} baselined"
                 if args.baseline else "")
              + f" [{elapsed:.1f}s]")
    return 2 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
