#!/usr/bin/env python
"""graftlint CLI — the project-native static-analysis suite.

    python tools/graftlint.py deeplearning4j_tpu tools bench.py
    python tools/graftlint.py --json ... | jq .
    python tools/graftlint.py --list-rules
    python tools/graftlint.py --write-baseline lint_baseline.json ...
    python tools/graftlint.py --baseline lint_baseline.json ...

Exit codes: 0 clean (or all findings baselined/suppressed), 2 on
unsuppressed findings, 1 on usage/internal error.

Suppression: ``# graftlint: disable=<rule>[,<rule>] -- <justification>``
on the flagged line (``disable-file=`` near the top of a file for
file-wide). The justification is REQUIRED; empty ones and stale pragmas
are findings themselves.

Baseline workflow (landing a NEW rule without blocking): run with
``--write-baseline lint_baseline.json`` once, commit the burn-down
file, and gate with ``--baseline lint_baseline.json`` — only NEW
findings fail; stale entries are reported so the file shrinks with the
debt. See docs/STATIC_ANALYSIS.md.
"""
import argparse
import json
import os
import sys
import time
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# The analyzer is stdlib-only, but `deeplearning4j_tpu/__init__.py`
# imports the whole framework (jax included). Register a namespace stub
# so `deeplearning4j_tpu.analysis` imports WITHOUT executing the heavy
# package root — the lint must run fast on boxes with no accelerator
# stack warmed up. (No-op when the real package is already imported,
# e.g. under pytest.)
if "deeplearning4j_tpu" not in sys.modules:
    _pkg = types.ModuleType("deeplearning4j_tpu")
    _pkg.__path__ = [os.path.join(ROOT, "deeplearning4j_tpu")]
    sys.modules["deeplearning4j_tpu"] = _pkg

from deeplearning4j_tpu import analysis  # noqa: E402


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="project-native static analysis (docs/STATIC_ANALYSIS.md)")
    p.add_argument("paths", nargs="*",
                   default=[os.path.join(ROOT, "deeplearning4j_tpu"),
                            os.path.join(ROOT, "tools"),
                            os.path.join(ROOT, "bench.py")],
                   help="files/dirs to lint (default: the shipped tree)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--select", metavar="RULES",
                   help="comma-separated rule names to run (default all)")
    p.add_argument("--baseline", metavar="FILE",
                   help="suppress findings recorded in FILE; only NEW "
                        "findings gate")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="snapshot current unsuppressed findings to FILE "
                        "and exit 0 (the burn-down workflow)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _list_rules() -> int:
    for rule in analysis.ALL_RULES:
        print(f"{rule.name}")
        print(f"    {rule.summary}")
        print(f"    history: {rule.historical}")
    print(f"{analysis.PRAGMA_RULE}")
    print("    framework check: pragmas need non-empty justifications "
          "and must suppress something")
    print("parse-error")
    print("    framework check: an unreadable/unparseable file is a "
          "finding, never 'clean'")
    return 0


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()
    select = None
    if args.select:
        select = {s.strip() for s in args.select.split(",") if s.strip()}
        known = {r.name for r in analysis.ALL_RULES}
        bad = select - known
        if bad:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(bad))}",
                  file=sys.stderr)
            return 1
    t0 = time.time()
    try:
        result = analysis.run(args.paths, select=select)
    except OSError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 1
    if result.files == 0 and not result.findings:
        # a typo'd path must not read as a clean gate
        print("graftlint: no Python files under "
              f"{', '.join(args.paths)} — nothing was linted",
              file=sys.stderr)
        return 1

    if args.write_baseline:
        if select is not None:
            print("graftlint: refusing --write-baseline with --select — "
                  "the file would silently drop the other rules' debt",
                  file=sys.stderr)
            return 1
        analysis.write_baseline(args.write_baseline, result)
        n = len(result.all_unsuppressed)
        print(f"graftlint: baselined {n} finding(s) -> "
              f"{args.write_baseline}")
        return 0

    gating = result.all_unsuppressed
    stale = []
    if args.baseline:
        try:
            gating, stale = analysis.apply_baseline(args.baseline, result)
        except (OSError, ValueError, KeyError) as e:
            print(f"graftlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 1
        if select is not None:
            # a rule-filtered run cannot see the other rules' debt —
            # their baseline entries are NOT stale, just out of scope
            stale = []

    elapsed = time.time() - t0
    if args.json:
        payload = {
            "version": 1,
            "files": result.files,
            "elapsed_seconds": round(elapsed, 3),
            "findings": [
                {"rule": f.rule, "path": os.path.relpath(f.path, ROOT),
                 "line": f.line, "message": f.message}
                for f in gating],
            "suppressed": len(result.suppressed),
            "baselined": (len(result.all_unsuppressed) - len(gating)
                          if args.baseline else 0),
            "stale_baseline_entries": stale,
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in gating:
            print(f.render(ROOT))
        for key in stale:
            print(f"stale baseline entry (fixed — rewrite the "
                  f"baseline to bank it): {key}")
        n, s = len(gating), len(result.suppressed)
        print(f"graftlint: {result.files} files, {n} finding(s)"
              + (f", {s} suppressed" if s else "")
              + (f", {len(result.all_unsuppressed) - n} baselined"
                 if args.baseline else "")
              + f" [{elapsed:.1f}s]")
    return 2 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
