#!/usr/bin/env python
"""One-shot converter: any RecordReader / DataSetIterator -> the
streaming shard format (data/shards.py). Decode once at conversion time;
every subsequent epoch reads whole batches off memmapped shards with
zero per-sample Python — the offline half of the line-rate data plane.

Usage (pick ONE source):

    # images-from-directories (DataVec ImageRecordReader layout:
    # root/<label>/*.png) — decoded to raw uint8 HWC at convert time
    python tools/make_shards.py --out /data/shards \\
        --image-dir /data/train --height 224 --width 224 --channels 3

    # numeric CSV with a label column
    python tools/make_shards.py --out /data/shards \\
        --csv data.csv --label-index 4 --num-classes 3

    # escape hatch: any DataSetIterator from a factory
    python tools/make_shards.py --out /data/shards \\
        --factory mypkg.mymod:make_iterator

Labels that arrive as exact one-hot float batches are stored as int32
class ids + num_classes in the index (4 bytes/record) and rehydrate
bitwise-identically; uint8 image payloads are stored raw so they also
ship raw over the host->HBM link at fit time (device-side affine
normalization). Prints a JSON summary; --verify re-reads the first
batch and checks bitwise parity against the source.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _build_source(args):
    from deeplearning4j_tpu.data.records import (
        CSVRecordReader, ImageRecordReader, RecordReaderDataSetIterator,
    )
    if args.factory:
        mod, _, fn = args.factory.partition(":")
        if not fn:
            raise SystemExit("--factory must be module.path:callable")
        factory = getattr(importlib.import_module(mod), fn)
        return factory()
    if args.image_dir:
        rr = ImageRecordReader(args.height, args.width, args.channels,
                               shuffle=args.shuffle_seed is not None,
                               seed=args.shuffle_seed or 0)
        rr.initialize(args.image_dir)
        if args.shuffle_seed is None:
            print("make_shards: NOTE --image-dir keeps directory order "
                  "(all of class 0, then class 1, ...). Shard shuffling "
                  "at fit time is batch-granular, so class-grouped shards "
                  "yield single-class batches that train poorly — pass "
                  "--shuffle-seed N to mix records at conversion time.",
                  file=sys.stderr)
        return RecordReaderDataSetIterator(
            rr, batch_size=args.batch, label_index=-1,
            num_classes=rr.num_labels())
    if args.csv:
        rr = CSVRecordReader(args.csv, skip_lines=args.skip_lines)
        return RecordReaderDataSetIterator(
            rr, batch_size=args.batch, label_index=args.label_index,
            num_classes=args.num_classes, regression=args.regression)
    raise SystemExit("provide one of --image-dir / --csv / --factory")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", required=True, help="output shard directory")
    p.add_argument("--image-dir", help="root/<label>/*.png image tree")
    p.add_argument("--height", type=int, default=224)
    p.add_argument("--width", type=int, default=224)
    p.add_argument("--channels", type=int, default=3)
    p.add_argument("--shuffle-seed", type=int, default=None,
                   help="permute image-record order at conversion time "
                        "(fit-time shard shuffling is batch-granular, so "
                        "record-level mixing must happen here)")
    p.add_argument("--csv", help="numeric CSV path")
    p.add_argument("--label-index", type=int, default=None)
    p.add_argument("--num-classes", type=int, default=None)
    p.add_argument("--regression", action="store_true")
    p.add_argument("--skip-lines", type=int, default=0)
    p.add_argument("--factory", metavar="MOD:FN",
                   help="module.path:callable returning a DataSetIterator")
    p.add_argument("--batch", type=int, default=256,
                   help="conversion read batch (not the training batch)")
    p.add_argument("--shard-records", type=int, default=4096)
    p.add_argument("--verify", action="store_true",
                   help="re-read the first batch and assert bitwise parity")
    args = p.parse_args(argv)

    # keep the conversion itself in-process and quiet: the one-shot pass
    # has no compute to overlap with
    os.environ.setdefault("DL4J_TPU_ETL_WORKERS", "0")
    os.environ.setdefault("DL4J_TPU_FIT_PREFETCH", "0")

    from deeplearning4j_tpu.data.shards import (
        ShardDataSetIterator, write_shards,
    )
    source = _build_source(args)
    index = write_shards(source, args.out,
                         shard_records=args.shard_records)
    summary = {
        "out": args.out,
        "n_records": index["n_records"],
        "shards": len(index["shards"]),
        "features": index["features"],
        "labels": index["labels"],
        "num_classes": index["num_classes"],
        "bytes": sum(os.path.getsize(os.path.join(args.out, s["file"]))
                     for s in index["shards"]),
    }
    if args.verify and not hasattr(source, "reset"):
        # the conversion drained the source and it cannot be rewound —
        # re-reading the first batch would raise StopIteration AFTER a
        # successful conversion
        print("make_shards: --verify skipped — the source (plain "
              "generator from --factory?) is not resettable",
              file=sys.stderr)
        summary["verified"] = "skipped: source not resettable"
    elif args.verify:
        first_src = next(iter(source))
        b = int(np.asarray(first_src.features).shape[0])
        first_new = next(iter(ShardDataSetIterator(args.out, batch_size=b,
                                                   drop_last=False)))
        np.testing.assert_array_equal(np.asarray(first_src.features),
                                      np.asarray(first_new.features))
        if first_src.labels is not None:
            np.testing.assert_array_equal(np.asarray(first_src.labels),
                                          np.asarray(first_new.labels))
        summary["verified"] = True
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
