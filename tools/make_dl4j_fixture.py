"""Generate the golden DL4J-format fixture zip + expected outputs.

Writes tests/fixtures/dl4j/mlp_mnistlike.zip in the REFERENCE's on-disk
format (ModelSerializer.java zip entries; Jackson WRAPPER_OBJECT layer JSON;
Nd4j binary coefficients in the reference flat param order) and an expected
forward output computed by an independent NumPy oracle — deliberately not by
the serializer under test, so test_golden_dl4j_fixture is a genuine
cross-implementation regression check.

Run once: python tools/make_dl4j_fixture.py
"""
import io
import json
import os
import struct
import sys
import zipfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures", "dl4j")


def write_utf(f, s):
    b = s.encode()
    f.write(struct.pack(">H", len(b)) + b)


def write_nd4j(f, arr):
    arr = np.asarray(arr, np.float32).reshape(1, -1)
    si = [2, 1, arr.size, arr.size, 1, 0, 1, ord("c")]
    write_utf(f, "DIRECT")
    f.write(struct.pack(">i", len(si)))
    write_utf(f, "INT")
    f.write(np.asarray(si, ">i4").tobytes())
    write_utf(f, "DIRECT")
    f.write(struct.pack(">i", arr.size))
    write_utf(f, "FLOAT")
    f.write(arr.astype(">f4").tobytes())


def main():
    os.makedirs(FIXDIR, exist_ok=True)
    rs = np.random.RandomState(20260730)
    nin, nh, nout = 16, 12, 5
    W1 = (rs.randn(nin, nh) * 0.3).astype(np.float32)
    b1 = (rs.randn(nh) * 0.1).astype(np.float32)
    W2 = (rs.randn(nh, nout) * 0.3).astype(np.float32)
    b2 = (rs.randn(nout) * 0.1).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2])

    act = "org.nd4j.linalg.activations.impl.Activation"
    conf = {
        "backprop": True, "backpropType": "Standard", "pretrain": False,
        "confs": [
            {"layer": {"dense": {
                "activationFn": {"@class": act + "ReLU"},
                "nin": nin, "nout": nh, "hasBias": True,
                "layerName": "dense0",
                "iUpdater": {"@class":
                             "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 1e-3, "beta1": 0.9,
                             "beta2": 0.999, "epsilon": 1e-8}}},
             "seed": 12345},
            {"layer": {"output": {
                "activationFn": {"@class": act + "Softmax"},
                "nin": nh, "nout": nout, "hasBias": True,
                "lossFn": {"@class":
                           "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "iUpdater": {"@class":
                             "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 1e-3, "beta1": 0.9,
                             "beta2": 0.999, "epsilon": 1e-8}}},
             "seed": 12345},
        ],
    }

    zpath = os.path.join(FIXDIR, "mlp_mnistlike.zip")
    with zipfile.ZipFile(zpath, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(conf, indent=2))
        buf = io.BytesIO()
        write_nd4j(buf, flat)
        zf.writestr("coefficients.bin", buf.getvalue())

    # independent oracle forward
    x = rs.randn(3, nin).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    e = np.exp(z - z.max(-1, keepdims=True))
    y = e / e.sum(-1, keepdims=True)
    with open(os.path.join(FIXDIR, "mlp_mnistlike_expected.json"), "w") as f:
        json.dump({"input": x.tolist(), "output": y.tolist()}, f)
    print("wrote", zpath)


if __name__ == "__main__":
    sys.exit(main())
