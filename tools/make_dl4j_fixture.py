"""Generate the golden DL4J-format fixture zip + expected outputs.

Writes tests/fixtures/dl4j/mlp_mnistlike.zip in the REFERENCE's on-disk
format (ModelSerializer.java zip entries; Jackson WRAPPER_OBJECT layer JSON;
Nd4j binary coefficients in the reference flat param order) and an expected
forward output computed by an independent NumPy oracle — deliberately not by
the serializer under test, so test_golden_dl4j_fixture is a genuine
cross-implementation regression check.

Run once: python tools/make_dl4j_fixture.py
"""
import io
import json
import os
import struct
import zipfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures", "dl4j")


def write_utf(f, s):
    b = s.encode()
    f.write(struct.pack(">H", len(b)) + b)


def write_nd4j(f, arr):
    arr = np.asarray(arr, np.float32).reshape(1, -1)
    si = [2, 1, arr.size, arr.size, 1, 0, 1, ord("c")]
    write_utf(f, "DIRECT")
    f.write(struct.pack(">i", len(si)))
    write_utf(f, "INT")
    f.write(np.asarray(si, ">i4").tobytes())
    write_utf(f, "DIRECT")
    f.write(struct.pack(">i", arr.size))
    write_utf(f, "FLOAT")
    f.write(arr.astype(">f4").tobytes())


def main():
    os.makedirs(FIXDIR, exist_ok=True)
    rs = np.random.RandomState(20260730)
    nin, nh, nout = 16, 12, 5
    W1 = (rs.randn(nin, nh) * 0.3).astype(np.float32)
    b1 = (rs.randn(nh) * 0.1).astype(np.float32)
    W2 = (rs.randn(nh, nout) * 0.3).astype(np.float32)
    b2 = (rs.randn(nout) * 0.1).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2])

    act = "org.nd4j.linalg.activations.impl.Activation"
    conf = {
        "backprop": True, "backpropType": "Standard", "pretrain": False,
        "confs": [
            {"layer": {"dense": {
                "activationFn": {"@class": act + "ReLU"},
                "nin": nin, "nout": nh, "hasBias": True,
                "layerName": "dense0",
                "iUpdater": {"@class":
                             "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 1e-3, "beta1": 0.9,
                             "beta2": 0.999, "epsilon": 1e-8}}},
             "seed": 12345},
            {"layer": {"output": {
                "activationFn": {"@class": act + "Softmax"},
                "nin": nh, "nout": nout, "hasBias": True,
                "lossFn": {"@class":
                           "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"},
                "iUpdater": {"@class":
                             "org.nd4j.linalg.learning.config.Adam",
                             "learningRate": 1e-3, "beta1": 0.9,
                             "beta2": 0.999, "epsilon": 1e-8}}},
             "seed": 12345},
        ],
    }

    zpath = os.path.join(FIXDIR, "mlp_mnistlike.zip")
    with zipfile.ZipFile(zpath, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(_entry("configuration.json"),
                    json.dumps(conf, indent=2))
        buf = io.BytesIO()
        write_nd4j(buf, flat)
        zf.writestr(_entry("coefficients.bin"), buf.getvalue())

    # independent oracle forward
    x = rs.randn(3, nin).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    e = np.exp(z - z.max(-1, keepdims=True))
    y = e / e.sum(-1, keepdims=True)
    with open(os.path.join(FIXDIR, "mlp_mnistlike_expected.json"), "w") as f:
        json.dump({"input": x.tolist(), "output": y.tolist()}, f)
    print("wrote", zpath)


def _softmax(z):
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


ACT = "org.nd4j.linalg.activations.impl.Activation"
MCXENT = {"@class": "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}
ADAM = {"@class": "org.nd4j.linalg.learning.config.Adam",
        "learningRate": 1e-3, "beta1": 0.9, "beta2": 0.999,
        "epsilon": 1e-8}


FIXED_STAMP = (2026, 1, 1, 0, 0, 0)   # byte-deterministic regeneration


def _entry(name):
    # writestr(ZipInfo, ...) takes the compression from the ZipInfo, NOT
    # the archive default — set it explicitly or entries come out STORED
    zi = zipfile.ZipInfo(name, date_time=FIXED_STAMP)
    zi.compress_type = zipfile.ZIP_DEFLATED
    return zi


def _zip_model(name, confs, flat):
    conf = {"backprop": True, "backpropType": "Standard", "pretrain": False,
            "confs": confs}
    zpath = os.path.join(FIXDIR, name)
    with zipfile.ZipFile(zpath, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(_entry("configuration.json"),
                    json.dumps(conf, indent=2))
        buf = io.BytesIO()
        write_nd4j(buf, flat)
        zf.writestr(_entry("coefficients.bin"), buf.getvalue())
    print("wrote", zpath)


def _conf(kind, body):
    body = dict(body)
    body["iUpdater"] = ADAM
    return {"layer": {kind: body}, "seed": 12345}


def make_cnn():
    """conv(1->4,3x3) relu -> maxpool 2x2 -> softmax(10) on 10x10x1,
    reference NCHW layout; oracle = NumPy loops, NOT the importer."""
    rs = np.random.RandomState(20260731)
    Wc = (rs.randn(4, 1, 3, 3) * 0.4).astype(np.float32)   # (O,I,kh,kw)
    bc = (rs.randn(4) * 0.1).astype(np.float32)
    # 10x10 conv-valid -> 8x8, pool -> 4x4; flatten NCHW = 4*4*4 = 64
    Wd = (rs.randn(64, 10) * 0.2).astype(np.float32)
    bd = (rs.randn(10) * 0.1).astype(np.float32)
    flat = np.concatenate([bc, Wc.ravel(order="C"),
                           Wd.ravel(order="F"), bd])
    confs = [
        _conf("convolution", {"activationFn": {"@class": ACT + "ReLU"},
                              "nin": 1, "nout": 4, "kernelSize": [3, 3],
                              "stride": [1, 1], "padding": [0, 0],
                              "convolutionMode": "Truncate",
                              "hasBias": True}),
        _conf("subsampling", {"kernelSize": [2, 2], "stride": [2, 2],
                              "padding": [0, 0], "poolingType": "MAX",
                              "convolutionMode": "Truncate"}),
        _conf("output", {"activationFn": {"@class": ACT + "Softmax"},
                         "nin": 64, "nout": 10, "hasBias": True,
                         "lossFn": MCXENT}),
    ]
    _zip_model("cnn_mnistlike.zip", confs, flat)

    x = rs.randn(2, 1, 10, 10).astype(np.float32)          # NCHW
    B = x.shape[0]
    h = np.zeros((B, 4, 8, 8), np.float32)
    for i in range(8):
        for j in range(8):
            patch = x[:, :, i:i + 3, j:j + 3]
            h[:, :, i, j] = np.einsum("bchw,ochw->bo", patch, Wc)
    h = np.maximum(h + bc[None, :, None, None], 0)
    p = np.zeros((B, 4, 4, 4), np.float32)
    for i in range(4):
        for j in range(4):
            p[:, :, i, j] = h[:, :, 2 * i:2 * i + 2,
                              2 * j:2 * j + 2].max((2, 3))
    y = _softmax(p.reshape(B, -1) @ Wd + bd)
    with open(os.path.join(FIXDIR, "cnn_mnistlike_expected.json"),
              "w") as f:
        json.dump({"input_nchw": x.tolist(), "output": y.tolist()}, f)


def make_lstm():
    """LSTM(3->5) -> rnnoutput softmax(2); reference IFOG gate order."""
    rs = np.random.RandomState(20260732)
    nin, H = 3, 5
    W = (rs.randn(nin, 4 * H) * 0.4).astype(np.float32)
    R = (rs.randn(H, 4 * H) * 0.4).astype(np.float32)
    b = (rs.randn(4 * H) * 0.1).astype(np.float32)
    Wo = (rs.randn(H, 2) * 0.4).astype(np.float32)
    bo = (rs.randn(2) * 0.1).astype(np.float32)
    flat = np.concatenate([W.ravel(order="F"), R.ravel(order="F"), b,
                           Wo.ravel(order="F"), bo])
    confs = [
        _conf("LSTM", {"activationFn": {"@class": ACT + "TanH"},
                       "nin": nin, "nout": H,
                       "gateActivationFn": {"@class": ACT + "Sigmoid"},
                       "forgetGateBiasInit": 1.0}),
        _conf("rnnoutput", {"activationFn": {"@class": ACT + "Softmax"},
                            "nin": H, "nout": 2, "lossFn": MCXENT}),
    ]
    _zip_model("lstm_chars.zip", confs, flat)

    x = rs.randn(2, 6, nin).astype(np.float32)     # (B, T, F)

    def sig(z):
        return 1.0 / (1.0 + np.exp(-z))

    B, T, _ = x.shape
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    hs = np.zeros((B, T, H), np.float32)
    for t in range(T):
        z = x[:, t] @ W + h @ R + b
        i = sig(z[:, :H])
        f = sig(z[:, H:2 * H])
        o = sig(z[:, 2 * H:3 * H])            # reference IFOG block order
        g = np.tanh(z[:, 3 * H:])
        c = f * c + i * g
        h = o * np.tanh(c)
        hs[:, t] = h
    y = _softmax(hs @ Wo + bo)
    with open(os.path.join(FIXDIR, "lstm_chars_expected.json"), "w") as f:
        json.dump({"input": x.tolist(), "output": y.tolist()}, f)


if __name__ == "__main__":
    main()
    make_cnn()
    make_lstm()
