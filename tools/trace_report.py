#!/usr/bin/env python
"""Merge per-process trace segments into one Perfetto-loadable file.

A fleet request crosses three crash domains — router, subprocess
replica, batcher/decode scheduler — and each process saves its own
Chrome trace-event JSON (`monitor.save_trace`; the serving CLI's
``--trace-out`` threads per-replica paths automatically:
``PATH`` for the router, ``PATH-stem.replica-N.json`` per replica).
This tool stitches those segments into ONE trace with named process
tracks, so a single ``trace_id`` reads top-to-bottom in ui.perfetto.dev:

    python tools/trace_report.py --out merged.json \
        /tmp/fleet.json /tmp/fleet.replica-0.json /tmp/fleet.replica-1.json

Inputs are paths or ``LABEL=path`` pairs (the label becomes the Perfetto
process name; default: the file's basename). Colliding pids across
files (container restarts, pid reuse) are remapped to keep every
process on its own track.

``--trace-id <hex>`` additionally prints that request's spans — per
process, in time order, with durations — and restricts the merged file
to the request's events plus track metadata: the "histogram exemplar ->
concrete trace" hop of the runbook in docs/OBSERVABILITY.md.

Exit 0 on success; 2 for unreadable/invalid inputs (a typo'd CI
invocation must not read as green).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_segment(path: str) -> List[dict]:
    """One trace file -> its event list. Accepts both the object form
    ({"traceEvents": [...]}) monitor.save_trace writes and a bare JSON
    array of events."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError(f"{path}: no traceEvents array")
        return events
    if isinstance(doc, list):
        return doc
    raise ValueError(f"{path}: not a Chrome trace-event document")


def merge_trace_segments(segments: List[Tuple[str, List[dict]]]) -> dict:
    """[(label, events)] -> one merged trace document. Each segment's
    pids get a process_name metadata track; a pid already claimed by an
    earlier segment is remapped (offset past the max seen) so two
    processes never share a track."""
    merged: List[dict] = []
    used_pids: set = set()
    max_pid = 0
    for label, events in segments:
        pids = {e.get("pid", 0) for e in events}
        remap: Dict[int, int] = {}
        for pid in sorted(pids):
            if pid in used_pids:
                max_pid += 1
                while max_pid in used_pids:
                    max_pid += 1
                remap[pid] = max_pid
            else:
                remap[pid] = pid
            used_pids.add(remap[pid])
            max_pid = max(max_pid, remap[pid])
        named = set()
        for e in events:
            pid = remap.get(e.get("pid", 0), e.get("pid", 0))
            if e.get("ph") == "M" and e.get("name") == "process_name":
                named.add(pid)
            e = dict(e)
            e["pid"] = pid
            merged.append(e)
        for pid in sorted(remap.values()):
            if pid not in named:
                merged.append({"name": "process_name", "ph": "M",
                               "pid": pid, "args": {"name": label}})
    return {"traceEvents": merged, "displayTimeUnit": "ms"}


def merge_trace_files(inputs) -> dict:
    """Paths or (label, path) pairs -> merged trace document."""
    segments = []
    for item in inputs:
        if isinstance(item, tuple):
            label, path = item
        else:
            label, path = None, item
        if label is None:
            label = os.path.splitext(os.path.basename(path))[0]
        segments.append((label, load_segment(path)))
    return merge_trace_segments(segments)


def events_for_trace(doc: dict, trace_id: str) -> List[dict]:
    """The merged doc's complete-span events carrying `trace_id`."""
    return [e for e in doc["traceEvents"]
            if e.get("ph") == "X"
            and (e.get("args") or {}).get("trace_id") == trace_id]


def filter_to_trace(doc: dict, trace_id: str) -> dict:
    """Merged doc restricted to one request: its events + the metadata
    tracks they live on (still a valid, loadable trace)."""
    keep = events_for_trace(doc, trace_id)
    keep += [e for e in doc["traceEvents"]
             if e.get("ph") == "i"
             and (e.get("args") or {}).get("trace_id") == trace_id]
    pids = {e["pid"] for e in keep}
    meta = [e for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("pid") in pids]
    return {"traceEvents": meta + keep, "displayTimeUnit": "ms"}


def print_trace_summary(doc: dict, trace_id: str, out=sys.stdout):
    pnames = {e["pid"]: e["args"]["name"]
              for e in doc["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    spans = events_for_trace(doc, trace_id)
    print(f"trace {trace_id}: {len(spans)} spans across "
          f"{len({e['pid'] for e in spans})} process(es)", file=out)
    for e in sorted(spans, key=lambda e: (e["pid"], e["ts"])):
        proc = pnames.get(e["pid"], str(e["pid"]))
        print(f"  {proc:<24} {e['name']:<28} "
              f"{e.get('dur', 0) / 1e3:9.3f} ms", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("inputs", nargs="+", metavar="PATH|LABEL=PATH",
                   help="per-process trace files (monitor.save_trace "
                        "output); LABEL= names the Perfetto process "
                        "track")
    p.add_argument("--out", default=None,
                   help="merged trace path (default: print a summary "
                        "only)")
    p.add_argument("--trace-id", default=None,
                   help="print one request's cross-process span "
                        "timeline and restrict --out to it")
    args = p.parse_args(argv)

    pairs = []
    for item in args.inputs:
        label, sep, path = item.partition("=")
        pairs.append((label, path) if sep else (None, item))
    missing = [path for _, path in pairs if not os.path.isfile(path)]
    if missing:
        print(f"trace_report: no such input file(s): {missing}",
              file=sys.stderr)
        return 2
    try:
        doc = merge_trace_files(pairs)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace_report: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    procs = {e["pid"] for e in doc["traceEvents"]}
    trace_ids = {(e.get("args") or {}).get("trace_id")
                 for e in spans} - {None}
    print(f"merged {len(pairs)} segment(s): {len(spans)} spans, "
          f"{len(procs)} process track(s), "
          f"{len(trace_ids)} distinct trace_id(s)")

    out_doc = doc
    if args.trace_id:
        print_trace_summary(doc, args.trace_id)
        out_doc = filter_to_trace(doc, args.trace_id)
        if not events_for_trace(out_doc, args.trace_id):
            print(f"trace_report: trace_id {args.trace_id!r} not found "
                  "in any segment", file=sys.stderr)
            return 2
    if args.out:
        tmp = f"{args.out}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(out_doc, f)
        os.replace(tmp, args.out)
        print(f"wrote {args.out} "
              f"({len(out_doc['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
