#!/usr/bin/env python
"""Serving smoke: the acceptance gate for the model-serving subsystem.

    JAX_PLATFORMS=cpu python tools/serve_smoke.py

In one process (CI-friendly, CPU, no network egress):

1. builds a zoo LeNet, saves v1/v2 checkpoints (different seeds), deploys
   v1 behind a ModelServer with a {1, 8} bucket ladder (AOT-warmed);
2. fires >= 200 closed-loop HTTP predict requests from worker threads
   while the driver hot-swaps to v2 and then rolls back to v1
   MID-TRAFFIC — asserts ZERO failed requests (the zero-downtime
   contract) and that responses flipped versions;
3. scrapes /metrics and asserts the compile ledger shows every XLA
   compile happened in warmup (`serving_bucket_compiles_total` summed ==
   `serving_warmup_runs_total` summed), i.e. each bucket compiled at most
   once per model generation and never on the request path;
4. probes admission control: a saturated queue must yield 429 and an
   already-expired deadline 504 — clean JSON errors, never a 500.

Exit code 0 on success, 1 on failure; prints a JSON summary either way.
"""
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402

REQUESTS = 240
WORKERS = 6
BUCKETS = (1, 8)


def _post(url, body, timeout=60):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    r = urllib.request.urlopen(req, timeout=timeout)
    return r.status, json.loads(r.read())


def main() -> int:
    from deeplearning4j_tpu.models.zoo import LeNet
    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
    from deeplearning4j_tpu.util.serialization import save_model

    failures = []
    summary = {}
    tmp = tempfile.mkdtemp(prefix="serve_smoke_")
    v1_path = os.path.join(tmp, "lenet_v1.zip")
    v2_path = os.path.join(tmp, "lenet_v2.zip")
    save_model(LeNet(seed=1).init(), v1_path)
    save_model(LeNet(seed=2).init(), v2_path)

    registry = ModelRegistry()
    t0 = time.perf_counter()
    served = registry.deploy("lenet", v1_path, buckets=BUCKETS,
                             max_delay_ms=3.0, queue_limit=64)
    summary["warmup_s"] = round(time.perf_counter() - t0, 2)
    server = ModelServer(registry, port=0, default_deadline_s=120.0)
    base = server.url
    predict_url = f"{base}/v1/models/lenet/predict"

    rs = np.random.RandomState(0)
    bodies = [json.dumps({"inputs": rs.rand(b, 28, 28, 1).astype(
        "float32").tolist()}).encode() for b in (1, 2, 4, 8)]

    codes = {}
    versions_seen = set()
    lock = threading.Lock()
    counter = iter(range(REQUESTS))

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            try:
                code, out = _post(predict_url, bodies[i % len(bodies)])
                ver = out.get("version")
            except urllib.error.HTTPError as e:
                code, ver = e.code, None
                e.read()
            except Exception as e:  # noqa: BLE001
                code, ver = f"exc:{type(e).__name__}", None
            with lock:
                codes[code] = codes.get(code, 0) + 1
                if ver is not None:
                    versions_seen.add(ver)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"smoke-worker-{w}")
               for w in range(WORKERS)]
    for t in threads:
        t.start()

    # mid-traffic: hot-swap to v2, then one-step rollback to v1 — both
    # warm off-path, so concurrent requests must all succeed
    time.sleep(0.5)
    scode, _ = _post(f"{base}/v1/models/lenet/swap",
                     json.dumps({"source": v2_path}).encode(), timeout=300)
    if scode != 200:
        failures.append(f"swap returned {scode}")
    time.sleep(0.5)
    rcode, _ = _post(f"{base}/v1/models/lenet/rollback", b"{}", timeout=300)
    if rcode != 200:
        failures.append(f"rollback returned {rcode}")
    for t in threads:
        t.join(timeout=600)

    summary["codes"] = {str(k): v for k, v in sorted(codes.items(),
                                                     key=lambda kv: str(kv))}
    summary["versions_seen"] = sorted(versions_seen)
    if codes.get(200, 0) != REQUESTS:
        failures.append(f"expected {REQUESTS} x 200 through swap+rollback, "
                        f"got {summary['codes']}")
    if 2 not in versions_seen:
        failures.append("no response ever reported v2 — swap not observed "
                        "under traffic")

    # ---- compile ledger: every compile was a warmup, never a request ----
    metrics = urllib.request.urlopen(f"{base}/metrics", timeout=10
                                     ).read().decode()
    def _total(prefix):
        tot = 0.0
        for line in metrics.splitlines():
            if line.startswith(prefix) and not line.startswith("# "):
                tot += float(line.rsplit(" ", 1)[1])
        return tot
    compiles = _total("serving_bucket_compiles_total")
    warmups = _total("serving_warmup_runs_total")
    summary["bucket_compiles"] = compiles
    summary["warmup_runs"] = warmups
    # 3 generations (deploy, swap, rollback) x len(BUCKETS) buckets
    if compiles != warmups or compiles != 3 * len(BUCKETS):
        failures.append(
            f"compile ledger: {compiles} compiles vs {warmups} warmup runs "
            f"(expected both == {3 * len(BUCKETS)}: every bucket compiled "
            "exactly once per generation, all in warmup)")
    for fam in ("serving_requests_total", "serving_request_seconds",
                "serving_batch_size", "serving_queue_depth"):
        if fam not in metrics:
            failures.append(f"/metrics missing {fam}")

    # ---- admission control: expired deadline -> 504, never a 500 --------
    try:
        _post(f"{predict_url}?deadline_ms=0.001", bodies[-1])
        failures.append("deadline_ms=0.001 did not fail")
    except urllib.error.HTTPError as e:
        e.read()
        summary["deadline_code"] = e.code
        if e.code != 504:
            failures.append(f"expired deadline returned {e.code}, want 504")

    # ---- admission control: saturated queue -> 429 ----------------------
    # stall the batcher worker with a slow runner, fill the queue past its
    # bound, and require an explicit 429 (bounded queue = backpressure)
    real_runner = served.batcher.runner
    served.batcher.runner = lambda x: (time.sleep(0.4), real_runner(x))[1]
    got_429 = 0
    try:
        def _stall():
            try:
                _post(predict_url, bodies[-1])
            except Exception:               # noqa: BLE001 — sacrificial
                pass                        # stall request; outcome unused

        stalled = [threading.Thread(target=_stall, daemon=True,
                                    name=f"smoke-stall-{s}")
                   for s in range(4)]
        for t in stalled:
            t.start()
        time.sleep(0.1)
        for _ in range(served.batcher._queue.maxsize + 8):
            try:
                served.batcher.predict(np.zeros((8, 28, 28, 1), "float32"),
                                       deadline=None, timeout=0.001)
            except Exception as e:  # noqa: BLE001
                if type(e).__name__ == "ServerOverloadedError":
                    got_429 += 1
        for t in stalled:
            t.join(timeout=60)
    finally:
        served.batcher.runner = real_runner
    summary["queue_full_rejections"] = got_429
    if got_429 == 0:
        failures.append("saturating the queue never raised overload (429)")

    server.drain(timeout=30)
    summary["ok"] = not failures
    summary["failures"] = failures
    print(json.dumps(summary, indent=1))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
