"""First-contact smoke: Pallas flash fwd+bwd COMPILED on real TPU.

Checks numeric parity vs the dense XLA path at several shapes/dtypes,
including the masked + non-causal + return_lse variants the framework
uses, and times fwd and fwd+bwd. Exits nonzero on any parity failure.
"""
import os
import sys
import time
import traceback

# keep jax-internal frames: Mosaic/BlockSpec root causes live there
os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.ops import flash_attention

assert jax.devices()[0].platform != "cpu", "need TPU"
print("device:", jax.devices()[0], flush=True)

failures = []
_tb_dumped = [False]


def _dump_tb_once():
    """Full (trimmed) traceback for the FIRST failure — the bench error
    row only carries the last stderr lines, which for Mosaic/BlockSpec
    errors is just the docs link; the root cause is mid-traceback."""
    if not _tb_dumped[0]:
        _tb_dumped[0] = True
        tb = traceback.format_exc()
        print("---- first failure traceback (trimmed) ----", flush=True)
        print(tb[-4000:], flush=True)
        print("-------------------------------------------", flush=True)


def check(name, b, t, h, d, dtype, causal, masked, bq=None, bk=None):
    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(b, t, h, d), dtype) for _ in range(3)]
    mask = None
    if masked:
        m = np.ones((b, t), np.float32)
        m[:, t - t // 4:] = 0.0
        mask = jnp.asarray(m)

    dense = jax.jit(lambda q, k, v: dot_product_attention(
        q, k, v, mask=mask, causal=causal))
    flash = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, mask=mask, causal=causal, block_q=bq, block_k=bk,
        interpret=False))
    try:
        t0 = time.perf_counter()
        of = flash(q, k, v)
        of.block_until_ready()
        compile_s = time.perf_counter() - t0
        od = dense(q, k, v)
        err = float(jnp.max(jnp.abs(of.astype(jnp.float32)
                                    - od.astype(jnp.float32))))
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        ok = err < tol
        # timing best-of-3
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            of = flash(q, k, v)
            of.block_until_ready()
            el = time.perf_counter() - t0
            best = el if best is None else min(best, el)
        bestd = None
        for _ in range(3):
            t0 = time.perf_counter()
            od = dense(q, k, v)
            od.block_until_ready()
            el = time.perf_counter() - t0
            bestd = el if bestd is None else min(bestd, el)
        print(f"FWD {name}: err={err:.2e} {'OK' if ok else 'FAIL'} "
              f"flash={best*1e3:.2f}ms dense={bestd*1e3:.2f}ms "
              f"speedup={bestd/best:.2f}x (compile {compile_s:.1f}s)",
              flush=True)
        if not ok:
            failures.append(name)
    except Exception as e:
        print(f"FWD {name}: EXC {type(e).__name__}: {str(e)[:300]}",
              flush=True)
        _dump_tb_once()
        failures.append(name)


def check_bwd(name, b, t, h, d, dtype, causal):
    rs = np.random.RandomState(1)
    q, k, v = [jnp.asarray(rs.randn(b, t, h, d), dtype) for _ in range(3)]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=False).astype(jnp.float32)
                       ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(
            q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))
    try:
        t0 = time.perf_counter()
        dqf, dkf, dvf = gf(q, k, v)
        jax.block_until_ready((dqf, dkf, dvf))
        compile_s = time.perf_counter() - t0
        dqd, dkd, dvd = gd(q, k, v)
        errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                      - b_.astype(jnp.float32))))
                for a, b_ in ((dqf, dqd), (dkf, dkd), (dvf, dvd))]
        scale = float(jnp.max(jnp.abs(dqd.astype(jnp.float32)))) + 1e-6
        tol = (0.15 if dtype == jnp.bfloat16 else 1e-3) * scale
        ok = max(errs) < tol
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            out = gf(q, k, v)
            jax.block_until_ready(out)
            el = time.perf_counter() - t0
            best = el if best is None else min(best, el)
        bestd = None
        for _ in range(3):
            t0 = time.perf_counter()
            out = gd(q, k, v)
            jax.block_until_ready(out)
            el = time.perf_counter() - t0
            bestd = el if bestd is None else min(bestd, el)
        print(f"BWD {name}: errs={[f'{e:.2e}' for e in errs]} tol={tol:.2e} "
              f"{'OK' if ok else 'FAIL'} flash={best*1e3:.2f}ms "
              f"dense={bestd*1e3:.2f}ms speedup={bestd/best:.2f}x "
              f"(compile {compile_s:.1f}s)", flush=True)
        if not ok:
            failures.append(name)
    except Exception as e:
        print(f"BWD {name}: EXC {type(e).__name__}: {str(e)[:300]}",
              flush=True)
        _dump_tb_once()
        failures.append(name)


# the shapes the framework actually uses: transformer blocks + micro-bench
check("b4 t2048 h8 d64 bf16 causal", 4, 2048, 8, 64, jnp.bfloat16, True,
      False)
check("b4 t2048 h8 d64 bf16 full", 4, 2048, 8, 64, jnp.bfloat16, False,
      False)
check("b2 t1024 h8 d128 bf16 causal", 2, 1024, 8, 128, jnp.bfloat16, True,
      False)
check("b2 t512 h4 d64 f32 masked", 2, 512, 4, 64, jnp.float32, False, True)
check("b2 t300 h8 d64 bf16 causal pad", 2, 300, 8, 64, jnp.bfloat16, True,
      False)  # t not a multiple of 128 -> exercises the padding path
check("b1 t8192 h8 d64 bf16 causal", 1, 8192, 8, 64, jnp.bfloat16, True,
      False)
check("blockq64 t2048 bf16", 4, 2048, 8, 64, jnp.bfloat16, True, False,
      bq=64, bk=64)
check("blockq256 t2048 bf16", 4, 2048, 8, 64, jnp.bfloat16, True, False,
      bq=256, bk=256)
check_bwd("b4 t2048 h8 d64 bf16 causal", 4, 2048, 8, 64, jnp.bfloat16, True)
check_bwd("b2 t1024 h8 d64 f32 full", 2, 1024, 8, 64, jnp.float32, False)
check_bwd("b1 t4096 h8 d64 bf16 causal", 1, 4096, 8, 64, jnp.bfloat16, True)
check_bwd("b2 t300 h8 d64 bf16 causal pad", 2, 300, 8, 64, jnp.bfloat16,
          True)   # t % 128 != 0 -> padding path through the backward too

# return_lse path (the ring-flash composition residual)
try:
    rs = np.random.RandomState(2)
    q, k, v = [jnp.asarray(rs.randn(2, 1024, 8, 64), jnp.bfloat16)
               for _ in range(3)]
    f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=False, return_lse=True, interpret=False))
    out, lse = f(q, k, v)
    # merge two half-key shards via the documented rule == full attention
    k1, k2 = k[:, :512], k[:, 512:]
    v1, v2 = v[:, :512], v[:, 512:]
    o1, l1 = f(q, k1, v1)
    o2, l2 = f(q, k2, v2)
    l1f, l2f = l1.astype(jnp.float32), l2.astype(jnp.float32)
    m = jnp.maximum(l1f, l2f)
    w1 = jnp.exp(l1f - m)[..., None]
    w2 = jnp.exp(l2f - m)[..., None]
    merged = (w1 * o1.astype(jnp.float32) + w2 * o2.astype(jnp.float32)) \
        / (w1 + w2)
    err = float(jnp.max(jnp.abs(merged - out.astype(jnp.float32))))
    ok = err < 2e-2
    print(f"LSE-merge: err={err:.2e} {'OK' if ok else 'FAIL'}", flush=True)
    if not ok:
        failures.append("lse-merge")
except Exception as e:
    print(f"LSE-merge: EXC {type(e).__name__}: {str(e)[:300]}", flush=True)
    _dump_tb_once()
    failures.append("lse-merge")

print("FAILURES:", failures, flush=True)
sys.exit(1 if failures else 0)
