#!/usr/bin/env python
"""Chaos smoke: a short fit under randomized injected faults must still
converge. Usable locally and from CI:

    JAX_PLATFORMS=cpu python tools/chaos_fit.py --seed 3

Builds a small classifier on deterministic synthetic blobs, derives a
randomized-but-seeded fault schedule (NaN steps, transient errors, one
mid-run crash, one preemption), runs it through ResilientTrainer in a
crash/resume sequence, and asserts:

- every run survives its faults (skips + retries, no unhandled error),
- the killed-and-resumed sequence reaches bitwise-identical params to a
  clean uninterrupted run,
- the final loss improves on the initial loss (training actually worked).

Exit code 0 on success, 1 on failure; prints a JSON summary either way.
"""
import argparse
import json
import os
import random
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402


def _blobs(n=240, d=8, k=3, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    return X, Y


def _net(seed):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(2e-2))
            .list()
            .layer(DenseLayer(n_out=24, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--seed", type=int, default=0,
                   help="seeds the fault schedule AND the model")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=24)
    p.add_argument("--checkpoint-dir", default=None,
                   help="default: a fresh temp dir")
    args = p.parse_args(argv)

    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.train.resilience import (
        FaultPolicy, ResilientTrainer,
    )
    from deeplearning4j_tpu.util.faults import FaultInjector, SimulatedCrash

    X, Y = _blobs(seed=args.seed)
    steps_per_epoch = len(X) // args.batch_size
    total = steps_per_epoch * args.epochs
    data = lambda: ArrayDataSetIterator(X, Y, batch_size=args.batch_size)
    policy = FaultPolicy(backoff_base=0.001, backoff_max=0.01,
                         max_consecutive_skips=4)

    # randomized (seeded) schedule over the middle of the run: faults at
    # the edges are covered by the unit tests; the smoke wants overlap
    rng = random.Random(args.seed)
    pool = list(range(1, total - 1))
    rng.shuffle(pool)
    nan_at = sorted(pool[:3])
    transient_at = sorted(pool[3:6])
    crash_at = pool[6]
    summary = {"seed": args.seed, "total_steps": total, "nan_at": nan_at,
               "transient_at": transient_at, "crash_at": crash_at}

    ckdir = args.checkpoint_dir or tempfile.mkdtemp(prefix="chaos_fit_")
    refdir = tempfile.mkdtemp(prefix="chaos_ref_")
    failures = []
    try:
        from deeplearning4j_tpu.data.dataset import DataSet

        # reference: same fault schedule minus the crash, uninterrupted
        ref = _net(args.seed)
        initial = float(ref.score(DataSet(X, Y)))
        rep_ref = ResilientTrainer(
            ref, refdir, save_every_n_iterations=10_000, policy=policy,
            injector=FaultInjector(nan_at=nan_at, transient_at=transient_at)
        ).fit(data(), epochs=args.epochs)
        summary["ref"] = {"skipped": rep_ref.skipped_steps,
                          "retries": rep_ref.retries,
                          "score": rep_ref.final_score}

        # chaos run: same schedule PLUS a hard crash, then auto-resume
        net = _net(args.seed)
        try:
            ResilientTrainer(
                net, ckdir, save_every_n_iterations=2, policy=policy,
                injector=FaultInjector(nan_at=nan_at,
                                       transient_at=transient_at,
                                       crash_at=crash_at)
            ).fit(data(), epochs=args.epochs)
            failures.append("crash did not fire")
        except SimulatedCrash:
            pass
        resumed = _net(args.seed)
        rep = ResilientTrainer(
            resumed, ckdir, save_every_n_iterations=2, policy=policy,
            injector=FaultInjector(nan_at=nan_at, transient_at=transient_at)
        ).fit(data(), epochs=args.epochs)
        summary["resumed"] = {"resumed_from": rep.resumed_from,
                              "skipped": rep.skipped_steps,
                              "retries": rep.retries,
                              "score": rep.final_score}

        final = rep.final_score
        if rep.resumed_from is None:
            failures.append("resume did not engage")
        if not np.array_equal(np.asarray(ref.params_flat()),
                              np.asarray(resumed.params_flat())):
            failures.append("crash+resume params != uninterrupted params")
        if not np.isfinite(np.asarray(resumed.params_flat())).all():
            failures.append("non-finite params after chaos run")
        if not (final is not None and np.isfinite(final)
                and final < initial):
            failures.append(
                f"did not converge: initial {initial} -> final {final}")
        summary["initial_score"] = initial
    except Exception as e:  # noqa: BLE001 - smoke must report, not die
        failures.append(f"{type(e).__name__}: {e}")

    # attributable CI record: the run's full telemetry (skips, retries,
    # checkpoint IO, step timings) rides along in the summary JSON
    from deeplearning4j_tpu import monitor
    summary["metrics"] = monitor.summary()
    summary["failures"] = failures
    summary["ok"] = not failures
    print(json.dumps(summary, indent=1))
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
