#!/usr/bin/env python
"""Chaos SLO gate: the serving fleet must hold its contract under faults.

    JAX_PLATFORMS=cpu python tools/serve_chaos.py

The fleet-scope counterpart of tools/chaos_fit.py: stands up a REAL fleet
(3 subprocess serving replicas — each its own OS process and XLA runtime —
behind the ReplicaSupervisor + ResilientRouter), drives closed-loop
priority-tagged traffic through the router, and mid-traffic:

1. **SIGKILLs one replica** (machine-loss analog: no drain, no goodbye);
2. **wedges another** via its fault endpoint (`POST /v1/faults` with
   ``probe_delay_s`` + ``predict_delay_s`` — alive process, dead service:
   probes and predicts hang past every deadline).

The SLO asserted from the traffic log and the router's /metrics:

- **zero 5xx**: every response is 200 or explicit backpressure (429
  shed / 503 no-backend) — faults never surface as server errors;
- the killed AND the wedged replica are **restarted and rejoin** (state
  ready, generation bumped) within the recovery budget, proven by
  ``serving_fleet_restarts_total`` and live /readyz;
- the breaker state gauge and per-class shed counters are exposed, and
  shedding hit the LOW class (`serving_router_shed_total{cls="batch"}`);
- **post-fault p99 recovers** to within a CI-noise multiple of the
  pre-fault baseline;
- the **SLO engine pages on the wedge**: a fast-burn availability
  alert (monitor/slo.py over the in-process time-series ring, windows
  scaled to drill seconds) fires while the fleet is degraded — tripping
  a ``slo_availability_burn`` flight postmortem with request evidence —
  and resolves once traffic is clean again; the alert timeline is
  banked in the report and the router's /v1/slo fleet verdict returns
  to ``ok``.

After the predict fleet winds down, a second **disaggregation drill**
stands up a prefill/decode split LM fleet (subprocess replicas with
``kv_role`` prefill vs decode, router orchestrating KV-page transfers)
and SIGKILLs the prefill replica while transfers are the serving path:

- streams before the kill must ride completed transfers (the
  ``serving_transfer_orchestrations_total`` proof) with greedy output
  exactly equal across repeats;
- the kill must trip the router's mid-transfer failover
  (``serving_transfer_failovers_total``) — the stream falls back to
  local prefill on the decode replica, the client sees 200s throughout
  (**zero 5xx**), and a ``transfer_peer_lost`` flight postmortem names
  the dead peer.

Prints a JSON report (with a bench-style "sweep" row carrying
``chaos_p99_under_fault_ms`` / ``chaos_goodput_under_fault_rps`` /
``chaos_recovered_p99_ms`` plus the disaggregation-drill row, banked
via --out as CHAOS_r*.json for tools/perf_report.py's regression
gate). Exit 0 iff every SLO held.
"""
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from urllib.error import HTTPError

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

N_IN, N_OUT = 6, 3
RECOVERY_BUDGET_S = 150.0       # CPU CI: replica relaunch pays a jax import


def _calibrate(trials: int = 9) -> float:
    """Machine-speed reference: median wall-ms for a FIXED numpy f32
    matmul workload, identical to tools/decode_smoke.py's. Banked as
    ``calib_cpu_ms`` so perf_report compares chaos rounds taken on
    differently-loaded hosts in normalized space — the fault-injection
    tail percentiles are the most host-sensitive series this repo banks,
    and nothing in the code paths can move this number, only the
    machine."""
    import numpy as np
    a = np.random.RandomState(0).rand(384, 384).astype(np.float32)
    b = np.random.RandomState(1).rand(384, 384).astype(np.float32)
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        c = a
        for _ in range(20):
            c = c @ b
        float(c[0, 0])              # force materialization
        samples.append((time.perf_counter() - t0) * 1e3)
    samples.sort()
    return round(samples[len(samples) // 2], 3)


def _metric_total(metrics: str, prefix: str, contains: str = "") -> float:
    total = 0.0
    for line in metrics.splitlines():
        if line.startswith(prefix) and not line.startswith("# ") \
                and contains in line:
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _sse_gen(url: str, model: str, prompt, max_new_tokens: int = 4,
             timeout: float = 60.0):
    """One greedy generate through the router's SSE surface; returns
    (status code | "transport", [tokens])."""
    body = json.dumps({"prompt": list(prompt),
                       "max_new_tokens": max_new_tokens,
                       "temperature": 0.0}).encode()
    req = urllib.request.Request(
        f"{url}/v1/models/{model}/generate", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            toks = []
            for raw in r:
                line = raw.decode("utf-8", "replace").strip()
                if line.startswith("data: "):
                    ev = json.loads(line[6:])
                    if "token" in ev:
                        toks.append(ev["token"])
            return r.status, toks
    except HTTPError as e:
        e.read()
        return e.code, []
    except Exception:               # noqa: BLE001 — recorded, asserted on
        return "transport", []


def _disagg_drill(env, pm_dir):
    """Prefill/decode disaggregation under machine loss: a split LM
    fleet whose router ships KV pages from the prefill replica to the
    decode replica, then the prefill replica is SIGKILLed while those
    transfers are the serving path. Returns (summary, failures)."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.serving import (
        ReplicaSpec, ReplicaSupervisor, ResilientRouter, RouterServer,
        SubprocessReplica,
    )
    from deeplearning4j_tpu.serving.decode import DecodeConfig

    failures, out = [], {}
    arch = ("zoo:TransformerLM?vocab_size=48&n_layers=1&n_embd=32"
            "&n_heads=4&seq_length=32")
    roles = ("prefill", "decode")

    def factory(i):
        return SubprocessReplica(
            f"kv-{i}",
            ReplicaSpec([], lms=[("lm", arch)],
                        decode=DecodeConfig(slots=4, page_size=4),
                        postmortem_dir=pm_dir,
                        kv_role=roles[i % len(roles)]),
            env=env)

    # the probe interval is deliberately SLOW: the drill tests the
    # ROUTER's mid-transfer failover, so the supervisor must not sweep
    # the corpse out of the routing set before the router trips over it
    sup = ReplicaSupervisor(factory, 2, probe_interval_s=30.0,
                            probe_timeout_s=2.0, unhealthy_after=3)
    t0 = time.perf_counter()
    sup.start()
    out["fleet_start_s"] = round(time.perf_counter() - t0, 1)
    router = ResilientRouter(sup.healthy, hedge=False,
                             disagg_min_tokens=8, timeout_s=30.0)
    server = RouterServer(router, supervisor=sup)
    codes = {}

    def gen(i):
        code, toks = _sse_gen(server.url, "lm",
                              [(7 * i + j) % 48 for j in range(12)])
        codes[code] = codes.get(code, 0) + 1
        return toks

    def transfer_total(family):
        return _metric_total(monitor.prometheus_text(), family)

    try:
        # same prompt twice: the orchestrated path must stay greedy-exact
        a, b = gen(0), gen(0)
        if not a or a != b:
            failures.append(f"disaggregated greedy parity broke: "
                            f"{a} vs {b}")
        for i in range(1, 7):
            gen(i)
        orch = transfer_total("serving_transfer_orchestrations_total")
        out["orchestrations_before_kill"] = orch
        if orch <= 0:
            failures.append(
                "no disaggregated transfer completed before the kill — "
                "the drill never exercised the prefill/decode split")
        victim = sup.replicas[0]
        out["killed"] = victim.name
        victim.proc.kill()          # machine loss: no drain, no goodbye
        # the router must hit the dead transfer peer before the (slow)
        # supervisor does: keep offering streams until a failover meters
        deadline = time.monotonic() + 15.0
        i = 100
        while transfer_total("serving_transfer_failovers_total") <= 0 \
                and time.monotonic() < deadline:
            gen(i)
            i += 1
        out["failovers"] = transfer_total(
            "serving_transfer_failovers_total")
        if out["failovers"] <= 0:
            failures.append(
                "killing the prefill replica never tripped a transfer "
                "failover (the supervisor swept the corpse first?)")
        # streams keep flowing on local decode-side prefill afterwards
        for i in range(200, 204):
            if not gen(i):
                failures.append(
                    f"stream {i} produced no tokens after the prefill "
                    "peer loss")
                break
    finally:
        sup.stop()
        server.stop()
    out["codes"] = {str(k): v for k, v in codes.items()}
    bad = {c: n for c, n in codes.items()
           if isinstance(c, int) and c >= 500 and c != 503}
    if bad:
        failures.append(f"5xx during the disaggregation drill: {bad} "
                        "(contract: peer loss degrades to local "
                        "prefill, never a server error)")
    if codes.get("transport"):
        failures.append(
            f"{codes['transport']} transport-level failures reached the "
            "client during the disaggregation drill")
    # the failover must have postmortemed the DEAD PEER by name while
    # the request evidence was still in the flight ring
    pm = None
    for fn in sorted(os.listdir(pm_dir)) if os.path.isdir(pm_dir) else []:
        if fn.startswith("postmortem-") and fn.endswith(".json"):
            with open(os.path.join(pm_dir, fn)) as f:
                doc = json.load(f)
            if doc["reason"] == "transfer_peer_lost" \
                    and doc["meta"].get("peer") == out.get("killed"):
                pm = (fn, doc)
    if pm is None:
        failures.append(
            "no transfer_peer_lost postmortem names the dead prefill "
            f"peer {out.get('killed')!r}")
    else:
        out["postmortem"] = {"file": pm[0], "meta": pm[1]["meta"],
                             "n_records": pm[1]["n_records"]}
    return out, failures


def main(argv=None) -> int:
    import argparse

    import numpy as np

    from bench import cache_dir
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--bank-postmortem", default=None, metavar="PATH",
                    help="copy the fault-window flight postmortem here "
                         "(banked next to CHAOS_r*.json)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="bank the summary JSON here (e.g. "
                         "CHAOS_r20.json at the repo root)")
    cli = ap.parse_args(argv)
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving import (
        ReplicaSpec, ReplicaSupervisor, ResilientRouter, RouterServer,
        SubprocessReplica,
    )
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from serve_loadgen import LoadGen

    failures = []
    summary = {}
    calib_start = _calibrate()

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    net = MultiLayerNetwork(conf).init()
    tmp = tempfile.mkdtemp(prefix="serve_chaos_")
    model_zip = os.path.join(tmp, "model.zip")
    from deeplearning4j_tpu.util.serialization import save_model
    save_model(net, model_zip)

    # the always-on flight recorder: postmortems auto-dump into pm_dir
    # when the faults below trip an SLO (breaker open, wedge detection)
    from deeplearning4j_tpu.monitor import flight
    pm_dir = os.path.join(tmp, "postmortems")
    flight.enable_flight(capacity=512, dump_dir=pm_dir)

    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir())
    spec = ReplicaSpec([("m", model_zip)], buckets=(1, 8),
                       max_delay_ms=2.0, queue_limit=64,
                       default_deadline_s=30.0, enable_faults=True,
                       postmortem_dir=pm_dir,
                       # replica-side SLO engines too, so the router's
                       # /v1/slo fleet verdict aggregates 4 reporters
                       slo_availability=0.995, slo_sample_interval_s=0.5)
    supervisor = ReplicaSupervisor(
        lambda i: SubprocessReplica(f"replica-{i}", spec, env=env),
        n_replicas=3, probe_interval_s=0.5, probe_timeout_s=2.0,
        unhealthy_after=3, restart_backoff_s=0.5, restart_budget=6)
    t0 = time.perf_counter()
    supervisor.start()
    summary["fleet_start_s"] = round(time.perf_counter() - t0, 1)

    router = ResilientRouter(
        supervisor.healthy, classes=("interactive", "batch"),
        default_class="interactive", shed_floor=0.5,
        per_replica_inflight=4, hedge=True, hedge_min_s=0.2,
        timeout_s=30.0, breaker_open_for_s=3.0)
    server = RouterServer(router, supervisor=supervisor, port=0)

    # the SLO engine over the in-process time-series ring: availability
    # burn-rate alerting with windows scaled down to drill timescales
    # (seconds, not the SRE-workbook hours) so the wedge fires a
    # fast-burn page while the drill runs and resolves once the fleet
    # is clean again. "bad" = any non-2xx: the fleet contract above
    # means faults surface as 429/503 backpressure, never 5xx, and the
    # availability objective treats that backpressure as burned budget.
    from deeplearning4j_tpu.monitor import slo as slo_mod
    from deeplearning4j_tpu.monitor import timeseries
    ring = timeseries.enable_timeseries(interval_s=0.25, capacity=4096)
    slo_engine = slo_mod.enable_slo(
        [slo_mod.Objective(
            "router_availability", "availability",
            "serving_router_requests_total", target=0.98,
            bad_code=lambda code: not code.startswith("2"),
            reason="slo_availability_burn")],
        rules=(slo_mod.BurnRule("page", 10.0, 2.5, 2.0,
                                keep_firing_s=2.0),),
        ring=ring)

    class Args:                      # LoadGen's knob surface, programmatic
        url = server.url
        model = "m"
        requests = 120
        concurrency = 6
        rate = None
        batch_sizes = [1, 2, 4]
        priority_mix = {"interactive": 1, "batch": 1}
        max_retries = 4
        retry_cap_s = 2.0
        deadline_ms = None
        timeout_s = 60.0
        seed = 0

    try:
        # ---------------- phase A: pre-fault baseline -------------------
        base = LoadGen(Args, (N_IN,))
        wall, ok = base.run_closed()
        base_rep = base.report(wall, ok)
        summary["baseline"] = {"ok": ok, "codes": base_rep["codes"],
                               "p99_ms": base_rep["latency_ms"]["p99"]}
        if ok != Args.requests:
            failures.append(f"baseline phase not clean: {base_rep['codes']}")

        # ---------------- phase B: faults under traffic -----------------
        chaos_args = type("C", (Args,), {"requests": 240,
                                         "concurrency": 12,
                                         "seed": 1})
        chaos = LoadGen(chaos_args, (N_IN,))
        faults_done = threading.Event()

        def inject():
            time.sleep(0.5)          # traffic flowing first
            victim = supervisor.replicas[0]
            victim_gen = victim.generation
            victim.proc.kill()       # machine loss: SIGKILL, no drain
            wedged = supervisor.replicas[1]
            wedged_gen = wedged.generation
            try:
                urllib.request.urlopen(urllib.request.Request(
                    wedged.url + "/v1/faults",
                    data=json.dumps({"probe_delay_s": 5.0,
                                     "predict_delay_s": 5.0}).encode(),
                    headers={"Content-Type": "application/json"}),
                    timeout=10).read()
            except Exception as e:   # noqa: BLE001
                failures.append(f"could not wedge replica-1: {e}")
            summary["faults"] = {"killed": victim.name,
                                 "killed_gen": victim_gen,
                                 "wedged": wedged.name,
                                 "wedged_gen": wedged_gen}
            faults_done.set()

        injector = threading.Thread(target=inject, daemon=True,
                                    name="chaos-injector")
        injector.start()
        fault_wall, fault_ok = chaos.run_closed()
        injector.join(timeout=30)
        # keep offering traffic until both faulted replicas rejoined (the
        # rejoin-within-budget half of the SLO) — stats accumulate
        deadline = time.monotonic() + RECOVERY_BUDGET_S
        extra_walls = 0.0

        def recovered() -> bool:
            a, b = supervisor.replicas[0], supervisor.replicas[1]
            return a.generation >= 1 and a.state == "ready" \
                and b.generation >= 1 and b.state == "ready"

        while not recovered() and time.monotonic() < deadline:
            w, o = chaos.run_closed()
            extra_walls += w
            fault_ok += o
        chaos_rep = chaos.report(fault_wall + extra_walls, fault_ok)
        summary["under_fault"] = {
            "requests_total": sum(
                v for v in chaos.codes.values()),
            "codes": chaos_rep["codes"],
            "error_classes": chaos_rep["error_classes"],
            "retries": chaos_rep["retries"],
            "p99_ms": chaos_rep["latency_ms"]["p99"],
            "goodput_rps": chaos_rep["goodput_rps"],
            "per_class": chaos_rep.get("per_class"),
            "slowest": chaos_rep.get("slowest"),
        }
        bad = {c: n for c, n in chaos.codes.items()
               if isinstance(c, int) and c >= 500 and c not in (503,)}
        if bad:
            failures.append(f"5xx under fault: {bad} (contract: only "
                            "200/429/503)")
        if chaos.codes.get("transport"):
            failures.append(
                f"{chaos.codes['transport']} transport-level failures "
                "reached the client through the router")
        if not recovered():
            failures.append(
                "faulted replicas did not rejoin within "
                f"{RECOVERY_BUDGET_S:.0f}s: "
                f"{[r.describe() for r in supervisor.replicas]}")
        summary["recovery"] = {
            "replicas": [r.describe() for r in supervisor.replicas]}

        # ---------------- phase C: post-fault recovery ------------------
        rec_args = type("R", (Args,), {"seed": 2})
        rec = LoadGen(rec_args, (N_IN,))
        wall, ok = rec.run_closed()
        rec_rep = rec.report(wall, ok)
        summary["recovered"] = {"ok": ok, "codes": rec_rep["codes"],
                                "p99_ms": rec_rep["latency_ms"]["p99"]}
        if ok != Args.requests:
            failures.append(
                f"post-fault phase not clean: {rec_rep['codes']}")
        base_p99 = base_rep["latency_ms"]["p99"] or 0.0
        rec_p99 = rec_rep["latency_ms"]["p99"] or float("inf")
        p99_budget = max(3.0 * base_p99, base_p99 + 500.0)
        if rec_p99 > p99_budget:
            failures.append(
                f"post-fault p99 {rec_p99:.1f}ms did not recover "
                f"(baseline {base_p99:.1f}ms, budget {p99_budget:.1f}ms)")

        # ---------------- SLO burn-rate alert timeline -------------------
        # the wedge must have fired the fast-burn availability page while
        # the fleet was degraded, and with traffic now stopped the burn
        # evidence ages out of both windows, so the alert must resolve
        # (held keep_firing_s first — flap suppression)
        resolve_deadline = time.monotonic() + 30.0
        while slo_engine.alert_state("router_availability", "page") \
                != "inactive" and time.monotonic() < resolve_deadline:
            time.sleep(0.25)
        alerts = slo_engine.history()
        summary["slo_alerts"] = alerts
        slo_fired = [h for h in alerts if h["event"] == "fired"]
        slo_resolved = [h for h in alerts if h["event"] == "resolved"]
        if not slo_fired:
            failures.append(
                "the wedge drill never fired the fast-burn availability "
                f"alert (history: {alerts})")
        if not slo_resolved:
            failures.append(
                "the availability alert did not resolve after recovery "
                "(state "
                f"{slo_engine.alert_state('router_availability', 'page')})")
        if slo_fired and slo_resolved \
                and slo_resolved[-1]["unix"] < slo_fired[0]["unix"]:
            failures.append("alert resolution precedes the first fire")

        # the firing alert must have tripped a flight postmortem that
        # carries actual request timelines as evidence
        slo_pm = None
        for fn in sorted(os.listdir(pm_dir)) if os.path.isdir(pm_dir) \
                else []:
            if fn.startswith("postmortem-") and fn.endswith(".json"):
                with open(os.path.join(pm_dir, fn)) as f:
                    doc = json.load(f)
                if doc["reason"] == "slo_availability_burn":
                    slo_pm = (fn, doc)
        if slo_pm is None:
            failures.append(
                "the firing availability alert did not dump a "
                "slo_availability_burn flight postmortem")
        else:
            fn, doc = slo_pm
            summary["slo_postmortem"] = {"file": fn, "meta": doc["meta"],
                                         "n_records": doc["n_records"]}
            if doc["n_records"] <= 0:
                failures.append("slo_availability_burn postmortem "
                                "carries no flight records")

        # fleet verdict after recovery: the router engine plus all three
        # replica engines (spec slo_availability) report, and nothing
        # is firing any more
        fleet_slo = json.loads(urllib.request.urlopen(
            server.url + "/v1/slo", timeout=10).read())
        summary["fleet_slo"] = fleet_slo["fleet"]
        if not fleet_slo["router"].get("enabled"):
            failures.append("/v1/slo: router engine not enabled")
        if fleet_slo["fleet"]["state"] != "ok":
            failures.append(
                f"fleet SLO state after recovery: {fleet_slo['fleet']}")
        if fleet_slo["fleet"]["reporting"] < 4:
            failures.append(
                "expected router + 3 replica SLO engines reporting, got "
                f"{fleet_slo['fleet']['reporting']} "
                f"(unreachable: {fleet_slo['fleet']['unreachable']})")

        # ---------------- metrics assertions ----------------------------
        metrics = urllib.request.urlopen(server.url + "/metrics",
                                         timeout=10).read().decode()
        restarts = _metric_total(metrics, "serving_fleet_restarts_total")
        summary["fleet_restarts_total"] = restarts
        if restarts < 2:
            failures.append(f"expected >= 2 supervised restarts (kill + "
                            f"wedge), /metrics shows {restarts}")
        if "serving_router_breaker_state" not in metrics:
            failures.append("/metrics missing serving_router_breaker_state")
        shed_batch = _metric_total(metrics, "serving_router_shed_total",
                                   contains='cls="batch"')
        shed_inter = _metric_total(metrics, "serving_router_shed_total",
                                   contains='cls="interactive"')
        summary["shed"] = {"batch": shed_batch, "interactive": shed_inter}
        if shed_batch == 0:
            failures.append("fleet saturation never shed the batch class "
                            "(serving_router_shed_total{cls=batch} == 0)")
        for fam in ("serving_fleet_replicas", "serving_fleet_probe_seconds",
                    "serving_router_requests_total"):
            if fam not in metrics:
                failures.append(f"/metrics missing {fam}")
        for fam in ("serving_flight_records_total",
                    "serving_flight_postmortems_total",
                    "timeseries_samples_total", "slo_burn_rate",
                    "slo_alert_state", "slo_alerts_total"):
            if fam not in metrics:
                failures.append(f"/metrics missing {fam}")

        # ---------------- flight-recorder postmortems --------------------
        # the fault window must have auto-dumped at least one postmortem
        # that (a) names the faulted replica's generation and (b) holds
        # the full timeline of at least one shed and one hedged request
        pms = []
        for fn in sorted(os.listdir(pm_dir)) if os.path.isdir(pm_dir) \
                else []:
            if fn.startswith("postmortem-") and fn.endswith(".json"):
                with open(os.path.join(pm_dir, fn)) as f:
                    pms.append((fn, json.load(f)))
        summary["postmortems_dumped"] = [
            {"file": fn, "reason": doc["reason"], "meta": doc["meta"]}
            for fn, doc in pms]
        if not pms:
            failures.append("no flight postmortem auto-dumped during the "
                            f"fault window (dir {pm_dir})")
        faulted = summary.get("faults", {})
        named_gen = [
            (fn, doc) for fn, doc in pms
            if (doc["reason"] == "replica_wedged"
                and doc["meta"].get("replica") == faulted.get("wedged")
                and doc["meta"].get("generation")
                == faulted.get("wedged_gen"))
            or (doc["reason"] == "breaker_open"
                and doc["meta"].get("replica") in (faulted.get("killed"),
                                                   faulted.get("wedged")))]
        if pms and not named_gen:
            failures.append(
                "no postmortem names the killed/wedged replica "
                f"generation: {[d['meta'] for _, d in pms]}")

        def pm_evidence(doc):
            recs = doc.get("records", []) + doc.get("live", [])
            shed = [r for r in recs if r.get("outcome") == "shed_429"
                    or any(e.get("event") == "shed"
                           for e in r.get("events", []))]
            hedged = [r for r in recs
                      if any(e.get("event") == "hedge"
                             for e in r.get("events", []))]
            return shed, hedged

        banked_pm = None
        for fn, doc in reversed(named_gen or pms):
            shed, hedged = pm_evidence(doc)
            if shed and hedged:
                banked_pm = (fn, doc, shed, hedged)
                break
        if pms and banked_pm is None:
            # fall back to ANY dump carrying both timelines
            for fn, doc in reversed(pms):
                shed, hedged = pm_evidence(doc)
                if shed and hedged:
                    banked_pm = (fn, doc, shed, hedged)
                    break
        if pms and banked_pm is None:
            failures.append(
                "no postmortem holds both a shed and a hedged request "
                "timeline")
        if banked_pm is not None:
            fn, doc, shed, hedged = banked_pm
            summary["postmortem"] = {
                "file": fn, "reason": doc["reason"], "meta": doc["meta"],
                "n_records": doc["n_records"],
                "shed_records": len(shed), "hedged_records": len(hedged),
                "example_shed_trace": shed[-1].get("trace_id"),
                "example_hedged_trace": hedged[-1].get("trace_id"),
            }
            if cli.bank_postmortem:
                with open(cli.bank_postmortem, "w") as f:
                    json.dump(doc, f, indent=1)
                summary["postmortem"]["banked_as"] = cli.bank_postmortem
    finally:
        slo_mod.disable_slo()        # engine first: it listens on the ring
        timeseries.disable_timeseries()
        supervisor.stop()
        server.stop()

    # ------------- disaggregation drill: prefill death mid-transfer -----
    # its own fleet (prefill/decode split LM replicas), run after the
    # predict fleet wound down so the two drills never fight for cores
    disagg, disagg_failures = _disagg_drill(env, pm_dir)
    summary["disagg"] = disagg
    failures.extend(disagg_failures)

    summary["ok"] = not failures
    summary["failures"] = failures
    # host-speed reference sampled at both ends of the run and averaged
    # (the drills take minutes; the box's speed can drift mid-run) —
    # rounds before this banked none, so perf_report skips those as
    # baselines rather than judging a calibrated run by raw wall-clock
    summary["calib_cpu_ms"] = round((calib_start + _calibrate()) / 2, 3)
    # bench-style row so the driver can bank this run as CHAOS_r*.json and
    # tools/perf_report.py can gate the chaos-SLO trajectory
    summary["sweep"] = [{
        "mode": "serve_chaos", "on_tpu": False, "batch": None,
        "chaos_p99_under_fault_ms": summary.get(
            "under_fault", {}).get("p99_ms"),
        "chaos_goodput_under_fault_rps": summary.get(
            "under_fault", {}).get("goodput_rps"),
        "chaos_recovered_p99_ms": summary.get(
            "recovered", {}).get("p99_ms"),
        # the K slowest under-fault requests per class, by trace_id —
        # a banked percentile points at reproducible traces, not just a
        # number (server-side histogram exemplars carry the same ids)
        "slow_trace_ids": summary.get("under_fault", {}).get("slowest"),
        "postmortem": summary.get("postmortem", {}).get("file"),
        # burn-rate alert timeline: when the wedge paged, how hot the
        # burn was, and when the alert resolved after recovery
        "chaos_slo_fired_unix": next(
            (h["unix"] for h in summary.get("slo_alerts", [])
             if h["event"] == "fired"), None),
        "chaos_slo_resolved_unix": next(
            (h["unix"] for h in reversed(summary.get("slo_alerts", []))
             if h["event"] == "resolved"), None),
        "chaos_slo_burn_long_at_fire": next(
            (h["burn_long"] for h in summary.get("slo_alerts", [])
             if h["event"] == "fired"), None),
    }, {
        # the disaggregation drill row: ungated context proving the
        # prefill/decode split served transfers and survived peer loss
        "mode": "serve_chaos_disagg", "on_tpu": False, "batch": None,
        "chaos_disagg_orchestrations": disagg.get(
            "orchestrations_before_kill"),
        "chaos_disagg_failovers": disagg.get("failovers"),
        "chaos_disagg_codes": disagg.get("codes"),
        "disagg_postmortem": (disagg.get("postmortem") or {}).get("file"),
    }]
    print(json.dumps(summary, indent=1))
    if cli.out:
        with open(cli.out, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
