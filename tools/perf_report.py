#!/usr/bin/env python
"""Perf regression gate + roofline report — the CI teeth of the ledger.

Merges two artifact streams:

- the banked bench trajectory (``BENCH_r*.json`` /
  ``BENCH_TPU_MEASURED_*.json``): every throughput series that appears in
  more than one round — per-mode/batch ResNet imgs/sec, char-LSTM
  chars/sec, Word2Vec pairs/sec, LeNet imgs/sec, h2d MB/s, and the
  headline — is compared LATEST vs. BEST-EARLIER within its own device
  class (CPU rows never gate TPU rows and vice versa). Artifacts that
  bank a ``calib_cpu_ms`` machine-speed reference (decode smokes, r17+)
  are compared in HOST-NORMALIZED space: baselines are rescaled by the
  calibration ratio so a slower/faster container does not masquerade as
  a code regression/improvement, and uncalibrated earlier rounds are
  excluded (reported as skipped when no calibrated baseline exists);
- the compiled-program ledger (``monitor.xla.save_ledger()`` JSON,
  ``--ledger``): each program's arithmetic intensity is placed on the
  device roofline (ridge = peak_flops / hbm_bandwidth) to report whether
  it is compute- or memory-bound and what MFU ceiling the roofline allows
  — the standing context for ROADMAP item 2's 27% -> 40% chase.

Exit codes: 0 = no tracked series regressed beyond ``--threshold``
(default 15%); 2 = regression(s); 1 = usage/IO error. CI usage:

    python tools/perf_report.py                          # gate the repo
    python tools/perf_report.py --ledger perf_ledger.json --json
    python tools/perf_report.py --dir /path/to/artifacts --threshold 0.10
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: throughput keys a sweep row may carry; each becomes its own series.
#: fit_e2e_* are the PRODUCT-path (disk->decode->device, ETL included)
#: rows from `bench.py --mode fit_e2e`. fit_e2e_baseline_imgs_sec (the
#: deliberately-slow per-sample-loop reference the pipeline's speedup is
#: computed against) is NOT gated: it measures the path we replaced, and
#: its run-to-run spread exceeds the regression threshold.
#: mesh_imgs_sec is the GSPMD-plan scaling sweep (`bench.py --mode
#: mesh`, banked as MULTICHIP_r*.json): one row per plan config
#: (mesh-single / mesh-dp / mesh-dp_tp / mesh-zero1 / mesh-zero3).
#: decode_tokens_sec is the continuous-batching generate surface
#: (`tools/decode_smoke.py`, banked as DECODE_r*.json): generated tokens
#: per wall second across concurrent streams through a mid-traffic swap.
#: decode_cache_hit_rate is the shared-prefix workload's KV prefix-cache
#: hit fraction (DECODE_r*.json, r14+): higher = more prefill compute
#: skipped, gated like a throughput so a cache regression trips CI.
#: train_goodput_pct is the clean-fit step-compute share of wall-clock
#: from the goodput ledger (`tools/goodput_report.py`, banked as
#: GOODPUT_r*.json, r19+): an attribution regression (more time leaking
#: into data_wait/host_sync/other) trips CI even when raw imgs/sec
#: noise hides it.
#: decode_spill_hit_rate is the tiered-KV-fabric host-RAM tier's
#: admission hit fraction under pool pressure (DECODE_r*.json, r20+):
#: spill-probing admissions whose HBM-missed blocks promoted back from
#: host memory — a drop means evicted prefixes stopped coming back.
THROUGHPUT_KEYS = ("imgs_sec", "lenet_imgs_sec", "chars_sec", "pairs_sec",
                   "h2d_f32_mbytes_sec", "h2d_u8_mbytes_sec",
                   "fit_e2e_imgs_sec",
                   "fit_e2e_chars_sec", "fit_e2e_pairs_sec",
                   "chaos_goodput_under_fault_rps", "mesh_imgs_sec",
                   "decode_tokens_sec", "decode_cache_hit_rate",
                   "decode_spec_acceptance_rate", "train_goodput_pct",
                   "decode_spill_hit_rate")

#: lower-is-better series (latencies). Banked by tools/serve_chaos.py
#: (CHAOS_r*.json): p99 while a replica is killed + another wedged, and
#: post-fault recovered p99. decode_* are the streaming-generation tail
#: latencies from tools/decode_smoke.py (DECODE_r*.json): time-to-first-
#: token p99 and inter-token p99. Gated inverted: baseline = best
#: (lowest) earlier round, regression = latest above baseline by >
#: threshold.
#: decode_ttft_hot_p99_ms is time-to-first-token p99 for prefix-cache
#: HITS on the shared-prefix workload; decode_itl_interferer_p99_ms is
#: short-stream inter-token p99 while a long-prompt interferer admits
#: (chunked prefill keeps it bounded). Both r14+. The cold-TTFT and
#: chunking-off interferer numbers are banked for the ratio but NOT
#: gated (they measure the path the cache/chunking replaced).
#: rollout_* are the continuous-rollout control-loop latencies from
#: tools/rollout_drill.py (ROLLOUT_r*.json, r18+): fleet-wide staggered
#: promote fan-out seconds, and wall seconds from a poisoned blessing
#: landing on disk to the auto-rollback decision. Host-calibrated like
#: the decode series (both scale with model-load / probe round-trips).
#: decode_affinity_ttft_hot_p99_ms is the repeat-prefix (would-be-hot)
#: TTFT p99 through a 2-replica fleet router with prefix-affinity
#: steering ON (DECODE_r*.json, r20+); the random-routing arm of the
#: same A/B is banked as decode_affinity_ttft_random_p99_ms but NOT
#: gated (it measures the policy affinity replaced).
LATENCY_KEYS = ("chaos_p99_under_fault_ms", "chaos_recovered_p99_ms",
                "decode_ttft_p99_ms", "decode_itl_p99_ms",
                "decode_ttft_hot_p99_ms", "decode_itl_interferer_p99_ms",
                "rollout_promote_s", "rollout_rollback_detect_s",
                "decode_affinity_ttft_hot_p99_ms")

#: dimensionless series (fractions of work, not work per second): host
#: speed cannot move them, so calibration normalization never applies —
#: they always compare raw, against every earlier round.
RATIO_KEYS = ("decode_cache_hit_rate", "decode_spec_acceptance_rate",
              "train_goodput_pct", "decode_spill_hit_rate")


def _round_of(name: str) -> int:
    m = re.search(r"_r(\d+)", name)
    return int(m.group(1)) if m else 0


def load_rounds(directory: str):
    """Parse every banked bench artifact into (round, on_tpu, payload)
    entries. Artifacts wrap the bench JSON under "parsed" (driver capture)
    or are the bare JSON (watcher-banked TPU measurements); unparseable or
    payload-less rounds are skipped, not fatal — a wedged round must not
    break the gate."""
    entries = []
    names = (sorted(glob.glob(os.path.join(directory, "BENCH_r*.json")))
             + sorted(glob.glob(os.path.join(directory,
                                             "BENCH_TPU_MEASURED_*.json")))
             + sorted(glob.glob(os.path.join(directory, "CHAOS_r*.json")))
             # GSPMD-plan scaling sweeps; pre-r06 MULTICHIP artifacts
             # are driver dryrun stamps without a sweep and skip below
             + sorted(glob.glob(os.path.join(directory,
                                             "MULTICHIP_r*.json")))
             # continuous-batching decode smokes (tokens/sec, TTFT, ITL)
             + sorted(glob.glob(os.path.join(directory,
                                             "DECODE_r*.json")))
             # continuous-rollout drills (promote fan-out / rollback
             # detection latency from tools/rollout_drill.py)
             + sorted(glob.glob(os.path.join(directory,
                                             "ROLLOUT_r*.json")))
             # goodput-ledger acceptance runs (clean-fit goodput% from
             # tools/goodput_report.py)
             + sorted(glob.glob(os.path.join(directory,
                                             "GOODPUT_r*.json"))))
    for path in names:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        payload = doc.get("parsed", doc) if isinstance(doc, dict) else None
        if not isinstance(payload, dict):
            continue
        if "sweep" not in payload and payload.get("value") is None:
            continue
        # absent flag = the early TPU rounds (r01/r02) that predate it
        on_tpu = not payload.get("tpu_unavailable", False)
        # machine-speed reference (decode_smoke r17+): wall-ms for a
        # fixed numpy workload on the banking host; None on older rounds
        calib = payload.get("calib_cpu_ms")
        if not isinstance(calib, (int, float)) or calib <= 0:
            calib = None
        entries.append({"artifact": os.path.basename(path),
                        "round": _round_of(os.path.basename(path)),
                        "on_tpu": on_tpu, "calib": calib,
                        "payload": payload})
    entries.sort(key=lambda e: (e["round"], e["artifact"]))
    return entries


def extract_series(entries):
    """{series_id: [(round, artifact, value, calib), ...]} — series_id
    keys are (on_tpu, mode, batch, metric); the headline rides as
    (on_tpu, "__headline__", None, "value"). ``calib`` is the artifact's
    machine-speed reference (None when the round predates it)."""
    series = {}

    def add(sid, rnd, artifact, value, calib):
        series.setdefault(sid, []).append(
            (rnd, artifact, float(value), calib))

    for e in entries:
        p = e["payload"]
        if isinstance(p.get("value"), (int, float)):
            add((e["on_tpu"], "__headline__", None, "value"),
                e["round"], e["artifact"], p["value"], e["calib"])
        for row in p.get("sweep", []) or []:
            if not isinstance(row, dict) or "error" in row \
                    or "skipped" in row:
                continue
            on_tpu = bool(row.get("on_tpu", e["on_tpu"]))
            for key in THROUGHPUT_KEYS + LATENCY_KEYS:
                if isinstance(row.get(key), (int, float)):
                    add((on_tpu, row.get("mode"), row.get("batch"), key),
                        e["round"], e["artifact"], row[key], e["calib"])
    return series


def check_regressions(series, threshold: float):
    """LATEST occurrence vs BEST of strictly-earlier rounds, per series.
    "Best" is direction-aware: highest for throughput series, lowest for
    LATENCY_KEYS series, and a regression is a move AWAY from best beyond
    the threshold in either regime. Single-round series (e.g. a config
    measured only once) cannot gate.

    Machine-speed normalization: when the LATEST artifact banked a
    ``calib_cpu_ms`` reference, every baseline candidate that also has
    one is mapped to the latest host's speed before the comparison
    (throughput scales with 1/calib, latency with calib) — the gate then
    measures the CODE, not which container the round happened to run in.
    Earlier rounds WITHOUT a reference cannot give a fair verdict against
    a calibrated latest, so they are excluded from baseline selection; if
    none remain the series is reported as skipped, not gated. A latest
    without a reference keeps the legacy raw comparison.

    Calibration may EXCUSE, never convict: the reference is one matmul
    kernel, so the ratio tracks the host's compute speed but not its
    Python/dispatch overhead — a faster-matmul host does not make every
    latency proportionally cheaper. A slow host's raw regression is
    forgiven when the normalized delta is clean (the original purpose),
    but a conviction additionally requires the RAW delta against the
    same baseline round to exceed the threshold; otherwise a fast-calib
    round would manufacture regressions out of series whose raw numbers
    held steady or improved."""
    checked, regressions, skipped = [], [], []
    for sid, points in sorted(series.items(), key=lambda kv: str(kv[0])):
        lower_better = sid[3] in LATENCY_KEYS
        better = (lambda a, b: a < b) if lower_better \
            else (lambda a, b: a > b)
        rounds = {}
        for rnd, artifact, value, calib in points:
            cur = rounds.get(rnd)
            if cur is None or better(value, cur[1]):  # same-round: best
                rounds[rnd] = (artifact, value, calib)
        if len(rounds) < 2:
            continue
        latest_round = max(rounds)
        latest_art, latest, latest_calib = rounds[latest_round]
        earlier = {r: v for r, v in rounds.items() if r != latest_round}
        on_tpu, mode, batch, key = sid
        sdesc = {"on_tpu": on_tpu, "mode": mode, "batch": batch,
                 "metric": key}
        calibrated = latest_calib is not None and key not in RATIO_KEYS
        if calibrated:
            earlier = {r: v for r, v in earlier.items()
                       if v[2] is not None}
            if not earlier:
                skipped.append({
                    "series": sdesc,
                    "latest": {"round": latest_round,
                               "artifact": latest_art, "value": latest},
                    "reason": "no calibrated baseline round",
                })
                continue

            def adjust(value, calib):
                # map a baseline taken at `calib` to the latest host
                ratio = latest_calib / calib
                return value * (ratio if lower_better else 1.0 / ratio)
        else:
            def adjust(value, calib):
                return value
        base_round, (base_art, base_raw, base_calib) = \
            (min if lower_better else max)(
                earlier.items(), key=lambda rv: adjust(rv[1][1], rv[1][2]))
        baseline = adjust(base_raw, base_calib)
        delta = (latest - baseline) / baseline if baseline > 0 else 0.0
        if lower_better:
            delta = -delta      # normalized: negative delta == worse
        raw_delta = (latest - base_raw) / base_raw if base_raw > 0 else 0.0
        if lower_better:
            raw_delta = -raw_delta
        calibration = {
            "latest_calib_ms": latest_calib,
            "baseline_calib_ms": base_calib,
            "host_speed_ratio": round(latest_calib / base_calib, 4),
            "baseline_raw": base_raw,
            "raw_delta_pct": round(raw_delta * 100, 2),
        } if calibrated else None
        rec = {
            "series": sdesc,
            "baseline": {"round": base_round, "artifact": base_art,
                         "value": baseline},
            "latest": {"round": latest_round, "artifact": latest_art,
                       "value": latest},
            "delta_pct": round(delta * 100, 2),
            "regressed": delta < -threshold
            and (not calibrated or raw_delta < -threshold),
        }
        if calibration:
            rec["calibration"] = calibration
        checked.append(rec)
        if rec["regressed"]:
            regressions.append(rec)
    return checked, regressions, skipped


def roofline(ledger: dict):
    """Place every ledger program on the device roofline. Returns [] when
    the ledger carries no peak numbers (unlisted device, no override) —
    informational, never gating."""
    peak = ledger.get("peak_flops")
    bw = ledger.get("hbm_bytes_per_sec")
    rows = []
    for prog in ledger.get("programs", []):
        ai = prog.get("arithmetic_intensity")
        row = {"name": prog.get("name"),
               "fingerprint": prog.get("fingerprint"),
               "flops": prog.get("flops"),
               "arithmetic_intensity": ai,
               "hbm_peak_bytes": prog.get("hbm_peak_bytes"),
               "compile_seconds": prog.get("compile_seconds"),
               # sharded (GSPMD plan) vs replicated programs roofline
               # differently — per-chip flops and HBM are 1/N figures
               "sharded": bool(prog.get("sharded", False)),
               "arg_shardings": prog.get("arg_shardings")}
        if ai and peak and bw:
            ridge = peak / bw
            attainable = min(peak, ai * bw)
            row.update({
                "ridge_intensity": round(ridge, 2),
                "bound": "compute" if ai >= ridge else "memory",
                "attainable_flops": attainable,
                "mfu_ceiling_pct": round(100.0 * attainable / peak, 1),
            })
        rows.append(row)
    return rows


def _fmt_series(sid_rec) -> str:
    s = sid_rec["series"]
    where = "tpu" if s["on_tpu"] else "cpu"
    mode = s["mode"] if s["mode"] != "__headline__" else "headline"
    batch = "" if s["batch"] is None else f" b{s['batch']}"
    return f"{where} {mode}{batch} [{s['metric']}]"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_*.json artifacts (default: repo root)")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="perf-ledger JSON (monitor.xla.save_ledger / "
                        "--perf-ledger) to roofline-annotate")
    p.add_argument("--threshold", type=float, default=0.15,
                   help="fractional regression that fails the gate "
                        "(default 0.15 = 15%%)")
    p.add_argument("--json", action="store_true",
                   help="emit the full machine-readable report on stdout")
    args = p.parse_args(argv)

    entries = load_rounds(args.dir)
    if not entries:
        print(f"perf_report: no BENCH_*.json artifacts under {args.dir}",
              file=sys.stderr)
        return 1
    series = extract_series(entries)
    checked, regressions, skipped = check_regressions(series,
                                                      args.threshold)

    ledger_doc, roof = None, []
    if args.ledger:
        try:
            with open(args.ledger) as f:
                ledger_doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"perf_report: cannot read ledger {args.ledger}: {e}",
                  file=sys.stderr)
            return 1
        roof = roofline(ledger_doc)

    report = {
        "artifacts": [e["artifact"] for e in entries],
        "threshold": args.threshold,
        "series_tracked": len(series),
        "series_compared": len(checked),
        "series_skipped": skipped,
        "comparisons": checked,
        "regressions": regressions,
        "roofline": roof,
        "ok": not regressions,
    }
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(f"perf_report: {len(entries)} artifacts, {len(series)} "
              f"series, {len(checked)} compared "
              f"(threshold {args.threshold:.0%})")
        for rec in checked:
            mark = "REGRESSED" if rec["regressed"] else "ok"
            cal = rec.get("calibration")
            note = (f"  [host x{cal['host_speed_ratio']:.2f}, baseline "
                    f"{cal['baseline_raw']:.2f} raw, "
                    f"{cal['raw_delta_pct']:+.1f}% raw]" if cal else "")
            print(f"  {mark:>9}  {_fmt_series(rec):<42} "
                  f"{rec['baseline']['value']:>12.2f} (r{rec['baseline']['round']})"
                  f" -> {rec['latest']['value']:>12.2f} "
                  f"(r{rec['latest']['round']})  {rec['delta_pct']:+.1f}%"
                  f"{note}")
        for rec in skipped:
            print(f"    skipped  {_fmt_series(rec):<42} "
                  f"{rec['reason']} (latest r{rec['latest']['round']})")
        for row in roof:
            pos = (f"{row['bound']}-bound, MFU ceiling "
                   f"{row['mfu_ceiling_pct']}%"
                   if "bound" in row else "roofline n/a (no device peak)")
            ai = row["arithmetic_intensity"]
            print(f"  roofline  {row['name']:<28} "
                  f"AI={'n/a' if ai is None else f'{ai:.1f}'}  {pos}")
        if regressions:
            print(f"perf_report: {len(regressions)} series regressed "
                  f"beyond {args.threshold:.0%} — failing the gate")
        else:
            print("perf_report: gate clean")
    return 2 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
