#!/usr/bin/env python
"""Continuous-rollout drill: train -> bless -> canary -> verdict ->
promote / auto-rollback, plus load-signal autoscaling — end to end.

    JAX_PLATFORMS=cpu python tools/rollout_drill.py [--out ROLLOUT.json]

The acceptance run for serving/rollout.py, the loop that closes training
into serving. Four phases, all against REAL components (no fakes):

1. **Train & bless** — ResilientTrainer fits a classifier with an eval
   gate; the passing checkpoint lands in the manifest AND in
   ``blessed.json`` (CheckpointManager.bless), the contract the rollout
   watcher tails.
2. **Canary -> promote** — a 3-subprocess-replica fleet (each its own
   OS process, XLA runtime, SLO engine + time-series ring) serves a v1
   model behind the ResilientRouter while closed-loop traffic flows.
   The RolloutController spots the blessing, swaps ONE replica to the
   blessed version (its /readyz flips role=canary, /v1/fleet shows the
   rollout), holds the admin surface, judges the observation window on
   per-replica /v1/slo + /v1/timeseries + accuracy probes, and promotes
   fleet-wide with a staggered fan-out. Assert: **zero 5xx end to end**,
   every replica's active version is the blessed source, the shared
   ReplicaSpec was rewritten (restart durability).
3. **Poisoned blessing -> auto-rollback** — an UNTRAINED model is
   checkpointed and blessed with lying metrics (the broken-eval-gate
   scenario). The canary's accuracy probes catch it; the controller
   rolls the replica back and trips a ``rollout_rejected`` flight
   postmortem naming the regressing metric (``probe_accuracy``) and the
   rejected source. Assert: fleet still serves the good version, zero
   5xx while the poison was live, postmortem on disk.
4. **Autoscale** — a separate in-process mini-fleet (min 1 / max 3)
   under a stepped open-loop ramp (tools/serve_loadgen.py ``run_ramp``
   with /v1/fleet sampling). Slowed predicts push router in-flight past
   the high watermark: the supervisor scales up; when the ramp ends it
   scales down by DRAINING the victim (readyz flip confirmed, in-flight
   zero, graceful stop) — never a kill. Assert: peak > initial
   replicas, ``forced_kills == 0``, every retirement readyz-confirmed.

Prints a JSON report with a bench-style "sweep" row carrying
``rollout_promote_s`` (staggered fan-out duration) and
``rollout_rollback_detect_s`` (poisoned blessing on disk -> rollback
decision), plus the ``calib_cpu_ms`` machine-speed reference so
tools/perf_report.py gates both in host-normalized space (banked as
ROLLOUT_r*.json). Exit 0 iff every assertion held.
"""
import json
import os
import sys
import tempfile
import threading
import time
import traceback
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

N_IN, N_OUT = 6, 3
FLEET_READY_BUDGET_S = 180.0    # CPU CI: each subprocess pays a jax import
PROMOTE_BUDGET_S = 120.0
ROLLBACK_BUDGET_S = 90.0
SCALE_DOWN_BUDGET_S = 90.0


def _blobs(n=480, seed=0):
    import numpy as np
    rs = np.random.RandomState(seed)
    centers = rs.randn(N_OUT, N_IN) * 3.0
    X = np.empty((n, N_IN), dtype=np.float32)
    Y = np.zeros((n, N_OUT), dtype=np.float32)
    for i in range(n):
        c = i % N_OUT
        X[i] = centers[c] + rs.randn(N_IN) * 0.7
        Y[i, c] = 1.0
    idx = rs.permutation(n)
    return X[idx], Y[idx]


def _net(seed):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(2e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _accuracy(net, X, Y) -> float:
    import numpy as np
    pred = np.argmax(np.asarray(net.output(X)), axis=1)
    return float((pred == np.argmax(Y, axis=1)).mean())


def _get_json(url, timeout=10.0) -> dict:
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def _active_source(replica_url: str, model: str = "m"):
    """The source path of the ACTIVE version on one replica (GET
    /v1/models/{name} returns active_version + the version history)."""
    doc = _get_json(f"{replica_url}/v1/models/{model}")
    active = doc.get("active_version")
    for v in doc.get("versions", []):
        if v.get("version") == active:
            return v.get("source")
    return None


def _count_5xx(codes: dict) -> int:
    # 503 is explicit backpressure/no-backend in this repo's contract
    # (see tools/serve_chaos.py) — everything else >= 500 is a failure.
    # report() stringifies code keys; "transport" stays non-numeric.
    n = 0
    for c, cnt in codes.items():
        try:
            code = int(c)
        except (TypeError, ValueError):
            continue
        if code >= 500 and code != 503:
            n += cnt
    return n


class _Pump:
    """Closed-loop traffic on a background thread until stopped;
    accumulates into ONE LoadGen so codes/latencies pool across runs."""

    def __init__(self, gen):
        self.gen = gen
        self.wall = 0.0
        self.ok = 0
        self.crashed = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rollout-drill-pump")

    def _loop(self):
        try:
            while not self._stop.is_set():
                w, o = self.gen.run_closed()
                self.wall += w
                self.ok += o
        except Exception:  # noqa: BLE001 — a dead pump must be loud
            self.crashed = traceback.format_exc()
            print(f"[drill] traffic pump crashed:\n{self.crashed}",
                  file=sys.stderr)

    def start(self):
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        self._thread.join(timeout=120.0)
        return self.gen.report(self.wall, self.ok)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the JSON report here")
    cli = ap.parse_args(argv)

    import numpy as np

    from bench import cache_dir
    from deeplearning4j_tpu.monitor import flight
    from deeplearning4j_tpu.serving import (
        AutoscaleConfig, InProcessReplica, ReplicaSpec, ReplicaSupervisor,
        ResilientRouter, RolloutController, RouterServer, SubprocessReplica,
    )
    from deeplearning4j_tpu.serving.rollout import read_blessed
    from deeplearning4j_tpu.train.resilience import ResilientTrainer
    from deeplearning4j_tpu.util.serialization import save_model
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from decode_smoke import _calibrate
    from serve_loadgen import LoadGen

    failures = []
    summary = {}
    calib_start = _calibrate()

    # ---------------- phase 1: train & bless ----------------------------
    X, Y = _blobs(seed=0)
    Xh, Yh = X[-60:], Y[-60:]            # held-out: eval gate + probes
    Xt, Yt = X[:-60], Y[:-60]
    tmp = tempfile.mkdtemp(prefix="rollout_drill_")
    ckpt_dir = os.path.join(tmp, "ckpts")

    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    v1 = _net(seed=1)
    v1.fit(ArrayDataSetIterator(Xt, Yt, batch_size=32))     # one epoch
    v1_zip = os.path.join(tmp, "v1.zip")
    save_model(v1, v1_zip)
    v1_acc = _accuracy(v1, Xh, Yh)

    gate_calls = [0]

    def eval_gate(net):
        gate_calls[0] += 1
        acc = _accuracy(net, Xh, Yh)
        # bless only a model that beats chance decisively — the gate
        # between "trainer wrote a checkpoint" and "fleet may canary it"
        return {"accuracy": round(acc, 4)} if acc >= 0.6 else None

    t0 = time.perf_counter()
    trainer = ResilientTrainer(_net(seed=2), ckpt_dir,
                               save_every_n_iterations=10_000,
                               save_every_n_epochs=1, keep_last=3,
                               eval_gate=eval_gate)
    fit_report = trainer.fit(ArrayDataSetIterator(Xt, Yt, batch_size=32),
                             epochs=4)
    blessed = read_blessed(ckpt_dir)
    summary["train"] = {
        "fit_s": round(time.perf_counter() - t0, 1),
        "v1_accuracy": round(v1_acc, 4),
        "checkpoints_written": fit_report.checkpoints_written,
        "checkpoints_blessed": fit_report.checkpoints_blessed,
        "eval_gate_calls": gate_calls[0],
        "blessed": {k: blessed[k] for k in
                    ("file", "sha256", "metrics")} if blessed else None,
    }
    if fit_report.checkpoints_blessed < 1 or blessed is None:
        failures.append("trainer produced no blessed checkpoint "
                        f"({fit_report.checkpoints_blessed} blessed, "
                        f"read_blessed -> {blessed})")
        print(json.dumps({"ok": False, "failures": failures,
                          "summary": summary}, indent=1))
        return 1
    v2_path = blessed["path"]
    probes = [(Xh[i], int(np.argmax(Yh[i]))) for i in range(24)]

    # ---------------- phase 2: fleet + canary -> promote -----------------
    pm_dir = os.path.join(tmp, "postmortems")
    flight.enable_flight(capacity=512, dump_dir=pm_dir)
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir())
    spec = ReplicaSpec([("m", v1_zip)], buckets=(1, 8), max_delay_ms=2.0,
                       queue_limit=64, default_deadline_s=30.0,
                       postmortem_dir=pm_dir,
                       # per-replica SLO engine + time-series ring: the
                       # rollout verdict reads each replica's OWN stats
                       slo_availability=0.995, slo_sample_interval_s=0.5)
    supervisor = ReplicaSupervisor(
        lambda i: SubprocessReplica(f"replica-{i}", spec, env=env),
        n_replicas=3, probe_interval_s=0.5, probe_timeout_s=2.0,
        unhealthy_after=3, restart_backoff_s=0.5, restart_budget=6)
    t0 = time.perf_counter()
    supervisor.start()
    deadline = time.monotonic() + FLEET_READY_BUDGET_S
    while len(supervisor.healthy()) < 3 and time.monotonic() < deadline:
        time.sleep(0.5)
    summary["fleet_start_s"] = round(time.perf_counter() - t0, 1)
    if len(supervisor.healthy()) < 3:
        failures.append("fleet did not reach 3 ready replicas within "
                        f"{FLEET_READY_BUDGET_S:.0f}s")

    # hedging off: a hedged duplicate served by the canary would blur
    # which replica's stats a request belongs to
    router = ResilientRouter(supervisor.healthy, per_replica_inflight=8,
                             hedge=False, timeout_s=30.0,
                             canary_fraction=0.25)
    server = RouterServer(router, supervisor=supervisor, port=0)
    rollout = RolloutController(
        supervisor, router, ckpt_dir, "m", watch="blessed",
        poll_interval_s=0.5, observe_s=8.0, min_canary_requests=10,
        probe_set=probes, probe_min_accuracy=0.6,
        # CPU-noise guard: p99 on millisecond predicts is not a verdict
        p99_floor_ms=250.0, promote_stagger_s=0.2)
    server.rollout = rollout

    class Args:
        url = server.url
        model = "m"
        requests = 80
        concurrency = 8
        rate = None
        batch_sizes = [1, 2]
        priority_mix = None
        max_retries = 4
        retry_cap_s = 2.0
        deadline_ms = None
        timeout_s = 60.0
        seed = 0

    try:
        pump = _Pump(LoadGen(Args, (N_IN,))).start()
        time.sleep(1.0)                      # traffic flowing first
        rollout.start(interval_s=0.25)

        # while the canary is live: /v1/fleet must show the rollout and
        # the canary replica's own /readyz must agree (satellite 2)
        canary_seen = None
        deadline = time.monotonic() + PROMOTE_BUDGET_S
        while time.monotonic() < deadline:
            doc = _get_json(server.url + "/v1/fleet")
            ro = doc.get("rollout") or {}
            if ro.get("state") == "canary" and canary_seen is None:
                name = (ro.get("canary") or {}).get("replica")
                rep = next((r for r in doc.get("replicas", [])
                            if r.get("name") == name), None)
                readyz = {}
                if rep and rep.get("url"):
                    try:
                        readyz = _get_json(rep["url"] + "/readyz")
                    except OSError:
                        pass
                canary_seen = {"replica": name,
                               "fleet_role": (rep or {}).get("role"),
                               "readyz_role": readyz.get("role"),
                               "readyz_generation":
                                   readyz.get("rollout_generation")}
            verdict = rollout.describe()["last_verdict"]
            if verdict is not None:
                break
            time.sleep(0.2)
        traffic = pump.stop()
        verdict = rollout.describe()["last_verdict"]

        n5xx = _count_5xx(traffic["codes"])
        summary["promote"] = {
            "verdict": verdict,
            "canary_observed": canary_seen,
            "requests": traffic["requests"],
            "codes": traffic["codes"],
            "server_5xx": n5xx,
            "p99_ms": traffic["latency_ms"]["p99"],
        }
        if verdict is None or verdict.get("decision") != "promoted":
            failures.append(f"blessed checkpoint was not promoted within "
                            f"{PROMOTE_BUDGET_S:.0f}s: {verdict}")
        if n5xx:
            failures.append(f"{n5xx} 5xx during canary/promote "
                            f"(codes {traffic['codes']})")
        if traffic["codes"].get("transport"):
            failures.append("transport failures reached the client "
                            "during promote")
        if canary_seen is None:
            failures.append("/v1/fleet never surfaced the canary rollout")
        elif not (canary_seen["fleet_role"] == "canary"
                  and canary_seen["readyz_role"] == "canary"):
            failures.append("fleet view and replica /readyz disagree on "
                            f"the canary role: {canary_seen}")
        # every replica now serves the blessed source, and the shared
        # spec was rewritten (a later relaunch comes up on v2)
        actives = {}
        for r in supervisor.replicas:
            try:
                actives[r.name] = _active_source(r.url)
            except (OSError, KeyError, ValueError) as e:
                actives[r.name] = f"error: {e}"
        summary["promote"]["active_sources"] = actives
        if not all(src == v2_path for src in actives.values()):
            failures.append(f"fleet not fully on the promoted source: "
                            f"{actives}")
        if spec.models != [("m", v2_path)]:
            failures.append(f"ReplicaSpec not rewritten on promote: "
                            f"{spec.models}")

        # ------------- phase 3: poisoned blessing -> auto-rollback -------
        poison = _net(seed=99)               # untrained: ~chance accuracy
        t_poison = time.monotonic()
        p_path = trainer.ckpt.save(poison, {})
        trainer.ckpt.bless(p_path, {"accuracy": 0.99})   # the eval lied
        pump = _Pump(LoadGen(type("B", (Args,), {"seed": 3}),
                             (N_IN,))).start()
        deadline = time.monotonic() + ROLLBACK_BUDGET_S
        verdict = None
        while time.monotonic() < deadline:
            verdict = rollout.describe()["last_verdict"]
            if verdict and verdict.get("source") == p_path:
                break
            verdict = None
            time.sleep(0.2)
        detect_wall_s = time.monotonic() - t_poison
        traffic = pump.stop()
        n5xx = _count_5xx(traffic["codes"])
        summary["rollback"] = {
            "verdict": verdict,
            "detect_wall_s": round(detect_wall_s, 2),
            "codes": traffic["codes"],
            "server_5xx": n5xx,
        }
        if verdict is None or verdict.get("decision") != "rejected":
            failures.append("poisoned blessing was not rejected within "
                            f"{ROLLBACK_BUDGET_S:.0f}s: {verdict}")
        else:
            if verdict.get("metric") != "probe_accuracy":
                failures.append("rejection did not name probe_accuracy: "
                                f"{verdict.get('metric')}")
            if not verdict.get("rolled_back"):
                failures.append("canary was not rolled back: "
                                f"{verdict}")
        if n5xx:
            failures.append(f"{n5xx} 5xx while the poisoned canary was "
                            f"live (codes {traffic['codes']})")
        actives = {}
        for r in supervisor.replicas:
            try:
                actives[r.name] = _active_source(r.url)
            except (OSError, KeyError, ValueError) as e:
                actives[r.name] = f"error: {e}"
        summary["rollback"]["active_sources"] = actives
        if not all(src == v2_path for src in actives.values()):
            failures.append("fleet left the promoted source after the "
                            f"poison rollback: {actives}")

        # the postmortem receipt: reason + regressing metric + source
        pm = None
        if os.path.isdir(pm_dir):
            for fn in sorted(os.listdir(pm_dir)):
                if fn.startswith("postmortem-") and fn.endswith(".json"):
                    with open(os.path.join(pm_dir, fn)) as f:
                        doc = json.load(f)
                    if doc.get("reason") == "rollout_rejected":
                        pm = (fn, doc)
        if pm is None:
            failures.append("no rollout_rejected flight postmortem was "
                            f"dumped (dir {pm_dir})")
            summary["rollback"]["postmortem_metric"] = None
        else:
            fn, doc = pm
            meta = doc.get("meta", {})
            summary["rollback"]["postmortem"] = {"file": fn, "meta": meta}
            summary["rollback"]["postmortem_metric"] = meta.get("metric")
            if meta.get("metric") != "probe_accuracy" \
                    or meta.get("source") != p_path:
                failures.append("postmortem does not name the regressing "
                                f"metric + rejected source: {meta}")

        # rollout metric families (controller runs in this process)
        metrics = urllib.request.urlopen(server.url + "/metrics",
                                         timeout=10).read().decode()
        for fam in ("serving_rollout_state",
                    "serving_rollout_canaries_total",
                    "serving_rollout_promotions_total",
                    "serving_rollout_rollbacks_total",
                    "serving_rollout_promote_seconds",
                    "serving_rollout_rollback_detect_seconds"):
            if fam not in metrics:
                failures.append(f"/metrics missing {fam}")
    finally:
        rollout.stop()
        server.stop()
        supervisor.stop()

    # ---------------- phase 4: load-signal autoscaling -------------------
    spec2 = ReplicaSpec([("m", v2_path)], buckets=(1, 8), max_delay_ms=1.0,
                        queue_limit=128, default_deadline_s=10.0,
                        enable_faults=True)
    auto_cfg = AutoscaleConfig(min_replicas=1, max_replicas=3,
                               capacity_per_replica=2,
                               high_watermark=0.8, low_watermark=0.25,
                               up_after_ticks=2, down_after_ticks=4,
                               cooldown_s=2.0, drain_timeout_s=20.0)
    seen = {}

    def factory(i):
        r = InProcessReplica(f"auto-{i}", spec2)
        seen[r.name] = r
        return r

    sup2 = ReplicaSupervisor(factory, n_replicas=1, probe_interval_s=0.25,
                             probe_timeout_s=2.0, unhealthy_after=3,
                             restart_backoff_s=0.5, restart_budget=6,
                             autoscale=auto_cfg)
    sup2.start()
    deadline = time.monotonic() + 60.0
    while len(sup2.healthy()) < 1 and time.monotonic() < deadline:
        time.sleep(0.2)
    router2 = ResilientRouter(sup2.healthy, per_replica_inflight=16,
                              hedge=False, timeout_s=15.0)
    server2 = RouterServer(router2, supervisor=sup2, port=0)

    # slow every replica's predicts (0.3s) so offered rps translates to
    # sustained router in-flight — the load signal the autoscaler reads.
    # The injector keeps running so scale-up NEWCOMERS get slowed too.
    stop_inject = threading.Event()

    def inject():
        done = set()
        while not stop_inject.wait(0.25):
            for r in list(sup2.replicas):
                key = (r.name, r.generation)
                if key in done or r.state != "ready" or not r.url:
                    continue
                try:
                    urllib.request.urlopen(urllib.request.Request(
                        r.url + "/v1/faults",
                        data=json.dumps({"predict_delay_s": 0.3}).encode(),
                        headers={"Content-Type": "application/json"}),
                        timeout=5).read()
                    done.add(key)
                except OSError:
                    pass                     # retried next sweep

    injector = threading.Thread(target=inject, daemon=True,
                                name="rollout-drill-fault-injector")
    injector.start()

    class Args3:
        url = server2.url
        model = "m"
        requests = 0
        concurrency = 1
        rate = None
        batch_sizes = [1]
        priority_mix = None
        max_retries = 2
        retry_cap_s = 1.0
        deadline_ms = None
        timeout_s = 20.0
        seed = 7

    try:
        initial = len(sup2.replicas)
        gen3 = LoadGen(Args3, (N_IN,))
        # baseline -> surge past the high watermark -> near-idle
        wall, ok3 = gen3.run_ramp([(2, 6), (12, 12), (0.5, 10)],
                                  fleet_url=server2.url,
                                  sample_interval_s=0.5)
        ramp_rep = gen3.report(wall, ok3)
        peak = max((s["ready"] for s in ramp_rep["replicas_over_time"]),
                   default=initial)
        # after the ramp: wait for the fleet to drain back to the floor
        deadline = time.monotonic() + SCALE_DOWN_BUDGET_S
        while time.monotonic() < deadline:
            active = [r for r in sup2.replicas
                      if r.scaledown is None and r.state != "stopped"]
            if len(active) <= 1 and len(sup2.replicas) <= 1:
                break
            time.sleep(0.5)
        retired = [r for r in seen.values() if r.scaledown is not None]
        summary["autoscale"] = {
            "initial_replicas": initial,
            "peak_replicas": peak,
            "final_replicas": len(sup2.replicas),
            "ramp": ramp_rep["ramp"],
            "replicas_over_time": ramp_rep["replicas_over_time"],
            "codes": ramp_rep["codes"],
            "retired": [{"name": r.name,
                         "readyz_confirmed":
                             r.scaledown.get("readyz_confirmed"),
                         "forced_kill": r.scaledown.get("forced_kill")}
                        for r in retired],
            "forced_kills": sum(1 for r in retired
                                if r.scaledown.get("forced_kill")),
        }
        if peak <= initial:
            failures.append(f"ramp never scaled the fleet up "
                            f"(initial {initial}, peak {peak})")
        if len(sup2.replicas) > 1:
            failures.append("fleet did not scale back to the floor within "
                            f"{SCALE_DOWN_BUDGET_S:.0f}s "
                            f"({[r.describe() for r in sup2.replicas]})")
        if not retired:
            failures.append("no replica was drained on scale-down")
        for r in retired:
            if not r.scaledown.get("readyz_confirmed"):
                failures.append(f"{r.name}: retired without a confirmed "
                                "readyz flip (drain contract)")
            if r.scaledown.get("forced_kill"):
                failures.append(f"{r.name}: scale-down FORCED a kill "
                                "instead of draining")
    finally:
        stop_inject.set()
        injector.join(timeout=5)
        server2.stop()
        sup2.stop()
        flight.disable_flight()

    summary["calib_cpu_ms"] = round((calib_start + _calibrate()) / 2, 3)
    summary["ok"] = not failures
    summary["failures"] = failures
    promote_v = (summary.get("promote") or {}).get("verdict") or {}
    summary["sweep"] = [{
        "mode": "rollout", "on_tpu": False, "batch": None,
        # gated (host-calibrated) control-loop latencies
        "rollout_promote_s": promote_v.get("promote_s"),
        "rollout_rollback_detect_s":
            (summary.get("rollback") or {}).get("detect_wall_s"),
        # informational context for the banked row
        "rollout_observe_s": promote_v.get("observe_s"),
        "rollout_5xx": ((summary.get("promote") or {}).get("server_5xx", 0)
                        + (summary.get("rollback") or {}).get("server_5xx",
                                                              0)),
        "autoscale_peak_replicas":
            (summary.get("autoscale") or {}).get("peak_replicas"),
        "postmortem": ((summary.get("rollback") or {})
                       .get("postmortem") or {}).get("file"),
    }]
    out = json.dumps(summary, indent=1)
    print(out)
    if cli.out:
        with open(cli.out, "w") as f:
            f.write(out)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
