#!/usr/bin/env python
"""Telemetry smoke: a short instrumented fit must leave a scrapeable
/metrics payload and a valid Perfetto-loadable trace. CI-friendly:

    JAX_PLATFORMS=cpu python tools/telemetry_smoke.py --trace-out /tmp/t.json

Exercises every instrumented subsystem on CPU in one process:

- ResilientTrainer fit over an AsyncDataSetIterator (train + ETL +
  resilience series; one injected NaN step ticks
  resilience_steps_skipped_total) with the compiled-program ledger
  enabled (xla_* series + a live train_mfu_pct) AND the goodput ledger
  enabled — the fit's attributed category seconds must sum to its
  externally measured wall-clock within tolerance (the exclusivity
  contract) and the train_goodput_pct / train_time_seconds_total
  families must be live,
- ParallelInference BATCHED serving (inference + serving-side ledger),
- a two-rank SocketTransport exchange (transport series),

then asserts:

- GET /metrics on a live UIServer returns valid Prometheus text with
  >= 20 distinct metric families spanning train/ETL/transport/
  resilience/inference/xla, including xla_compile_seconds,
  xla_program_flops, xla_hbm_peak_bytes, and a train_mfu_pct gauge that
  carries a live nonzero value from the real fit,
- the perf-ledger JSON (monitor.xla.save_ledger) is schema-valid and
  holds >= 1 captured program with a fingerprint and flops,
- the Chrome trace JSON loads, spans nest (train/step inside
  resilience/fit), xla/compile spans appear, and at least two distinct
  thread tracks appear,
- traceparent propagation holds end-to-end through a live in-process
  fleet (router + 2 replicas over real HTTP): the router-minted
  trace_id comes back as X-Trace-Id AND appears in both a router span
  and a replica-side serving span; a client-supplied traceparent is
  adopted; the flight recorder exposes the request on
  GET /v1/debug/flight (router-aggregated) and the
  serving_flight_* / trace_* metric families are live,
- the SLO engine answers live over the same fleet: GET /v1/slo reports
  the router's engine enabled with a fleet verdict, /v1/timeseries
  serves windowed counter increases from real traffic, the
  ``?format=openmetrics`` exposition renders histogram trace exemplars
  and terminates with ``# EOF`` while the default v0.0.4 exposition
  stays exemplar-free, and the ``timeseries_*`` / ``slo_*`` metric
  families are live,
- tools/trace_report.py merges per-process segments into one valid
  Perfetto document with distinct process tracks (pid collisions
  remapped).

Exit code 0 on success, 1 on failure; prints a JSON summary either way.
"""
import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
# the plan-sharded ledger check (arg_shardings) needs a mesh to shard
# over: force the 8-virtual-device CPU topology (no-op when the caller
# already forced a count; only affects the CPU platform)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# CPU has no tabulated device peak: a nominal override keeps the MFU
# accountant live (the gauge's absolute value is synthetic on CPU — the
# smoke asserts liveness, not truth)
os.environ.setdefault("DL4J_TPU_PEAK_FLOPS", "1e12")

import numpy as np  # noqa: E402

GROUPS = {
    "train": ("train_",),
    "etl": ("etl_", "train_etl_"),
    "transport": ("transport_",),
    "resilience": ("resilience_",),
    "inference": ("inference_",),
    "xla": ("xla_",),
}

#: acceptance families the compiled-step observatory must expose
XLA_REQUIRED = ("xla_compile_seconds", "xla_program_flops",
                "xla_hbm_peak_bytes", "train_mfu_pct")

#: request-tracing + flight-recorder families (docs/OBSERVABILITY.md
#: "Tracing a single request")
TRACE_REQUIRED = ("trace_contexts_minted_total",
                  "serving_flight_records_total")

#: time-series ring + SLO engine families (docs/OBSERVABILITY.md "SLOs
#: and burn-rate alerting"); slo_alerts_total is deliberately absent —
#: a clean smoke run never transitions an alert
SLO_REQUIRED = ("timeseries_samples_total", "timeseries_sample_seconds",
                "timeseries_series", "slo_objective_ratio",
                "slo_burn_rate", "slo_alert_state")

#: goodput-ledger families (docs/OBSERVABILITY.md "Goodput accounting");
#: train_step_anomalies_total is deliberately absent — a clean smoke
#: fit never trips the anomaly detector
GOODPUT_REQUIRED = ("train_goodput_pct", "train_time_seconds_total")

#: exclusivity tolerance: attributed category seconds vs the externally
#: measured fit wall-clock (acceptance: within 5%, plus a small absolute
#: slack for the clock reads outside the session)
GOODPUT_SUM_TOL_FRAC = 0.05
GOODPUT_SUM_TOL_ABS_S = 0.25

#: top-level + per-program keys of the persisted perf-ledger schema
LEDGER_KEYS = ("version", "created_unix", "device_kind", "backend",
               "peak_flops", "hbm_bytes_per_sec", "programs")
PROGRAM_KEYS = ("fingerprint", "name", "domain", "arg_shapes", "hlo_hash",
                "compile_seconds", "compiles", "flops", "bytes_accessed",
                "arithmetic_intensity", "hbm", "hbm_peak_bytes",
                "examples_per_call", "steps_per_call",
                "total_flops_per_call", "arg_shardings", "sharded",
                "first_captured_unix")


def _net(seed=0):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _transport_exchange(failures):
    """One round-trip over the host-side DCN path (two in-process ranks)."""
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    base = 30200 + (os.getpid() % 5000)
    msg = (np.arange(4, dtype=np.int32), np.ones(4, np.float32), 0.5)
    try:
        with SocketTransport(0, 2, base_port=base) as t0, \
                SocketTransport(1, 2, base_port=base) as t1:
            t0.broadcast(0, msg)
            t1.broadcast(1, msg)
            t0.recv(1, timeout=20)
            t1.recv(1, timeout=20)
    except Exception as e:  # noqa: BLE001
        failures.append(f"transport exchange failed: {type(e).__name__}: {e}")


def _span_index(events):
    return [e for e in events if e.get("ph") == "X"]


def _nested(parent, child):
    eps = 1.0  # µs
    return (parent["tid"] == child["tid"]
            and parent["ts"] - eps <= child["ts"]
            and child["ts"] + child.get("dur", 0)
            <= parent["ts"] + parent.get("dur", 0) + eps)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--trace-out", default=None,
                   help="default: a fresh temp file")
    p.add_argument("--perf-ledger", default=None,
                   help="perf-ledger JSON path (default: alongside the "
                        "trace)")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=16)
    args = p.parse_args(argv)
    trace_path = args.trace_out or os.path.join(
        tempfile.mkdtemp(prefix="telemetry_smoke_"), "trace.json")
    ledger_path = args.perf_ledger or os.path.join(
        os.path.dirname(trace_path), "perf_ledger.json")

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.data.async_iterator import AsyncDataSetIterator
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference,
    )
    from deeplearning4j_tpu.train.listeners import PerformanceListener
    from deeplearning4j_tpu.train.resilience import ResilientTrainer
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.faults import FaultInjector

    monitor.enable_tracing()
    monitor.xla.enable_ledger(ledger_path)
    monitor.goodput.enable_goodput()
    failures = []
    summary = {"trace_out": trace_path, "perf_ledger": ledger_path}

    # ---- train + ETL + resilience -------------------------------------
    rs = np.random.RandomState(0)
    X = rs.randn(96, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 96)]
    net = _net()
    net.set_listeners(PerformanceListener(frequency=1, report=False))
    ckdir = tempfile.mkdtemp(prefix="telemetry_ck_")
    source = AsyncDataSetIterator(
        ArrayDataSetIterator(X, Y, batch_size=args.batch_size))
    trainer = ResilientTrainer(
        net, ckdir, save_every_n_iterations=4,
        injector=FaultInjector(nan_at=[3]))
    fit_t0 = time.perf_counter()
    report = trainer.fit(source, epochs=args.epochs,
                         batch_size=args.batch_size)
    fit_wall = time.perf_counter() - fit_t0
    summary["fit"] = {"applied": report.applied_steps,
                      "skipped": report.skipped_steps,
                      "checkpoints": report.checkpoints_written}
    if report.skipped_steps < 1:
        failures.append("injected NaN step was not skipped")

    # ---- goodput exclusivity: attributed seconds == measured wall ------
    if report.goodput_pct is None or not report.time_by_category:
        failures.append("FitReport carries no goodput session summary")
    else:
        attributed = sum(report.time_by_category.values())
        tol = max(GOODPUT_SUM_TOL_FRAC * fit_wall, GOODPUT_SUM_TOL_ABS_S)
        summary["goodput"] = {
            "goodput_pct": report.goodput_pct,
            "categories_s": {k: round(v, 4)
                             for k, v in report.time_by_category.items()},
            "attributed_s": round(attributed, 4),
            "measured_wall_s": round(fit_wall, 4)}
        if abs(attributed - fit_wall) > tol:
            failures.append(
                f"goodput exclusivity broke: categories sum to "
                f"{attributed:.3f}s but the fit measured {fit_wall:.3f}s "
                f"(tolerance {tol:.3f}s)")
        if any(v < 0 for v in report.time_by_category.values()):
            failures.append("goodput category went negative: "
                            f"{report.time_by_category}")

    # ---- GSPMD plan-sharded fit: arg_shardings lands in the ledger -----
    import jax
    from deeplearning4j_tpu.parallel.plan import ShardingPlan
    if len(jax.devices()) >= 2:
        pnet = _net(seed=3)
        Xp = rs.randn(128, 6).astype("float32")
        Yp = np.eye(3, dtype="float32")[rs.randint(0, 3, 128)]
        pnet.fit(ArrayDataSetIterator(Xp, Yp, batch_size=32), epochs=1,
                 plan=ShardingPlan(data=len(jax.devices())))
        sharded = [r for r in monitor.xla.records()
                   if r.is_sharded and any("'data'" in s
                                           for s in r.arg_shardings)]
        if not sharded:
            failures.append(
                "plan-sharded fit produced no ledger record carrying a "
                "'data' PartitionSpec in arg_shardings")
        summary["plan_sharded_programs"] = len(sharded)
    else:
        failures.append("no multi-device mesh for the plan-sharded "
                        "ledger check (device-count flag not applied?)")

    # ---- inference -----------------------------------------------------
    with ParallelInference(net, mode=InferenceMode.BATCHED,
                           max_batch_size=32) as pi:
        out = pi.output(X[:8])
    if out.shape != (8, 3):
        failures.append(f"inference output shape {out.shape} != (8, 3)")

    # ---- transport -----------------------------------------------------
    _transport_exchange(failures)

    # ---- serving fleet: traceparent propagation + flight recorder ------
    from deeplearning4j_tpu.monitor import flight
    from deeplearning4j_tpu.serving.fleet import (
        InProcessReplica, ReplicaSpec, ReplicaSupervisor,
    )
    from deeplearning4j_tpu.serving.router import (
        ResilientRouter, RouterServer,
    )
    flight.enable_flight(capacity=64, dump_dir=os.path.join(
        os.path.dirname(trace_path), "postmortems"))
    # SLO engine over the in-process time-series ring, watching the
    # router's own metric families (short windows: the smoke only needs
    # the machinery live, not SRE-workbook timescales)
    from deeplearning4j_tpu.monitor import slo as slo_mod
    from deeplearning4j_tpu.monitor import timeseries
    ring = timeseries.enable_timeseries(interval_s=0.2, capacity=512)
    slo_mod.enable_slo(
        slo_mod.router_objectives(slo_p99_ms=5000.0,
                                  availability_target=0.99),
        rules=(slo_mod.BurnRule("page", 5.0, 1.0, 14.4),), ring=ring)
    serve_net = _net(seed=7)
    spec = ReplicaSpec([("m", serve_net)], buckets=(1, 8),
                       max_delay_ms=1.0)
    supervisor = ReplicaSupervisor(
        lambda i: InProcessReplica(f"replica-{i}", spec), n_replicas=2,
        probe_interval_s=0.5)
    supervisor.start()
    router = ResilientRouter(supervisor.healthy, hedge=False)
    rserver = RouterServer(router, supervisor=supervisor, port=0)
    try:
        body = json.dumps(
            {"inputs": rs.rand(2, 6).astype("float32").tolist()}).encode()
        # 1) no client header: the ROUTER mints the context
        r = urllib.request.urlopen(urllib.request.Request(
            rserver.url + "/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-Priority": "interactive"}), timeout=30)
        r.read()
        minted = r.headers.get("X-Trace-Id")
        summary["router_minted_trace_id"] = minted
        if r.status != 200:
            failures.append(f"fleet predict answered {r.status}")
        if not minted:
            failures.append("router response carries no X-Trace-Id")
        # 2) client-supplied traceparent is ADOPTED, not replaced
        client_tid = "ab" * 16
        r = urllib.request.urlopen(urllib.request.Request(
            rserver.url + "/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{client_tid}-{'cd' * 8}-01"}),
            timeout=30)
        r.read()
        if r.headers.get("X-Trace-Id") != client_tid:
            failures.append(
                "client traceparent not adopted: X-Trace-Id "
                f"{r.headers.get('X-Trace-Id')} != {client_tid}")
        # 3) ONE trace_id spans router AND replica-side serving spans
        events = [e for e in monitor.trace_events()
                  if e.get("ph") == "X" and minted
                  and (e.get("args") or {}).get("trace_id") == minted]
        names = {e["name"] for e in events}
        summary["propagated_span_names"] = sorted(names)
        if "serving/route" not in names:
            failures.append("router-minted id missing from the "
                            "serving/route span")
        if not names & {"serving/request", "serving/batch",
                        "serving/queue_wait"}:
            failures.append(
                "router-minted id never reached a replica-side span "
                f"(got {sorted(names)}) — traceparent propagation broke")
        # 4) the router-aggregated flight endpoint shows the request
        fdoc = json.loads(urllib.request.urlopen(
            rserver.url + "/v1/debug/flight", timeout=10).read())
        router_recs = fdoc.get("router", {}).get("records", [])
        if minted and not any(rec.get("trace_id") == minted
                              for rec in router_recs):
            failures.append("router flight ring has no record for the "
                            "minted trace_id")
        if len(fdoc.get("replicas", {})) != 2:
            failures.append("router /v1/debug/flight did not aggregate "
                            "both replicas")
        elif minted and not any(
                rec.get("trace_id") == minted
                for rep in fdoc["replicas"].values()
                for rec in rep.get("records", [])):
            failures.append("no replica flight record carries the "
                            "minted trace_id")
        summary["flight_router_records"] = len(router_recs)
        # 5) SLO + time-series endpoints answer live over real traffic.
        # Bracket a known burst of predicts with explicit samples so the
        # windowed increase is deterministic (the background sampler
        # also runs; extra samples are harmless).
        ring.sample()
        for _ in range(3):
            urllib.request.urlopen(urllib.request.Request(
                rserver.url + "/v1/models/m/predict", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=30).read()
        ring.sample()
        slo_doc = json.loads(urllib.request.urlopen(
            rserver.url + "/v1/slo", timeout=10).read())
        summary["fleet_slo"] = slo_doc.get("fleet")
        if not slo_doc.get("router", {}).get("enabled"):
            failures.append("/v1/slo: router SLO engine not enabled")
        if len(slo_doc.get("replicas", {})) != 2:
            failures.append("/v1/slo did not poll both replicas")
        if slo_doc.get("fleet", {}).get("state") != "ok":
            failures.append("clean smoke traffic should leave the fleet "
                            f"SLO ok, got {slo_doc.get('fleet')}")
        ts_doc = json.loads(urllib.request.urlopen(
            rserver.url + "/v1/timeseries?series="
            "serving_router_requests_total&window=60", timeout=10).read())
        summary["timeseries_query"] = ts_doc
        if ts_doc.get("kind") != "counter" \
                or (ts_doc.get("increase") or 0) < 3:
            failures.append(
                "windowed /v1/timeseries increase did not cover the "
                f"predict burst: {ts_doc}")
        # 6) OpenMetrics opt-in renders exemplars + # EOF; the default
        # v0.0.4 exposition stays byte-compatible (no exemplars, no EOF)
        om = urllib.request.urlopen(
            rserver.url + "/metrics?format=openmetrics",
            timeout=10).read().decode()
        v004 = urllib.request.urlopen(
            rserver.url + "/metrics", timeout=10).read().decode()
        if not om.endswith("# EOF\n"):
            failures.append("openmetrics exposition missing # EOF "
                            "terminator")
        if ' # {trace_id="' not in om:
            failures.append("openmetrics exposition carries no histogram "
                            "trace exemplars")
        if "# EOF" in v004 or ' # {' in v004:
            failures.append("default /metrics exposition leaked "
                            "OpenMetrics syntax (v0.0.4 compat broke)")
    finally:
        slo_mod.disable_slo()       # engine first: it listens on the ring
        timeseries.disable_timeseries()
        supervisor.stop()
        rserver.stop()

    # ---- /metrics scrape ----------------------------------------------
    server = UIServer(port=0)
    try:
        body = urllib.request.urlopen(server.url + "metrics",
                                      timeout=10).read().decode()
    finally:
        server.stop()
    families = [ln.split()[2] for ln in body.splitlines()
                if ln.startswith("# TYPE ")]
    summary["metric_families"] = len(families)
    if len(families) < 20:
        failures.append(f"only {len(families)} metric families exposed "
                        f"(need >= 20): {families}")
    for group, prefixes in GROUPS.items():
        if not any(f.startswith(pre) for f in families for pre in prefixes):
            failures.append(f"no {group} metrics in /metrics exposition")
    for fam in XLA_REQUIRED:
        if fam not in families:
            failures.append(f"{fam} missing from /metrics exposition")
    for fam in TRACE_REQUIRED:
        if fam not in families:
            failures.append(f"{fam} missing from /metrics exposition")
    for fam in SLO_REQUIRED:
        if fam not in families:
            failures.append(f"{fam} missing from /metrics exposition")
    for fam in GOODPUT_REQUIRED:
        if fam not in families:
            failures.append(f"{fam} missing from /metrics exposition")

    # ---- static<->live family cross-check ------------------------------
    # graftlint rule (8) extracts the emitted families from the AST and
    # gates them against docs/OBSERVABILITY.md; the smoke consumes the
    # SAME extraction so the catalog check and the live scrape can't
    # drift apart: every family this live run exposed must be one the
    # static analysis knows about.
    from deeplearning4j_tpu.analysis import extract_metric_families
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    static_families = set(extract_metric_families(
        [os.path.join(repo, "deeplearning4j_tpu")]))
    summary["static_metric_families"] = len(static_families)
    unknown = sorted(f for f in families if f not in static_families)
    if unknown:
        failures.append(
            "live /metrics exposes families the static extraction (and "
            f"therefore the catalog gate) cannot see: {unknown} — "
            "dynamic family names bypass metric-family-registration")
    skip_ctr = monitor.REGISTRY.collect("resilience_steps_skipped_total")
    if skip_ctr is None or skip_ctr.value() < 1:
        failures.append("resilience_steps_skipped_total did not increment")

    # ---- compiled-program ledger ---------------------------------------
    mfu = monitor.REGISTRY.collect("train_mfu_pct")
    summary["train_mfu_pct"] = None if mfu is None else mfu.value()
    if mfu is None or mfu.value() <= 0:
        failures.append("train_mfu_pct gauge not live after the fit")
    compiles = monitor.REGISTRY.collect("xla_compiles_total")
    if compiles is None or not compiles._children:
        failures.append("xla_compiles_total never incremented")
    try:
        n_progs = monitor.xla.save_ledger(ledger_path)
        summary["ledger_programs"] = n_progs
        with open(ledger_path) as f:
            ledger = json.load(f)
        missing = [k for k in LEDGER_KEYS if k not in ledger]
        if missing:
            failures.append(f"perf ledger missing keys: {missing}")
        if not ledger.get("programs"):
            failures.append("perf ledger captured no programs")
        else:
            prog = ledger["programs"][0]
            missing = [k for k in PROGRAM_KEYS if k not in prog]
            if missing:
                failures.append(f"ledger program missing keys: {missing}")
            if not prog.get("fingerprint"):
                failures.append("ledger program has no fingerprint")
            if not any(p.get("flops") for p in ledger["programs"]):
                failures.append("no ledger program carries flops "
                                "(cost_analysis degraded on CPU?)")
    except (OSError, ValueError) as e:
        failures.append(f"perf ledger invalid: {type(e).__name__}: {e}")

    # ---- trace validity ------------------------------------------------
    n_events = monitor.save_trace(trace_path)
    summary["trace_events"] = n_events
    try:
        with open(trace_path) as f:
            doc = json.load(f)
        spans = _span_index(doc["traceEvents"])
        fits = [e for e in spans if e["name"] == "resilience/fit"]
        steps = [e for e in spans if e["name"] == "train/step"]
        if not fits or not steps:
            failures.append("missing resilience/fit or train/step spans")
        elif not any(_nested(f, s) for f in fits for s in steps):
            failures.append("train/step spans do not nest inside "
                            "resilience/fit")
        compiles = [e for e in spans if e["name"] == "xla/compile"]
        summary["xla_compile_spans"] = len(compiles)
        if not compiles:
            failures.append("no xla/compile spans in the trace")
        tids = {e["tid"] for e in spans}
        summary["trace_threads"] = len(tids)
        if len(tids) < 2:
            failures.append("expected spans from >= 2 threads "
                            "(train + prefetch/inference workers)")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"trace file invalid: {type(e).__name__}: {e}")

    # ---- merged-trace validity (tools/trace_report.py) -----------------
    # simulate the fleet layout: this process's saved trace plus a
    # second "replica" segment whose pid COLLIDES — the merge must
    # remap pids, name both process tracks, and stay JSON-valid
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    seg2_path = os.path.join(os.path.dirname(trace_path), "segment2.json")
    with open(seg2_path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "serving/request", "ph": "X", "ts": 1.0, "dur": 5.0,
             "pid": os.getpid(), "tid": 1,
             "args": {"trace_id": "ff" * 16}}]}, f)
    try:
        merged = trace_report.merge_trace_files(
            [("router", trace_path), ("replica", seg2_path)])
        json.loads(json.dumps(merged))        # round-trip validity
        procs = {e.get("pid") for e in merged["traceEvents"]}
        pnames = [e for e in merged["traceEvents"]
                  if e.get("ph") == "M" and e.get("name") == "process_name"]
        summary["merged_process_tracks"] = len(pnames)
        if len(procs) < 2 or len(pnames) < 2:
            failures.append(
                f"merged trace did not keep 2 process tracks apart "
                f"(pids {sorted(procs)}, {len(pnames)} names) — pid "
                "collision remap broke")
        if not trace_report.events_for_trace(merged, "ff" * 16):
            failures.append("merged trace lost the replica segment's "
                            "trace_id-carrying span")
    except (OSError, ValueError, KeyError) as e:
        failures.append(f"trace_report merge failed: "
                        f"{type(e).__name__}: {e}")

    summary["failures"] = failures
    summary["ok"] = not failures
    print(json.dumps(summary, indent=1))
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
