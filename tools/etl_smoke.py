#!/usr/bin/env python
"""ETL smoke: round-trip a small dataset through
writer -> shards -> multi-process shared-memory pipeline -> fit on CPU,
asserting the data plane's two contracts (CI-friendly):

1. **Bitwise parity** — every batch delivered by the multi-process ring
   (data/pipeline.MultiProcessDataSetIterator + ShardBatchLoader) equals
   the in-process reader path (ShardDataSetIterator) bit for bit, and
   the shard round-trip itself is lossless (uint8 payloads + int-id ->
   one-hot label rehydration).
2. **Telemetry** — a fit() through the full default data plane (ring ->
   AsyncDataSetIterator double-buffered device prefetch) exports the
   `etl_*` metric families, including `etl_fetch_wait_seconds` (the
   consumer-side wait that diagnoses ETL-bound fits) and the per-worker
   `etl_worker_*` series with `worker` labels.

Exit code 0 on success, 1 on failure; the LAST stdout line is a JSON
summary either way (the preceding lines are progress notes).

    JAX_PLATFORMS=cpu python tools/etl_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402


def run() -> dict:
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.data.pipeline import (
        MultiProcessDataSetIterator, ShardBatchLoader,
    )
    from deeplearning4j_tpu.data.shards import (
        ShardDataSetIterator, write_shards,
    )
    from deeplearning4j_tpu.data.normalization import (
        ImagePreProcessingScaler,
    )
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    summary = {"ok": False}
    rs = np.random.RandomState(0)
    n, h, w, c, classes, batch = 600, 12, 12, 1, 10, 50
    X = rs.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    Y = np.eye(classes, dtype="float32")[rs.randint(0, classes, n)]

    with tempfile.TemporaryDirectory() as td:
        # ---- writer -> shards
        index = write_shards(
            ArrayDataSetIterator(X, Y, batch_size=100, drop_last=False),
            td, shard_records=128)
        assert index["n_records"] == n, index
        assert index["num_classes"] == classes
        print(f"etl_smoke: wrote {len(index['shards'])} shards, "
              f"{n} records")

        # ---- in-process reader path (the parity reference)
        ref = list(ShardDataSetIterator(td, batch_size=batch,
                                        shuffle=True, seed=11))
        # shard round-trip is lossless vs the source arrays
        flat_order = list(ShardDataSetIterator(td, batch_size=batch))
        np.testing.assert_array_equal(flat_order[0].features, X[:batch])
        np.testing.assert_array_equal(flat_order[0].labels, Y[:batch])

        # ---- multi-process pipeline parity (bitwise, in order)
        with MultiProcessDataSetIterator(
                ShardBatchLoader(td, batch, shuffle=True, seed=11),
                num_workers=2, name="etl-smoke") as pipe:
            parity = 0
            for got, want in zip(pipe, ref):
                np.testing.assert_array_equal(got.features, want.features)
                np.testing.assert_array_equal(got.labels, want.labels)
                assert got.features.dtype == np.uint8
                parity += 1
            assert parity == len(ref) > 0
            summary["parity_batches"] = parity
            print(f"etl_smoke: {parity} batches bitwise-identical "
                  f"(ring vs in-process)")

            # ---- fit through the FULL default data plane: ring ->
            # async double-buffered device prefetch -> device-affine
            # normalization (uint8 over the wire)
            pipe.reset()
            pipe.set_pre_processor(ImagePreProcessingScaler())
            conf = (NeuralNetConfiguration.Builder().seed(0)
                    .updater(Adam(1e-2)).list()
                    .layer(DenseLayer(n_out=16, activation="relu"))
                    .layer(OutputLayer(n_out=classes,
                                       activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.convolutional(h, w, c))
                    .build())
            net = MultiLayerNetwork(conf).init()
            net.fit(pipe, epochs=2)
            assert np.isfinite(net.score()), net.score()
            summary["fit_score"] = float(net.score())
            summary["fit_iterations"] = net.iteration_count

    # ---- telemetry contract
    text = monitor.prometheus_text()
    for family in ("etl_fetch_wait_seconds", "etl_queue_depth",
                   "etl_batches_prefetched_total",
                   "etl_worker_batches_total", "etl_worker_decode_seconds",
                   "etl_ring_ready_depth"):
        assert family in text, f"metric family {family} not exported"
    assert 'worker="0"' in text or 'worker="1"' in text, \
        "per-worker ETL labels missing"
    wait = monitor.histogram("etl_fetch_wait_seconds").snapshot()
    summary["etl_fetch_wait_exported"] = True
    summary["etl_fetch_wait_count"] = int(wait.get("count", 0))
    summary["etl_fetch_wait_mean_s"] = round(
        wait["sum"] / wait["count"], 6) if wait.get("count") else 0.0
    summary["metric_families"] = sum(
        1 for line in text.splitlines() if line.startswith("# TYPE"))
    summary["ok"] = True
    return summary


def main() -> int:
    try:
        summary = run()
    except BaseException:
        traceback.print_exc()
        print(json.dumps({"ok": False}))
        return 1
    print(json.dumps(summary))
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
