"""Capture a jax.profiler trace of the ResNet-50 training step on TPU.

Runs a handful of warm per-call steps, then traces ~10 steps plus one
scan-of-10 invocation. The trace directory (/tmp/dl4jtpu_trace by
default) can be inspected with tensorboard or xprof; a one-line summary
of wall-per-step goes to stdout so PERF.md can quote it even if the
trace artifact is never pulled.
"""
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.util.env import env_flag, env_int, env_str

# CPU run allowed only for smoke-testing the script itself (tiny batch);
# the watcher always runs it on hardware
if env_flag("DL4J_TPU_TRACE_ALLOW_CPU", default=False):
    # the axon plugin force-appends itself to jax_platforms at import —
    # pin back to CPU or a wedged tunnel hangs the smoke in backend init
    jax.config.update("jax_platforms", "cpu")
else:
    assert jax.devices()[0].platform != "cpu", "need TPU"

import dataclasses

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph

TRACE_DIR = env_str("DL4J_TPU_TRACE_DIR", "/tmp/dl4jtpu_trace")
BATCH = env_int("DL4J_TPU_TRACE_BATCH", 128)
# input size knob so the ALLOW_CPU smoke can shrink the model (a 224x224
# ResNet-50 compile on CPU runs minutes; 64x64 is seconds)
HW = env_int("DL4J_TPU_TRACE_HW", 224)

model = ResNet50(num_classes=1000, input_shape=(HW, HW, 3))
conf = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
net = ComputationGraph(conf).init()
tx = net._tx

rs = np.random.RandomState(0)
X = jnp.asarray(rs.rand(BATCH, HW, HW, 3).astype("float32"))
Y = jnp.asarray(np.eye(1000, dtype="float32")[rs.randint(0, 1000, BATCH)])


def raw_step(params, opt_state, state, rng):
    def loss_fn(p):
        loss, (new_state, _) = net._score_fn(
            p, state, (X,), (Y,), None, None, True, rng)
        return loss, new_state
    (loss, new_state), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    updates, new_opt = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), new_opt, new_state, loss


# graftlint: disable=donated-aliasing -- params come from ComputationGraph.init() on-device in this process; nothing deserialized/numpy-backed reaches the donated args
jstep = jax.jit(raw_step, donate_argnums=(0, 1, 2))


@jax.jit
def scan10(p, o, s, rng):
    def body(carry, _):
        cp, co, cs, cr = carry
        cr, sub = jax.random.split(cr)
        cp, co, cs, loss = raw_step(cp, co, cs, sub)
        return (cp, co, cs, cr), loss
    (p, o, s, rng), losses = lax.scan(body, (p, o, s, rng), jnp.arange(10))
    return p, o, s, losses[-1]


p, o, s = net.params, net.opt_state, net.state
rng = jax.random.PRNGKey(0)

# warm both programs (compile outside the trace window)
p, o, s, loss = jstep(p, o, s, rng)
float(loss)
p, o, s, loss = scan10(p, o, s, rng)
float(loss)
print("warm done", flush=True)

t0 = time.perf_counter()
with jax.profiler.trace(TRACE_DIR):
    for i in range(10):
        p, o, s, loss = jstep(p, o, s, jax.random.fold_in(rng, i))
    float(loss)
    t_per_call = time.perf_counter() - t0
    t1 = time.perf_counter()
    p, o, s, loss = scan10(p, o, s, rng)
    float(loss)
    t_scan = time.perf_counter() - t1

print(f"trace saved to {TRACE_DIR}", flush=True)
# platform stamp on the throughput line, and no "imgs/s" text at all on a
# CPU run: the watcher banks this log on `grep imgs/s`, so a
# DL4J_TPU_TRACE_ALLOW_CPU smoke run must never look like a hardware
# measurement (mirrors bench.py's per-row on_tpu guard)
_plat = jax.devices()[0].device_kind
if jax.devices()[0].platform == "cpu":
    print(f"[{_plat}] CPU smoke only — throughput suppressed "
          f"(per-call {t_per_call * 100:.1f} ms/step, "
          f"scan10 {t_scan * 100:.1f} ms/step)", flush=True)
else:
    print(f"[{_plat}] per-call: {10 * BATCH / t_per_call:.1f} imgs/s "
          f"({t_per_call * 100:.1f} ms/step); "
          f"scan10: {10 * BATCH / t_scan:.1f} imgs/s "
          f"({t_scan * 100:.1f} ms/step)", flush=True)
