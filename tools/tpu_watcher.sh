#!/bin/bash
# Background TPU watcher: probe the axon tunnel every ~3 min; on every
# healthy answer, run the next queued hardware job (bench sweep first,
# then the Pallas flash first-contact smoke, then reruns) so no healthy
# hardware minute is wasted. Log to /tmp/tpu_watch.log.
#
# The bench itself (bench.py, round-5 architecture) is wedge-tolerant:
# each config runs in a subprocess with a watchdog, results stream to
# /tmp/bench_partial.jsonl, and a mid-sweep wedge yields a partial JSON
# instead of a hang — so even an unlucky window produces numbers.
PROBE='import jax,sys; ds=jax.devices(); sys.exit(0 if ds and ds[0].platform!="cpu" else 3)'
LOG=/tmp/tpu_watch.log
echo "watcher start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  timeout 180 python -c "$PROBE" >/dev/null 2>&1
  rc=$?
  echo "probe rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
  if [ "$rc" = "0" ]; then
    touch /tmp/tpu_up
    if [ ! -f /tmp/bench_tpu_done ]; then
      echo "TPU UP — running bench $(date -u +%FT%TZ)" >> "$LOG"
      # outer timeout > worst case (9 configs x 1800s watchdog + probes);
      # bench.py kills its in-flight config subprocess on SIGTERM
      (cd /root/repo && timeout -k 60 18000 python bench.py > /tmp/bench_tpu.json 2>/tmp/bench_tpu.err)
      brc=$?
      echo "bench rc=$brc $(date -u +%FT%TZ)" >> "$LOG"
      # done only if the sweep produced a real TPU number — a CPU-fallback
      # run also prints a numeric value but with tpu_unavailable: true
      if [ "$brc" = "0" ] && grep -q '"value": [0-9]' /tmp/bench_tpu.json \
         && grep -q '"tpu_unavailable": false' /tmp/bench_tpu.json; then
        touch /tmp/bench_tpu_done
      fi
    elif [ ! -f /tmp/flash_smoke_done ]; then
      echo "TPU UP — running flash smoke $(date -u +%FT%TZ)" >> "$LOG"
      (cd /root/repo && timeout 3600 python tools/flash_smoke.py > /tmp/flash_smoke.log 2>&1)
      src=$?
      echo "flash smoke rc=$src $(date -u +%FT%TZ)" >> "$LOG"
      [ "$src" = "0" ] && touch /tmp/flash_smoke_done
      # nonzero rc still counts as contact if it printed results;
      # leave undone so a later healthy window can retry
    elif [ ! -f /tmp/trace_done ]; then
      echo "TPU UP — capturing profiler trace $(date -u +%FT%TZ)" >> "$LOG"
      (cd /root/repo && timeout 2400 python tools/profile_capture.py > /tmp/trace_capture.log 2>&1)
      trc=$?
      echo "trace rc=$trc $(date -u +%FT%TZ)" >> "$LOG"
      [ "$trc" = "0" ] && touch /tmp/trace_done
    else
      sleep 420   # all jobs done; stay armed for manual reruns
    fi
    sleep 30
  else
    sleep 170
  fi
done
