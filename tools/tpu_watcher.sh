#!/bin/bash
# Background TPU watcher: probe the axon tunnel every ~4 min; on first
# healthy answer, mark /tmp/tpu_up and run the full bench sweep so no
# healthy hardware minute is wasted. Log everything to /tmp/tpu_watch.log.
PROBE='import jax,sys; ds=jax.devices(); sys.exit(0 if ds and ds[0].platform!="cpu" else 3)'
LOG=/tmp/tpu_watch.log
echo "watcher start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  timeout 180 python -c "$PROBE" >/dev/null 2>&1
  rc=$?
  echo "probe rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
  if [ "$rc" = "0" ]; then
    touch /tmp/tpu_up
    echo "TPU UP — running bench $(date -u +%FT%TZ)" >> "$LOG"
    (cd /root/repo && timeout 3000 python bench.py > /tmp/bench_tpu.json 2>/tmp/bench_tpu.err)
    echo "bench rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    # keep watching in case we want reruns; but slow down
    sleep 600
  else
    sleep 240
  fi
done
