#!/bin/bash
# Background TPU watcher: probe the axon tunnel every ~3 min; on every
# healthy answer, run the next queued hardware job (bench sweep first,
# then the Pallas flash first-contact smoke, then reruns) so no healthy
# hardware minute is wasted. Log: $REPO/.watcher/watch.log.
#
# The bench itself (bench.py, round-5 architecture) is wedge-tolerant:
# each config runs in a subprocess with a watchdog, results stream to
# $DL4J_TPU_BENCH_PARTIAL, and a mid-sweep wedge yields a partial JSON
# instead of a hang — so even an unlucky window produces numbers.
PROBE='import jax,sys; ds=jax.devices(); sys.exit(0 if ds and ds[0].platform!="cpu" else 3)'
# Stage done-flags, window accumulators and in-flight outputs live in a
# REPO-LOCAL state dir (gitignored): /tmp is wiped between builder
# sessions, and losing the flags made a fresh session re-run stages whose
# results were already banked at HEAD (overwriting analyzed artifacts).
# REPO override is for the unit tests (tests/test_watcher.py)
REPO="${DL4J_TPU_WATCHER_REPO:-/root/repo}"
STATE="$REPO/.watcher"
mkdir -p "$STATE"
LOG="$STATE/watch.log"
# derive stage-1 done from the repo itself: if a fully-measured sweep is
# already banked at HEAD, never re-run stage 1 (it would overwrite the
# artifact PERF.md's analysis quotes). COMMITTED at HEAD, not just in
# the worktree — a stranded copy left by a failed bank() must keep the
# stage live so a later window rebanks it.
if [ ! -f "$STATE/bench_tpu_done" ] \
   && (cd "$REPO" \
       && git ls-files --error-unmatch -- BENCH_TPU_MEASURED_r05.json >/dev/null 2>&1 \
       && git diff --quiet HEAD -- BENCH_TPU_MEASURED_r05.json) \
   && grep -q '"tpu_unavailable": false' "$REPO/BENCH_TPU_MEASURED_r05.json" 2>/dev/null \
   && grep -q '"value": [0-9]' "$REPO/BENCH_TPU_MEASURED_r05.json" 2>/dev/null; then
  touch "$STATE/bench_tpu_done"
  echo "stage-1 done derived from banked BENCH_TPU_MEASURED_r05.json $(date -u +%FT%TZ)" >> "$LOG"
fi
# headline per-call program is a disk-cache hit after first contact, so a
# healthy config needs ~2 min; 600 s cuts wedge recovery from 30 min to 10
export DL4J_TPU_BENCH_CONFIG_TIMEOUT="${DL4J_TPU_BENCH_CONFIG_TIMEOUT:-600}"
# same default bench.py uses; export so both sides agree even if the
# operator overrides it
export DL4J_TPU_BENCH_PARTIAL="${DL4J_TPU_BENCH_PARTIAL:-/tmp/bench_partial.jsonl}"

# bank <src> <dest-name> <msg>: copy a measurement artifact into the repo
# and commit ONLY that path, retrying around a concurrent session's
# .git/index.lock. Pathspec'd commit so anything the session has staged is
# neither swept into this commit nor lost. Idempotent: identical content
# already at HEAD counts as banked (no retry burn, no false alarm).
bank() {
  if ! cp "$1" "$REPO/$2"; then
    echo "bank FAILED for $2: cp $1 failed $(date -u +%FT%TZ)" >> "$LOG"
    return 1
  fi
  if (cd "$REPO" && git ls-files --error-unmatch -- "$2" >/dev/null 2>&1 \
      && git diff --quiet HEAD -- "$2"); then
    echo "bank: $2 already at HEAD $(date -u +%FT%TZ)" >> "$LOG"
    return 0
  fi
  for i in 1 2 3 4 5; do
    if (cd "$REPO" && git add -- "$2" \
        && git commit -q -m "$3" \
            -m "No-Verification-Needed: measurement artifact, no code change" \
            -- "$2"); then
      echo "banked $2 $(date -u +%FT%TZ)" >> "$LOG"
      return 0
    fi
    sleep 20
  done
  # unstage so a concurrent session's plain `git commit` can't sweep the
  # artifact into an unrelated commit
  (cd "$REPO" && git reset -q -- "$2") || true
  echo "bank FAILED for $2 (index lock?) $(date -u +%FT%TZ)" >> "$LOG"
  return 1
}

# bank_windowed <src> <tmp-accum> <dest-name> <msg>: append <src> to the
# /tmp accumulator under a JSON window-marker row (keeps .jsonl artifacts
# line-parseable), then bank the accumulator. Seeds the accumulator from
# the repo copy when /tmp was wiped, so earlier windows' rows genuinely
# survive at HEAD. Skips the append when the payload is byte-identical to
# the previous window's (a deterministic repeating failure must not grow
# the artifact or mint a commit per probe).
bank_windowed() {
  [ -s "$2" ] || { [ -f "$REPO/$3" ] && cp "$REPO/$3" "$2"; }
  local sum; sum=$(md5sum < "$1" | cut -d' ' -f1)
  if [ -f "$2.lastsum" ] && [ "$(cat "$2.lastsum")" = "$sum" ]; then
    echo "bank_windowed: $3 payload unchanged, skipping $(date -u +%FT%TZ)" >> "$LOG"
    return 0
  fi
  { echo "{\"window\": \"$(date -u +%FT%TZ)\"}"; cat "$1"; } >> "$2"
  bank "$2" "$3" "$4" && echo "$sum" > "$2.lastsum"
}

# measured_row <json> <kind>: true iff the sweep JSON holds a MEASURED
# on-TPU row for that config kind — error/skipped rows also contain the
# kind name (bench.py stamps {**canon(cfg), "error"/"skipped": ...}), so
# a plain grep would retire a retry stage on a wedge; parse properly.
measured_row() {
  python - "$1" "$2" <<'PYEOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(1)
rows = d.get("sweep", [])
# measured rows are the runner's own dict and label themselves via
# "mode" (e.g. {"mode": "char-lstm", "chars_sec": ...}); only the
# error/skipped paths spread the config and carry "kind"
ok = any(sys.argv[2] in (r.get("kind"), r.get("mode"))
         and r.get("on_tpu")
         and "error" not in r and "skipped" not in r for r in rows)
sys.exit(0 if ok else 1)
PYEOF
}

# run_sweep <out-json> <done-flag> <required-kind> <label> <dest>: run the
# full bench sweep; bank a fully-measured result (rc=0 +
# tpu_unavailable:false + a MEASURED row of required-kind if given) into
# <dest>, else bank any on_tpu partial rows. The ONE implementation both
# sweep stages share. Distinct <dest> per stage keeps the artifact
# PERF.md's analysis quotes intact at HEAD.
run_sweep() {
  local out="$1" flag="$2" need="$3" label="$4" dest="$5"
  # fresh partial file per attempt; rows already banked in-repo from
  # earlier windows are preserved there (bank_windowed)
  : > "$DL4J_TPU_BENCH_PARTIAL"
  # outer timeout > worst case (configs x watchdog + probes); bench.py
  # kills its in-flight config subprocess on SIGTERM
  (cd "$REPO" && timeout -k 60 18000 python bench.py > "$out" 2>"${out%.json}.err")
  local rc=$?
  echo "$label rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
  # done only if the sweep produced a real TPU number — a CPU-fallback
  # run also prints a numeric value but with tpu_unavailable: true.
  # done-flag only AFTER a successful bank — a stranded /tmp artifact
  # must keep this branch live for the next window to rebank
  if [ "$rc" = "0" ] && grep -q '"value": [0-9]' "$out" \
     && grep -q '"tpu_unavailable": false' "$out" \
     && { [ -z "$need" ] || measured_row "$out" "$need"; }; then
    bank "$out" "$dest" \
      "Bank measured TPU bench sweep ($label $(date -u +%FT%TZ))" \
      && touch "$flag"
  elif grep -q '"on_tpu": true' "$DL4J_TPU_BENCH_PARTIAL" 2>/dev/null; then
    # sweep didn't fully land but some configs DID measure ON TPU — bank
    # those rows. Guard is per-row: every bench runner stamps its result
    # with the platform it executed on, so a CPU row can never be banked
    grep '"on_tpu": true' "$DL4J_TPU_BENCH_PARTIAL" > /tmp/bench_tpu_rows.jsonl
    bank_windowed /tmp/bench_tpu_rows.jsonl $STATE/bench_windowed.jsonl \
      BENCH_TPU_PARTIAL_r05.jsonl \
      "Bank partial TPU bench rows ($label window $(date -u +%FT%TZ))"
  fi
}

# sourced (tests/test_watcher.py): expose the functions + the stage-1
# derive above, skip the probe loop
if [ "${BASH_SOURCE[0]}" != "$0" ]; then
  return 0 2>/dev/null || exit 0
fi

echo "watcher start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  timeout 180 python -c "$PROBE" >/dev/null 2>&1
  rc=$?
  echo "probe rc=$rc $(date -u +%FT%TZ)" >> "$LOG"
  if [ "$rc" = "0" ]; then
    touch /tmp/tpu_up
    if [ ! -f $STATE/bench_tpu_done ]; then
      # a measured sweep stranded in /tmp by a failed bank (index-lock
      # exhaustion) must be rebanked BEFORE the rerun truncates it
      if [ -f $STATE/bench_tpu.json ] \
         && grep -q '"value": [0-9]' $STATE/bench_tpu.json \
         && grep -q '"tpu_unavailable": false' $STATE/bench_tpu.json; then
        bank $STATE/bench_tpu.json BENCH_TPU_MEASURED_r05.json \
          "Bank measured TPU bench sweep (recovered stranded result)" \
          && touch $STATE/bench_tpu_done
        # whether or not the bank landed, never fall through to a rerun
        # this window — the rerun's truncation is the loss this guards
        continue
      fi
      echo "TPU UP — running bench $(date -u +%FT%TZ)" >> "$LOG"
      run_sweep $STATE/bench_tpu.json $STATE/bench_tpu_done "" "bench" \
        BENCH_TPU_MEASURED_r05.json
    elif [ ! -f $STATE/bench2_done ]; then
      # second full sweep FIRST in the queue after the headline bank:
      # it completes BASELINE.md's config coverage (the 01:28Z wedge
      # cut off char-lstm / word2vec / lenet) AND runs the fixed
      # attention micro — the first flash-vs-dense hardware timing —
      # so it outranks the dedicated flash smoke now that Mosaic
      # lowering is CI-proven (tests/test_tpu_lowering.py). resnet
      # programs are compile-cache hits; done-gate requires a
      # MEASURED char-lstm row. Distinct artifact keeps the r05 JSON
      # PERF.md quotes byte-stable at HEAD.
      echo "TPU UP — bench sweep 2 (full config set) $(date -u +%FT%TZ)" >> "$LOG"
      run_sweep $STATE/bench_tpu2.json $STATE/bench2_done "char-lstm" "bench2" \
        BENCH_TPU_MEASURED_r05b.json
    elif [ ! -f $STATE/flash_smoke_done ]; then
      echo "TPU UP — running flash smoke $(date -u +%FT%TZ)" >> "$LOG"
      (cd "$REPO" && timeout 3600 python tools/flash_smoke.py > /tmp/flash_smoke.log 2>&1)
      src=$?
      echo "flash smoke rc=$src $(date -u +%FT%TZ)" >> "$LOG"
      # bank only logs that carry real kernel results (FWD/BWD/LSE lines,
      # not a bare traceback); done-flag needs BOTH rc=0 and a successful
      # bank so results can't be stranded in /tmp; a failed window leaves
      # the flag unset and a later healthy window retries
      if grep -q ': err=' /tmp/flash_smoke.log 2>/dev/null; then
        # ': err=' matches only genuine kernel-result lines — an
        # all-exception log (every kernel raising on first contact)
        # prints 'FWD x: EXC ...' lines and is not banked
        bank_windowed /tmp/flash_smoke.log $STATE/flash_smoke_windowed.log \
          FLASH_SMOKE_r05.log \
          "Bank Pallas flash first-contact smoke log (rc=$src)" \
          && [ "$src" = "0" ] && touch $STATE/flash_smoke_done
      fi
    elif [ ! -f $STATE/trace_done ]; then
      echo "TPU UP — capturing profiler trace $(date -u +%FT%TZ)" >> "$LOG"
      (cd "$REPO" && timeout 2400 python tools/profile_capture.py > /tmp/trace_capture.log 2>&1)
      trc=$?
      echo "trace rc=$trc $(date -u +%FT%TZ)" >> "$LOG"
      # the trace run also prints measured per-call/scan10 throughput —
      # bank the log whenever those numbers landed
      if grep -q 'imgs/s' /tmp/trace_capture.log 2>/dev/null; then
        bank_windowed /tmp/trace_capture.log $STATE/trace_windowed.log \
          TRACE_CAPTURE_r05.log \
          "Bank profiler-trace capture log (rc=$trc)" \
          && [ "$trc" = "0" ] && touch $STATE/trace_done
      fi
    elif [ ! -f $STATE/mfu_probe_done ]; then
      # 5400s: fwd-only and fwd+bwd are cold compiles through the tunnel;
      # only the full-step program shares the bench's compile cache
      echo "TPU UP — running mfu probe $(date -u +%FT%TZ)" >> "$LOG"
      (cd "$REPO" && timeout 5400 python tools/mfu_probe.py \
        > /tmp/mfu_probe.log 2>/tmp/mfu_probe.err)
      mrc=$?
      echo "mfu probe rc=$mrc $(date -u +%FT%TZ)" >> "$LOG"
      # per-row on_tpu stamps guard against CPU rows, as in the bench
      if grep -q '"on_tpu": true' /tmp/mfu_probe.log 2>/dev/null; then
        bank_windowed /tmp/mfu_probe.log $STATE/mfu_windowed.jsonl \
          MFU_PROBE_r05.jsonl \
          "Bank MFU calibration probe (matmul peak + step segments, rc=$mrc)" \
          && [ "$mrc" = "0" ] && touch $STATE/mfu_probe_done
      fi
    elif [ ! -f $STATE/s2d_done ]; then
      # space-to-depth stem A/B: resnet configs only (exactly-equivalent
      # model, MXU-friendlier head conv — models/zoo.py
      # s2d_stem_weights). Needs a MEASURED resnet row to retire.
      echo "TPU UP — s2d stem A/B sweep $(date -u +%FT%TZ)" >> "$LOG"
      DL4J_TPU_BENCH_S2D=1 DL4J_TPU_BENCH_LSTM=0 DL4J_TPU_BENCH_W2V=0 \
      DL4J_TPU_BENCH_LENET=0 DL4J_TPU_BENCH_ATTENTION=0 \
      DL4J_TPU_BENCH_H2D=0 DL4J_TPU_BENCH_BATCHES=128 \
        run_sweep $STATE/bench_s2d.json $STATE/s2d_done "" "s2d" \
          BENCH_TPU_S2D_r05.json
    else
      sleep 420   # all jobs done; stay armed for manual reruns
    fi
    sleep 30
  else
    # short sleep when down: a wedged probe already burns its 180s
    # timeout, and observed healthy windows last only ~5-10 min — a
    # ~4 min down-cycle can miss one entirely, a ~2.5 min one won't
    sleep 50
  fi
done
