#!/usr/bin/env python
"""Goodput acceptance run: wall-clock waterfalls for a clean and a
throttled fit, the exclusivity check, and the anomaly postmortem drill.

    JAX_PLATFORMS=cpu python tools/goodput_report.py [--out GOODPUT.json]

Two instrumented ResilientTrainer fits on CPU (monitor/goodput.py — see
docs/OBSERVABILITY.md "Goodput accounting"):

1. **Clean** — checkpoint saves + an eval gate every 16 steps, so every
   category of the partition gets exercised. Asserts the exclusivity
   contract: the categories sum to an externally measured fit wall-clock
   within 5%.
2. **Throttled ETL** — a `FaultInjector(etl_stall_at=..., etl_stall_s=...)`
   freezes the input pipeline mid-run (no checkpoint saves scheduled
   before it, so nothing shadows the trip inside the detector cooldown).
   Asserts the stall lands in ``data_wait``, the step-time anomaly
   detector fires, and the auto-dumped flight postmortem names
   ``data_wait`` as the dominant category WITH all-thread stack
   snapshots attached.

Prints a JSON report with a bench-style "sweep" row carrying
``train_goodput_pct`` of the clean fit (a dimensionless ratio:
tools/perf_report.py gates it raw, calibration-exempt) plus the
``calib_cpu_ms`` machine-speed reference for the banked wall-second
context (GOODPUT_r*.json). Exit 0 iff every assertion held.
"""
import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

N_IN, N_OUT = 6, 3
SUM_TOL_FRAC = 0.05             # acceptance: categories-vs-wall miss
SUM_TOL_ABS_S = 0.25            # floor for very short CPU fits
STALL_STEP, STALL_S = 30, 0.5


def _blobs(n=480, seed=0):
    import numpy as np
    rs = np.random.RandomState(seed)
    X = rs.randn(n, N_IN).astype("float32")
    Y = np.eye(N_OUT, dtype="float32")[rs.randint(0, N_OUT, n)]
    return X, Y


def _net(seed=7):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


def _data():
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    X, Y = _blobs()
    return ArrayDataSetIterator(X, Y, batch_size=10)   # 48 steps/epoch


def _waterfall(title, summary, fit_wall):
    print(f"\n{title}: wall {summary['wall_s']:.3f}s "
          f"(stopwatch {fit_wall:.3f}s), {summary['steps']} steps, "
          f"goodput {summary['goodput_pct']:.1f}%")
    cats = summary["categories"]
    for name in sorted(cats, key=cats.get, reverse=True):
        secs = cats[name]
        if secs <= 0:
            continue
        bar = "#" * max(1, int(40 * secs / max(summary["wall_s"], 1e-9)))
        print(f"  {name:<14} {secs:>8.3f}s  {bar}")


def run_clean(workdir, failures):
    from deeplearning4j_tpu.monitor import goodput
    from deeplearning4j_tpu.train import FaultPolicy, ResilientTrainer
    goodput.enable_goodput()
    try:
        trainer = ResilientTrainer(
            _net(), os.path.join(workdir, "clean"),
            save_every_n_iterations=16,
            policy=FaultPolicy(backoff_base=0.001, backoff_max=0.004),
            eval_gate=lambda net: {"score": float(net.score() or 0.0)})
        t0 = time.perf_counter()
        report = trainer.fit(_data(), epochs=1)
        fit_wall = time.perf_counter() - t0
    finally:
        summary = goodput.last_session()
        goodput.disable_goodput()
    if summary is None or report.goodput_pct is None:
        failures.append("clean: no goodput session recorded")
        return {"error": "no session"}
    _waterfall("clean fit", summary, fit_wall)
    attributed = sum(report.time_by_category.values())
    tol = max(SUM_TOL_FRAC * fit_wall, SUM_TOL_ABS_S)
    if abs(attributed - fit_wall) > tol:
        failures.append(
            f"clean: exclusivity broken — categories sum to "
            f"{attributed:.3f}s vs {fit_wall:.3f}s stopwatch (tol {tol:.3f})")
    for name in ("checkpoint", "eval_gate", "data_wait"):
        if report.time_by_category.get(name, 0.0) <= 0.0:
            failures.append(f"clean: category {name!r} never attributed")
    return {"summary": summary, "fit_wall_s": round(fit_wall, 6),
            "attributed_s": round(attributed, 6),
            "exclusivity_miss_s": round(abs(attributed - fit_wall), 6)}


def run_throttled(workdir, failures):
    from deeplearning4j_tpu.monitor import flight, goodput
    from deeplearning4j_tpu.train import FaultPolicy, ResilientTrainer
    from deeplearning4j_tpu.util.faults import FaultInjector
    pm_dir = os.path.join(workdir, "postmortems")
    flight.enable_flight(dump_dir=pm_dir)
    goodput.enable_goodput(anomaly_min_s=0.05)
    try:
        trainer = ResilientTrainer(
            _net(seed=11), os.path.join(workdir, "throttled"),
            save_every_n_iterations=10_000,   # nothing shadows the trip
            policy=FaultPolicy(backoff_base=0.001, backoff_max=0.004),
            injector=FaultInjector(etl_stall_at=[STALL_STEP],
                                   etl_stall_s=STALL_S))
        t0 = time.perf_counter()
        report = trainer.fit(_data(), epochs=1)
        fit_wall = time.perf_counter() - t0
    finally:
        summary = goodput.last_session()
        goodput.disable_goodput()
        docs = [d for d in flight.postmortems()
                if d["reason"] == "step_time_anomaly"]
        flight.disable_flight()
    if summary is None:
        failures.append("throttled: no goodput session recorded")
        return {"error": "no session"}
    _waterfall("throttled fit", summary, fit_wall)
    data_wait = summary["categories"]["data_wait"]
    if data_wait < STALL_S:
        failures.append(f"throttled: injected {STALL_S}s ETL stall but "
                        f"data_wait={data_wait:.3f}s")
    if summary["anomalies"] < 1:
        failures.append("throttled: the stall never tripped the "
                        "step-time anomaly detector")
    out = {"summary": summary, "fit_wall_s": round(fit_wall, 6),
           "goodput_pct": report.goodput_pct}
    if not docs:
        failures.append("throttled: no step_time_anomaly postmortem")
        return out
    doc = docs[-1]
    meta = doc["meta"]
    print(f"  postmortem: step {meta.get('step')}, "
          f"iteration wall {meta.get('iteration_wall_s')}s "
          f"(median {meta.get('median_s')}s), dominant "
          f"{meta.get('dominant_category')} "
          f"({meta.get('dominant_seconds')}s), "
          f"{len(doc.get('threads', []))} thread stacks")
    if meta.get("dominant_category") != "data_wait":
        failures.append(f"throttled: postmortem blames "
                        f"{meta.get('dominant_category')!r}, not data_wait")
    if not doc.get("threads"):
        failures.append("throttled: postmortem has no thread stacks")
    dumps = glob.glob(os.path.join(pm_dir, "*step_time_anomaly*.json"))
    if not dumps:
        failures.append("throttled: postmortem JSON not dumped to disk")
    out["postmortem"] = {
        "dominant_category": meta.get("dominant_category"),
        "step": meta.get("step"),
        "iteration_wall_s": meta.get("iteration_wall_s"),
        "n_threads": len(doc.get("threads", [])),
        "dumped": [os.path.basename(p) for p in dumps]}
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the JSON report to PATH")
    args = p.parse_args(argv)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from decode_smoke import _calibrate
    calib_start = _calibrate()

    failures = []
    with tempfile.TemporaryDirectory(prefix="goodput_report_") as workdir:
        clean = run_clean(workdir, failures)
        throttled = run_throttled(workdir, failures)

    summary = {
        "clean": clean,
        "throttled": throttled,
        "calib_cpu_ms": round((calib_start + _calibrate()) / 2, 3),
        "ok": not failures,
        "failures": failures,
        "sweep": [{
            "mode": "goodput_fit", "on_tpu": False, "batch": None,
            # gated (dimensionless — raw comparison in perf_report)
            "train_goodput_pct":
                (clean.get("summary") or {}).get("goodput_pct"),
            # informational context for the banked row
            "goodput_categories_s": (clean.get("summary") or {}
                                     ).get("categories"),
            "throttled_data_wait_s": (throttled.get("summary") or {}
                                      ).get("categories", {}
                                            ).get("data_wait"),
            "anomalies": (throttled.get("summary") or {}).get("anomalies"),
        }],
    }
    print("\n" + json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
            f.write("\n")
    if failures:
        print(f"\ngoodput_report: {len(failures)} FAILURE(S)",
              file=sys.stderr)
        for msg in failures:
            print(f"  - {msg}", file=sys.stderr)
        return 1
    print("\ngoodput_report: all assertions held")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
