#!/usr/bin/env python
"""Decode smoke: the acceptance gate for token-level continuous batching.

    JAX_PLATFORMS=cpu python tools/decode_smoke.py [--out DECODE_r11.json]

In one process (CI-friendly, CPU, no network egress):

1. deploys a `zoo:TransformerLM?...`-sized decode servable (plus int8 and
   bf16 post-training-quantized variants) behind a ModelServer — the zoo
   kwargs source means no checkpoint is needed to size the model;
2. drives N concurrent closed-loop token STREAMS through the generate
   surface (tools/serve_loadgen.py --mode decode as a library) and, MID
   TRAFFIC, hot-swaps the servable to a differently-seeded model —
   asserts ZERO 5xx across every stream and that post-swap streams
   answer from the new version while pre-swap streams finish cleanly on
   the old one (the rolling-swap contract);
3. scrapes /metrics and asserts the decode compile ledger balances:
   ``serving_decode_compiles_total`` summed == ``serving_decode_warmup_
   runs_total`` summed — every prefill bucket and the decode step
   compiled during warmup, never on the request path — and that
   ``serving_decode_preempted_joins_total`` > 0 (streams actually joined
   a running batch: continuous batching happened, it wasn't sequential);
4. measures the quantized variants against the base engine on a shared
   token set (`quantize.quality_delta`): next-token perplexity delta and
   mean absolute logit error per variant;
5. drives a deterministic SHARED-PREFIX workload (serve_loadgen
   --prefix-mix as a library) against a longer-context servable and
   asserts the KV prefix cache engaged (cache_hit_rate > 0), then
   measures cold-vs-hot TTFT on a controlled sequential pass — the
   acceptance bar is hot p99 at least 2x better than cold;
6. measures short-stream inter-token p99 while a LONG-PROMPT INTERFERER
   continuously admits, with chunked prefill on vs off — chunking must
   improve the interferer ITL p99 (head-of-line-free prefill);
7. drives a speculative-decoding A/B (same greedy prompts against an
   ``@spec:draft=int8,k=12`` self-drafting servable and its plain twin):
   token streams must be EXACTLY equal, the acceptance rate must clear
   0.5, per-stream mean ITL p99 must improve, and the compile ledger
   must still balance with the draft/verify programs live;
8. exercises the tiered KV fabric's host-RAM spill tier on a servable
   with a deliberately tight HBM pool: distinct long prompts force
   zero-ref retained prefixes to demote to the pinned host store, then
   re-driving the first prompt must promote its pages back (spill hit)
   and reproduce the EXACT greedy tokens of the cold pass — the banked
   ``decode_spill_hit_rate`` is the admission hit fraction;
9. stands up a real 2-replica in-process fleet and runs the
   prefix-affinity A/B: two routers over the SAME fleet (affinity on vs
   off), disjoint page-aligned shared-prefix sets per arm, ownership
   refreshed via the /readyz heartbeat between the cold and measured
   passes — affinity steering must beat random (p2c) routing on
   repeat-prefix TTFT p99, and one serve_loadgen --prefix-mix pass
   through the affinity router banks the per-replica cache-hit split;
10. banks a bench-style ``sweep`` with the decode throughput/latency row
   (``decode_tokens_sec``, ``decode_ttft_p99_ms``, ``decode_itl_p99_ms``),
   the prefix-cache row (``decode_cache_hit_rate``,
   ``decode_ttft_hot_p99_ms``, ``decode_ttft_cold_p99_ms``), the
   interferer row (``decode_itl_interferer_p99_ms`` + the ungated
   chunking-off reference), the speculative row
   (``decode_spec_acceptance_rate`` + its ITL A/B) and one quality row
   per variant, as DECODE_r*.json for tools/perf_report.py to gate. A
   ``calib_cpu_ms`` machine-speed reference (fixed numpy matmul timing,
   sampled before and after the measured phases) rides along so the
   gate can normalize cross-round comparisons for host-speed drift.

Exit 0 on success, 1 on failure; prints the JSON summary either way.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")


def _metric_sum(metrics_text: str, family: str) -> float:
    total = 0.0
    for line in metrics_text.splitlines():
        if line.startswith(family + "{") or line.startswith(family + " "):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _p99_ms(samples) -> float:
    from serve_loadgen import percentile
    return round((percentile(sorted(samples), 99) or 0.0) * 1e3, 3)


def _metric_sum_where(metrics_text: str, family: str, needle: str) -> float:
    """Like _metric_sum but only lines whose label set contains `needle`
    (e.g. 'model="lm_spill"') — the fabric phases share one process-wide
    registry with every other servable in this smoke."""
    total = 0.0
    for line in metrics_text.splitlines():
        if line.startswith(family + "{") and needle in line:
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _sse_ttft(url: str, model: str, prompt, max_new_tokens: int = 2,
              timeout: float = 120.0):
    """One greedy generate through a router's SSE surface; returns
    (ttft_s, tokens, X-Served-By header)."""
    body = json.dumps({"prompt": list(prompt),
                       "max_new_tokens": max_new_tokens,
                       "temperature": 0.0}).encode()
    req = urllib.request.Request(
        f"{url}/v1/models/{model}/generate", data=body,
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    ttft, toks = None, []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        served = r.headers.get("X-Served-By")
        for raw in r:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data: "):
                continue
            ev = json.loads(line[6:])
            if "token" in ev:
                if ttft is None:
                    ttft = time.perf_counter() - t0
                toks.append(ev["token"])
    return ttft, toks, served


def _calibrate(trials: int = 9) -> float:
    """Machine-speed reference: median wall-ms for a FIXED numpy f32
    matmul workload. Banked as ``calib_cpu_ms`` so perf_report can
    compare rounds taken on differently-loaded hosts in normalized
    space — nothing in this repo's code paths can move this number,
    only the machine can."""
    import numpy as np
    a = np.random.RandomState(0).rand(384, 384).astype(np.float32)
    b = np.random.RandomState(1).rand(384, 384).astype(np.float32)
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        c = a
        for _ in range(20):
            c = c @ b
        float(c[0, 0])              # force materialization
        samples.append((time.perf_counter() - t0) * 1e3)
    samples.sort()
    return round(samples[len(samples) // 2], 3)


def _drain(req, timeout=120.0):
    """Consume one library GenerateRequest; returns (token count,
    [inter-token gaps s])."""
    import time as _t
    ntok, last, itls = 0, None, []
    deadline = _t.monotonic() + timeout
    while True:
        ev = req.events.get(timeout=max(0.1, deadline - _t.monotonic()))
        if ev[0] == "token":
            now = _t.perf_counter()
            if last is not None:
                itls.append(now - last)
            last = now
            ntok += 1
        elif ev[0] == "done":
            return ntok, itls
        else:
            raise ev[1]


def _interferer_itl_p99(lm, vocab: int, rs, n_streams: int = 2,
                        gen_tokens: int = 48) -> float:
    """Short-stream inter-token p99 while a long-prompt interferer
    continuously admits (each interferer prompt is unique, so its whole
    suffix really prefills). The chunking A/B isolates head-of-line
    blocking: with chunking off every interferer admission stalls the
    running streams for one monolithic prefill."""
    import threading
    import time as _t

    import numpy as np
    itls, errs = [], []
    done = threading.Event()
    # per-thread RNG streams derived from the caller's seed: the chunked
    # and nochunk phases are seeded identically, and thread interleaving
    # must not reorder draws between them — the A/B compares the same
    # prompt sets
    seeds = rs.randint(0, 2 ** 31 - 1, n_streams + 1)

    def short(i):
        try:
            srs = np.random.RandomState(seeds[i])
            req = lm.generate(srs.randint(0, vocab, 8).tolist(),
                              max_new_tokens=gen_tokens)
            _, gaps = _drain(req)
            itls.extend(gaps)
        except Exception as e:          # noqa: BLE001 — asserted below
            errs.append(repr(e))

    def interferer():
        irs = np.random.RandomState(seeds[-1])
        while not done.is_set():
            try:
                req = lm.generate(irs.randint(0, vocab, 448).tolist(),
                                  max_new_tokens=1)
                _drain(req)
            except Exception as e:      # noqa: BLE001
                errs.append(repr(e))
                return
            _t.sleep(0.001)

    threads = [threading.Thread(target=short, args=(i,), daemon=True,
                                name=f"smoke-short-{i}")
               for i in range(n_streams)]
    intf = threading.Thread(target=interferer, daemon=True,
                            name="smoke-interferer")
    for t in threads:
        t.start()
    intf.start()
    for t in threads:
        t.join(timeout=300)
    done.set()
    intf.join(timeout=300)
    if errs:
        raise RuntimeError(f"interferer phase errors: {errs}")
    return _p99_ms(itls)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--streams", type=int, default=4,
                   help="concurrent closed-loop token streams")
    p.add_argument("--requests", type=int, default=24,
                   help="logical streams per traffic phase (2 phases)")
    p.add_argument("--prompt-len", type=int, default=12)
    p.add_argument("--max-new-tokens", type=int, default=24)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--n-embd", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--seq-length", type=int, default=128)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="bank the summary JSON here (e.g. "
                        "DECODE_r11.json at the repo root)")
    args = p.parse_args(argv)

    import numpy as np

    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
    from deeplearning4j_tpu.serving.decode import DecodeConfig
    from deeplearning4j_tpu.serving.quantize import quality_delta
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from serve_loadgen import LoadGen, parse_priority_mix

    failures = []
    summary = {}
    # machine-speed reference, sampled before AND after the measured
    # phases: the banked figure reflects the host across the whole window
    calib_start = _calibrate()
    arch = (f"zoo:TransformerLM?vocab_size={args.vocab}"
            f"&n_layers={args.n_layers}&n_embd={args.n_embd}"
            f"&n_heads={args.n_heads}&seq_length={args.seq_length}")
    cfg = DecodeConfig(slots=args.slots, page_size=args.page_size)

    registry = ModelRegistry()
    t0 = time.perf_counter()
    registry.deploy_lm("lm", arch, decode=cfg)
    registry.deploy_lm("lm_int8", arch + "@int8", decode=cfg)
    registry.deploy_lm("lm_bf16", arch + "@bf16", decode=cfg)
    # the prefix/interferer phases need room for long prompts: a fixed
    # 512-token-context sizing (independent of the CLI sizing knobs) so
    # cold prefill is genuinely heavier than a cache-hit suffix — on CPU
    # with tiny models, per-program dispatch overhead flattens the ratio
    # unless the prompt is long enough for compute to dominate. Same
    # model + config except the chunking knob — the interferer A/B.
    arch_long = (f"zoo:TransformerLM?vocab_size={args.vocab}"
                 f"&n_layers=2&n_embd=64&n_heads=4&seq_length=512")
    registry.deploy_lm("lm_prefix", arch_long,
                       decode=DecodeConfig(slots=args.slots, page_size=16))
    registry.deploy_lm("lm_nochunk", arch_long,
                       decode=DecodeConfig(slots=args.slots, page_size=16,
                                           prefill_chunk_tokens=0))
    summary["warmup_s"] = round(time.perf_counter() - t0, 2)
    server = ModelServer(registry, port=0, default_deadline_s=120.0)

    # ------------------------------------------------- quantized variants
    # measured BEFORE the swap phase: the variants were built from the
    # same weights the base currently serves — after the mid-traffic swap
    # the base answers from a different seed and the delta means nothing
    rs = np.random.RandomState(7)
    qa_tokens = rs.randint(0, args.vocab, (4, min(64, args.seq_length)))
    base_eng = registry.get("lm").scheduler.admitting_engine()
    quality = {}
    for variant in ("int8", "bf16"):
        eng = registry.get(f"lm_{variant}").scheduler.admitting_engine()
        quality[variant] = quality_delta(base_eng, eng, qa_tokens)
        if not np.isfinite(quality[variant]["ppl_variant"]):
            failures.append(f"{variant}: non-finite perplexity")
    # the head-to-head row: what does int8 cost RELATIVE to the bf16
    # variant an operator would otherwise deploy
    quality["int8_vs_bf16"] = quality_delta(
        registry.get("lm_bf16").scheduler.admitting_engine(),
        registry.get("lm_int8").scheduler.admitting_engine(), qa_tokens)
    summary["quant_quality"] = quality

    # ------------------------------------------------------ traffic + swap
    gen_args = argparse.Namespace(
        url=server.url, model="lm", mode="decode",
        prompt_len=args.prompt_len, max_new_tokens=args.max_new_tokens,
        temperature=0.0, top_k=0, vocab=args.vocab,
        requests=args.requests, concurrency=args.streams, rate=None,
        batch_sizes=[1], max_retries=4, retry_cap_s=2.0,
        deadline_ms=None, timeout_s=120.0, seed=0,
        priority_mix=parse_priority_mix("interactive=2,batch=1"))
    gen = LoadGen(gen_args, ())

    swap_state = {}

    def swapper():
        try:
            # wait for traffic to be genuinely mid-flight, then hot-swap
            time.sleep(0.5)
            body = json.dumps({"source": arch + "&seed=777"}).encode()
            t = time.perf_counter()
            r = urllib.request.urlopen(urllib.request.Request(
                server.url + "/v1/models/lm/swap", data=body,
                headers={"Content-Type": "application/json"}), timeout=300)
            swap_state["code"] = r.status
            swap_state["swap_s"] = round(time.perf_counter() - t, 2)
            swap_state["body"] = json.loads(r.read())
        except Exception as e:              # noqa: BLE001 — fail loud:
            # a silently-dead swapper reads as "swap never returned 200"
            # with no cause; the gate below reports swap_state verbatim
            swap_state["error"] = repr(e)

    swap_thread = threading.Thread(target=swapper, daemon=True,
                                   name="smoke-swapper")
    swap_thread.start()
    wall1, ok1 = gen.run_closed()
    swap_thread.join(timeout=300)
    if swap_state.get("code") != 200:
        failures.append(f"mid-traffic swap failed: {swap_state}")
    # phase 2: post-swap traffic (proves the new engine admits cleanly)
    wall2, ok2 = gen.run_closed()
    report = gen.report(wall1 + wall2, ok1 + ok2)
    summary["loadgen"] = report
    summary["swap"] = swap_state

    five_xx = sum(v for k, v in report["codes"].items()
                  if k.isdigit() and 500 <= int(k) < 600)
    if five_xx:
        failures.append(f"{five_xx} 5xx responses under decode traffic")
    if report["errors"]:
        failures.append(f"{report['errors']} streams failed "
                        f"({report['error_classes']})")

    # -------------------------------------- shared-prefix workload (HTTP)
    # the production shape prefix caching exists for: most prompts open
    # with one shared system prefix. Asserts the cache actually engaged.
    prefix_args = argparse.Namespace(
        url=server.url, model="lm_prefix", mode="decode",
        prompt_len=192, max_new_tokens=8, temperature=0.0, top_k=0,
        vocab=args.vocab, requests=max(16, args.requests),
        concurrency=3, rate=None, batch_sizes=[1], max_retries=4,
        retry_cap_s=2.0, deadline_ms=None, timeout_s=120.0, seed=3,
        priority_mix={}, prefix_mix={"shared": 3, "unique": 1},
        shared_prefix_len=160)
    pgen = LoadGen(prefix_args, ())
    pwall, pok = pgen.run_closed()
    preport = pgen.report(pwall, pok)
    summary["prefix_loadgen"] = preport
    hit_rate = (preport.get("prefix") or {}).get("cache_hit_rate")
    if preport["errors"]:
        failures.append(f"{preport['errors']} shared-prefix streams "
                        f"failed ({preport['error_classes']})")
    if not hit_rate or hit_rate <= 0:
        failures.append(f"prefix cache never hit on the shared-prefix "
                        f"workload (hit_rate={hit_rate})")

    # ------------------------------- cold-vs-hot TTFT (controlled, library)
    # sequential on an idle servable so the split measures prefill
    # compute, not queueing: cold = unique 448-token prompt (full
    # prefill under the default chunk budget), hot = 416 shared-prefix
    # tokens served from cached pages + a 32-token suffix chunk
    lmp = registry.get("lm_prefix")
    rs2 = np.random.RandomState(11)
    hot_prefix = rs2.randint(0, args.vocab, 416).tolist()
    _drain(lmp.generate(hot_prefix + rs2.randint(0, args.vocab, 32)
                        .tolist(), max_new_tokens=2))     # prime the cache
    cold_ttft, hot_ttft = [], []
    for _ in range(12):
        req = lmp.generate(rs2.randint(0, args.vocab, 448).tolist(),
                           max_new_tokens=2)
        _drain(req)
        cold_ttft.append(req.first_token_at - req.enqueued)
        req = lmp.generate(hot_prefix + rs2.randint(0, args.vocab, 32)
                           .tolist(), max_new_tokens=2)
        _drain(req)
        if req.cached_tokens != 416:
            failures.append(f"hot admission cached {req.cached_tokens} "
                            "of 416 shared-prefix tokens")
            break
        hot_ttft.append(req.first_token_at - req.enqueued)
    cold_p99, hot_p99 = _p99_ms(cold_ttft), _p99_ms(hot_ttft)
    summary["prefix_ttft"] = {"cold_p99_ms": cold_p99,
                              "hot_p99_ms": hot_p99,
                              "speedup": round(cold_p99 / hot_p99, 2)
                              if hot_p99 else None}
    if not hot_ttft or hot_p99 * 2 > cold_p99:
        failures.append(f"hot TTFT p99 {hot_p99}ms not >= 2x better "
                        f"than cold {cold_p99}ms")

    # ---------------------- long-prompt interferer ITL: chunking on vs off
    itl_chunked = _interferer_itl_p99(lmp, args.vocab,
                                      np.random.RandomState(13))
    itl_nochunk = _interferer_itl_p99(registry.get("lm_nochunk"),
                                      args.vocab,
                                      np.random.RandomState(13))
    summary["interferer_itl"] = {
        "chunked_p99_ms": itl_chunked, "nochunk_p99_ms": itl_nochunk,
        "chunk_tokens":
            lmp.scheduler.admitting_engine().prefill_chunk_tokens}
    if itl_chunked >= itl_nochunk:
        failures.append(
            f"chunked prefill did not improve interferer ITL p99 "
            f"({itl_chunked}ms chunked vs {itl_nochunk}ms monolithic)")

    # ------------------- speculative decoding A/B: parity + acceptance
    # A dedicated tiny arch: the int8 self-draft runs the same compute
    # per position as its target, so the ITL win on CPU comes purely
    # from amortizing the fixed per-token costs (dispatch + scheduler
    # tick: 2 dispatches and 1 tick per accepted burst vs 1 of each
    # per token). The per-position body compute is paid TWICE under
    # speculation, so the margin needs a large k over a very cheap
    # body — n_embd 16 / 1 head / vocab 32 with k=12 measures ~20%
    # lower mean ITL on CPU, well past timer noise.
    arch_spec = ("zoo:TransformerLM?vocab_size=32&n_layers=1"
                 "&n_embd=16&n_heads=1&seq_length=224")
    spec_cfg = DecodeConfig(slots=2, page_size=16)
    registry.deploy_lm("lm_spec_base", arch_spec, decode=spec_cfg)
    registry.deploy_lm("lm_spec", arch_spec + "@spec:draft=int8,k=12",
                       decode=spec_cfg)
    lsb, lsp = registry.get("lm_spec_base"), registry.get("lm_spec")

    def _spec_stream(lm, prompt, n=120):
        """One greedy stream; returns (tokens, inter-token gaps s,
        done-event info)."""
        req = lm.generate(prompt, max_new_tokens=n)
        toks, gaps, last = [], [], None
        deadline = time.monotonic() + 120.0
        while True:
            ev = req.events.get(
                timeout=max(0.1, deadline - time.monotonic()))
            if ev[0] == "token":
                now = time.perf_counter()
                if last is not None:
                    gaps.append(now - last)
                last = now
                toks.append(ev[1])
            elif ev[0] == "done":
                return toks, gaps, ev[1]
            else:
                raise ev[1]

    # 52 streams: the p99 across per-stream means then sheds the single
    # worst stream — one OS scheduling blip cannot decide the gate
    rs3 = np.random.RandomState(17)
    spec_prompts = [rs3.randint(0, 32, 8).tolist() for _ in range(52)]
    for _ in range(2):          # throwaway streams warm each arm
        for lm in (lsb, lsp):
            _spec_stream(lm, spec_prompts[0], n=16)
    base_itl, spec_itl = [], []
    spec_prop = spec_acc = spec_mismatches = 0
    for prompt in spec_prompts:
        bt, bg, _ = _spec_stream(lsb, prompt)
        st, sg, info = _spec_stream(lsp, prompt)
        if bt != st:
            spec_mismatches += 1
        if bg:
            base_itl.append(sum(bg) / len(bg))
        if sg:
            spec_itl.append(sum(sg) / len(sg))
        spec_prop += int(info.get("spec_proposed") or 0)
        spec_acc += int(info.get("spec_accepted") or 0)
    spec_rate = round(spec_acc / spec_prop, 4) if spec_prop else 0.0
    spec_p99 = _p99_ms(spec_itl)
    spec_base_p99 = _p99_ms(base_itl)
    summary["spec_ab"] = {
        "streams": len(spec_prompts), "mismatched_streams": spec_mismatches,
        "proposed": spec_prop, "accepted": spec_acc,
        "acceptance_rate": spec_rate,
        "itl_p99_ms": spec_p99, "base_itl_p99_ms": spec_base_p99}
    if spec_mismatches:
        failures.append(
            f"speculative greedy output diverged from the plain twin on "
            f"{spec_mismatches}/{len(spec_prompts)} streams — speculation "
            f"changed the distribution")
    if spec_prop <= 0:
        failures.append("speculation never proposed a token — "
                        "the draft path did not engage")
    elif spec_rate <= 0.5:
        failures.append(
            f"self-draft acceptance rate {spec_rate} not > 0.5 — the "
            f"int8 draft disagrees with its own target too often")
    if spec_p99 >= spec_base_p99:
        failures.append(
            f"speculation did not improve per-stream mean ITL p99 "
            f"({spec_p99}ms spec vs {spec_base_p99}ms plain)")

    # --------------------- tiered KV fabric: host-RAM spill tier parity
    # pool_pages barely over the floor (1 dump + one max-context
    # sequence) so retained zero-ref prefixes MUST demote to the pinned
    # host store when fresh admissions need pages; re-driving the first
    # prompt promotes them back and must reproduce its exact cold tokens
    from deeplearning4j_tpu import monitor as _monitor
    pages_per_slot = args.seq_length // args.page_size
    registry.deploy_lm(
        "lm_spill", arch,
        decode=DecodeConfig(slots=2, page_size=args.page_size,
                            pool_pages=pages_per_slot + 4, spill_pages=64))
    lm_spill = registry.get("lm_spill")
    rs4 = np.random.RandomState(19)
    spill_prompts = [rs4.randint(0, args.vocab, 80).tolist()
                     for _ in range(3)]
    cold_tokens, _, _ = _spec_stream(lm_spill, spill_prompts[0], n=8)
    for pr in spill_prompts[1:]:            # force demotion of prompt 0
        _spec_stream(lm_spill, pr, n=8)
    hot_tokens, _, _ = _spec_stream(lm_spill, spill_prompts[0], n=8)
    mtext = _monitor.prometheus_text()
    where = 'model="lm_spill"'
    spill = {k: _metric_sum_where(mtext, f"serving_kv_spill_{k}_total",
                                  where)
             for k in ("hits", "misses", "demotions", "promotions")}
    probes = spill["hits"] + spill["misses"]
    spill_hit_rate = round(spill["hits"] / probes, 4) if probes else 0.0
    summary["spill"] = dict(spill, hit_rate=spill_hit_rate,
                            parity=cold_tokens == hot_tokens)
    if spill["demotions"] <= 0:
        failures.append("spill tier never demoted a page — the tight "
                        "pool did not overflow into host RAM")
    if spill["hits"] <= 0 or spill["promotions"] <= 0:
        failures.append(
            f"re-driven prompt never hit the spill tier "
            f"(hits={spill['hits']} promotions={spill['promotions']})")
    if cold_tokens != hot_tokens:
        failures.append(
            f"greedy parity violated across the spill round-trip: "
            f"cold {cold_tokens} vs promoted {hot_tokens}")

    # -------------- prefix-affinity A/B: steering vs random over a fleet
    # two routers over the SAME 2-replica in-process fleet; each arm
    # drives its own disjoint page-aligned shared prefixes, so the only
    # difference the measured pass sees is the routing policy: affinity
    # steers repeat prefixes to the replica advertising ownership on its
    # /readyz heartbeat, random (p2c) rediscovers the cache by luck
    from deeplearning4j_tpu.serving.fleet import (
        InProcessReplica, ReplicaSpec, ReplicaSupervisor, http_probe,
    )
    from deeplearning4j_tpu.serving.router import (
        ResilientRouter, RouterServer,
    )
    fleet_cfg = DecodeConfig(slots=4, page_size=16, pool_pages=256,
                             spill_pages=128)

    def _replica_factory(i):
        return InProcessReplica(
            f"smoke-aff-{i}",
            ReplicaSpec([], lms=[("aff", arch_long)], decode=fleet_cfg))

    supervisor = ReplicaSupervisor(_replica_factory, 2,
                                   probe_interval_s=0.3,
                                   probe_timeout_s=10.0)
    supervisor.start()
    router_aff = ResilientRouter(supervisor.healthy, hedge=False,
                                 affinity=True)
    router_rand = ResilientRouter(supervisor.healthy, hedge=False,
                                  affinity=False)
    server_aff = RouterServer(router_aff, supervisor=supervisor)
    server_rand = RouterServer(router_rand)
    try:
        rs5 = np.random.RandomState(23)
        arm_p99 = {}
        for arm, url in (("affinity", server_aff.url),
                         ("random", server_rand.url)):
            # 416 = 26 full 16-token blocks: page-aligned, so the
            # leading-block digest chain is the ownership unit
            prefixes = [rs5.randint(0, args.vocab, 416).tolist()
                        for _ in range(4)]
            for pref in prefixes:           # cold pass seeds an owner
                _sse_ttft(url, "aff",
                          pref + rs5.randint(0, args.vocab, 32).tolist())
            # deterministic heartbeat: ownership advertisements land on
            # the replica handles before the measured pass
            for r in supervisor.replicas:
                http_probe(r, 10.0)
            samples = []
            for pref in prefixes:
                for _ in range(3):
                    # let the router's in-flight count on the previous
                    # stream decay: this pass measures steady-state
                    # routing policy, not the p2c guard racing the
                    # stream-teardown accounting
                    time.sleep(0.05)
                    ttft, _, _ = _sse_ttft(
                        url, "aff",
                        pref + rs5.randint(0, args.vocab, 32).tolist())
                    samples.append(ttft)
            arm_p99[arm] = _p99_ms(samples)
        owner_hits = _metric_sum_where(
            _monitor.prometheus_text(),
            "serving_router_affinity_requests_total", 'outcome="owner"')
        summary["affinity_ab"] = dict(arm_p99, owner_steered=owner_hits)
        if owner_hits <= 0:
            failures.append("affinity router never steered a request to "
                            "an ownership-advertising replica")
        if arm_p99["affinity"] >= arm_p99["random"]:
            failures.append(
                f"affinity routing did not beat random on repeat-prefix "
                f"TTFT p99 ({arm_p99['affinity']}ms affinity vs "
                f"{arm_p99['random']}ms random)")

        # the fleet-mode loadgen split: --prefix-mix through the
        # affinity router, per-replica cache-hit rates via X-Served-By
        fleet_args = argparse.Namespace(
            url=server_aff.url, model="aff", mode="decode",
            prompt_len=192, max_new_tokens=4, temperature=0.0, top_k=0,
            vocab=args.vocab, requests=16, concurrency=3, rate=None,
            batch_sizes=[1], max_retries=4, retry_cap_s=2.0,
            deadline_ms=None, timeout_s=120.0, seed=29,
            priority_mix={}, prefix_mix={"shared": 3, "unique": 1},
            shared_prefix_len=160)
        fgen = LoadGen(fleet_args, ())
        fwall, fok = fgen.run_closed()
        freport = fgen.report(fwall, fok)
        summary["fleet_prefix_loadgen"] = freport
        per_replica = (freport.get("prefix") or {}).get("per_replica")
        if freport["errors"]:
            failures.append(f"{freport['errors']} fleet prefix streams "
                            f"failed ({freport['error_classes']})")
        if not per_replica:
            failures.append("loadgen banked no per-replica cache-hit "
                            "split (X-Served-By missing from router "
                            "responses?)")
        elif max(v["cache_hit_rate"] for v in per_replica.values()) <= 0:
            failures.append(f"no replica saw a cache hit under the "
                            f"prefix-mix fleet workload: {per_replica}")
    finally:
        server_aff.stop()
        server_rand.stop()
        supervisor.stop()

    # ----------------------------------------------- compile-ledger proof
    metrics = urllib.request.urlopen(server.url + "/metrics",
                                     timeout=10).read().decode()
    compiles = _metric_sum(metrics, "serving_decode_compiles_total")
    warmups = _metric_sum(metrics, "serving_decode_warmup_runs_total")
    summary["ledger"] = {"compiles": compiles, "warmups": warmups}
    if compiles != warmups or compiles <= 0:
        failures.append(f"compile ledger imbalance: {compiles} compiles "
                        f"vs {warmups} warmups (a stream paid for XLA)")
    joins = _metric_sum(metrics, "serving_decode_preempted_joins_total")
    summary["preempted_joins"] = joins
    summary["kv_cache"] = {
        "hits": _metric_sum(metrics,
                            "serving_decode_kv_cache_hits_total"),
        "misses": _metric_sum(metrics,
                              "serving_decode_kv_cache_misses_total"),
        "evictions": _metric_sum(
            metrics, "serving_decode_kv_cache_evictions_total"),
    }
    if summary["kv_cache"]["hits"] <= 0:
        failures.append("serving_decode_kv_cache_hits_total never "
                        "incremented — prefix sharing did not engage")
    if joins <= 0:
        failures.append("no preempted joins recorded — streams never "
                        "joined a running batch (continuous batching "
                        "did not engage)")

    server.drain(timeout=30)

    dec = report.get("decode", {})
    summary["calib_cpu_ms"] = round((calib_start + _calibrate()) / 2, 3)
    summary["ok"] = not failures
    summary["failures"] = failures
    # bench-style rows: the decode throughput/latency series plus one
    # quality row per quantized variant, gated by tools/perf_report.py
    summary["sweep"] = [{
        "mode": "decode", "on_tpu": False, "batch": args.streams,
        "decode_tokens_sec": dec.get("decode_tokens_sec"),
        "decode_ttft_p99_ms": (dec.get("ttft_ms") or {}).get("p99"),
        "decode_itl_p99_ms": (dec.get("inter_token_ms") or {}).get("p99"),
        "streams": args.requests * 2,
        "zero_5xx": five_xx == 0,
        "compiles": compiles, "warmups": warmups,
        # slowest streams per class by client-minted trace_id: the
        # banked TTFT/ITL percentiles point at reproducible traces
        "slow_trace_ids": report.get("slowest"),
    }] + [{
        # the prefix-cache series: hit rate on the mixed shared/unique
        # HTTP workload, hot/cold TTFT from the controlled split (cold
        # banked for the ratio; only hot + hit rate are perf-gated)
        "mode": "decode_prefix", "on_tpu": False, "batch": 3,
        "decode_cache_hit_rate": hit_rate,
        "decode_ttft_hot_p99_ms": hot_p99,
        "decode_ttft_cold_p99_ms": cold_p99,
        "streams": preport["requests"],
    }, {
        # head-of-line: short-stream ITL under a long-prompt interferer;
        # nochunk is the ungated reference the improvement is against
        "mode": "decode_interferer", "on_tpu": False, "batch": 2,
        "decode_itl_interferer_p99_ms": itl_chunked,
        "decode_itl_interferer_nochunk_p99_ms": itl_nochunk,
    }, {
        # speculative A/B: acceptance rate is throughput-direction
        # gated; the spec-arm ITL rides the gated decode_itl_p99_ms
        # key in its own series; the plain-twin p99 is the ungated
        # reference the improvement was asserted against
        "mode": "decode_spec", "on_tpu": False, "batch": 1,
        "decode_spec_acceptance_rate": spec_rate,
        "decode_itl_p99_ms": spec_p99,
        "decode_spec_itl_base_p99_ms": spec_base_p99,
        "streams": len(spec_prompts),
    }, {
        # tiered KV fabric: the host spill tier's admission hit
        # fraction under pool pressure (ratio-gated); demotion/promotion
        # counts ride along as ungated context
        "mode": "decode_spill", "on_tpu": False, "batch": 1,
        "decode_spill_hit_rate": spill_hit_rate,
        "decode_spill_demotions": spill["demotions"],
        "decode_spill_promotions": spill["promotions"],
    }, {
        # prefix-affinity A/B over the 2-replica fleet: the affinity
        # arm's repeat-prefix TTFT p99 is latency-gated; the random arm
        # is the ungated reference the improvement was asserted against
        "mode": "decode_affinity", "on_tpu": False, "batch": 2,
        "decode_affinity_ttft_hot_p99_ms": arm_p99["affinity"],
        "decode_affinity_ttft_random_p99_ms": arm_p99["random"],
        "streams": 24,
    }] + [{
        "mode": f"decode_quant_{variant}", "on_tpu": False, "batch": None,
        **quality[variant],
    } for variant in sorted(quality)]
    print(json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
