#!/usr/bin/env python
"""Decode smoke: the acceptance gate for token-level continuous batching.

    JAX_PLATFORMS=cpu python tools/decode_smoke.py [--out DECODE_r11.json]

In one process (CI-friendly, CPU, no network egress):

1. deploys a `zoo:TransformerLM?...`-sized decode servable (plus int8 and
   bf16 post-training-quantized variants) behind a ModelServer — the zoo
   kwargs source means no checkpoint is needed to size the model;
2. drives N concurrent closed-loop token STREAMS through the generate
   surface (tools/serve_loadgen.py --mode decode as a library) and, MID
   TRAFFIC, hot-swaps the servable to a differently-seeded model —
   asserts ZERO 5xx across every stream and that post-swap streams
   answer from the new version while pre-swap streams finish cleanly on
   the old one (the rolling-swap contract);
3. scrapes /metrics and asserts the decode compile ledger balances:
   ``serving_decode_compiles_total`` summed == ``serving_decode_warmup_
   runs_total`` summed — every prefill bucket and the decode step
   compiled during warmup, never on the request path — and that
   ``serving_decode_preempted_joins_total`` > 0 (streams actually joined
   a running batch: continuous batching happened, it wasn't sequential);
4. measures the quantized variants against the base engine on a shared
   token set (`quantize.quality_delta`): next-token perplexity delta and
   mean absolute logit error per variant;
5. banks a bench-style ``sweep`` with the decode throughput/latency row
   (``decode_tokens_sec``, ``decode_ttft_p99_ms``, ``decode_itl_p99_ms``)
   and one quality row per variant, as DECODE_r*.json for
   tools/perf_report.py to gate.

Exit 0 on success, 1 on failure; prints the JSON summary either way.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")


def _metric_sum(metrics_text: str, family: str) -> float:
    total = 0.0
    for line in metrics_text.splitlines():
        if line.startswith(family + "{") or line.startswith(family + " "):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--streams", type=int, default=4,
                   help="concurrent closed-loop token streams")
    p.add_argument("--requests", type=int, default=24,
                   help="logical streams per traffic phase (2 phases)")
    p.add_argument("--prompt-len", type=int, default=12)
    p.add_argument("--max-new-tokens", type=int, default=24)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--n-embd", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--seq-length", type=int, default=128)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="bank the summary JSON here (e.g. "
                        "DECODE_r11.json at the repo root)")
    args = p.parse_args(argv)

    import numpy as np

    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
    from deeplearning4j_tpu.serving.decode import DecodeConfig
    from deeplearning4j_tpu.serving.quantize import quality_delta
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from serve_loadgen import LoadGen, parse_priority_mix

    failures = []
    summary = {}
    arch = (f"zoo:TransformerLM?vocab_size={args.vocab}"
            f"&n_layers={args.n_layers}&n_embd={args.n_embd}"
            f"&n_heads={args.n_heads}&seq_length={args.seq_length}")
    cfg = DecodeConfig(slots=args.slots, page_size=args.page_size)

    registry = ModelRegistry()
    t0 = time.perf_counter()
    registry.deploy_lm("lm", arch, decode=cfg)
    registry.deploy_lm("lm_int8", arch + "@int8", decode=cfg)
    registry.deploy_lm("lm_bf16", arch + "@bf16", decode=cfg)
    summary["warmup_s"] = round(time.perf_counter() - t0, 2)
    server = ModelServer(registry, port=0, default_deadline_s=120.0)

    # ------------------------------------------------- quantized variants
    # measured BEFORE the swap phase: the variants were built from the
    # same weights the base currently serves — after the mid-traffic swap
    # the base answers from a different seed and the delta means nothing
    rs = np.random.RandomState(7)
    qa_tokens = rs.randint(0, args.vocab, (4, min(64, args.seq_length)))
    base_eng = registry.get("lm").scheduler.admitting_engine()
    quality = {}
    for variant in ("int8", "bf16"):
        eng = registry.get(f"lm_{variant}").scheduler.admitting_engine()
        quality[variant] = quality_delta(base_eng, eng, qa_tokens)
        if not np.isfinite(quality[variant]["ppl_variant"]):
            failures.append(f"{variant}: non-finite perplexity")
    # the head-to-head row: what does int8 cost RELATIVE to the bf16
    # variant an operator would otherwise deploy
    quality["int8_vs_bf16"] = quality_delta(
        registry.get("lm_bf16").scheduler.admitting_engine(),
        registry.get("lm_int8").scheduler.admitting_engine(), qa_tokens)
    summary["quant_quality"] = quality

    # ------------------------------------------------------ traffic + swap
    gen_args = argparse.Namespace(
        url=server.url, model="lm", mode="decode",
        prompt_len=args.prompt_len, max_new_tokens=args.max_new_tokens,
        temperature=0.0, top_k=0, vocab=args.vocab,
        requests=args.requests, concurrency=args.streams, rate=None,
        batch_sizes=[1], max_retries=4, retry_cap_s=2.0,
        deadline_ms=None, timeout_s=120.0, seed=0,
        priority_mix=parse_priority_mix("interactive=2,batch=1"))
    gen = LoadGen(gen_args, ())

    swap_state = {}

    def swapper():
        # wait for traffic to be genuinely mid-flight, then hot-swap
        time.sleep(0.5)
        body = json.dumps({"source": arch + "&seed=777"}).encode()
        t = time.perf_counter()
        r = urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/lm/swap", data=body,
            headers={"Content-Type": "application/json"}), timeout=300)
        swap_state["code"] = r.status
        swap_state["swap_s"] = round(time.perf_counter() - t, 2)
        swap_state["body"] = json.loads(r.read())

    swap_thread = threading.Thread(target=swapper, daemon=True)
    swap_thread.start()
    wall1, ok1 = gen.run_closed()
    swap_thread.join(timeout=300)
    if swap_state.get("code") != 200:
        failures.append(f"mid-traffic swap failed: {swap_state}")
    # phase 2: post-swap traffic (proves the new engine admits cleanly)
    wall2, ok2 = gen.run_closed()
    report = gen.report(wall1 + wall2, ok1 + ok2)
    summary["loadgen"] = report
    summary["swap"] = swap_state

    five_xx = sum(v for k, v in report["codes"].items()
                  if k.isdigit() and 500 <= int(k) < 600)
    if five_xx:
        failures.append(f"{five_xx} 5xx responses under decode traffic")
    if report["errors"]:
        failures.append(f"{report['errors']} streams failed "
                        f"({report['error_classes']})")

    # ----------------------------------------------- compile-ledger proof
    metrics = urllib.request.urlopen(server.url + "/metrics",
                                     timeout=10).read().decode()
    compiles = _metric_sum(metrics, "serving_decode_compiles_total")
    warmups = _metric_sum(metrics, "serving_decode_warmup_runs_total")
    summary["ledger"] = {"compiles": compiles, "warmups": warmups}
    if compiles != warmups or compiles <= 0:
        failures.append(f"compile ledger imbalance: {compiles} compiles "
                        f"vs {warmups} warmups (a stream paid for XLA)")
    joins = _metric_sum(metrics, "serving_decode_preempted_joins_total")
    summary["preempted_joins"] = joins
    if joins <= 0:
        failures.append("no preempted joins recorded — streams never "
                        "joined a running batch (continuous batching "
                        "did not engage)")

    server.drain(timeout=30)

    dec = report.get("decode", {})
    summary["ok"] = not failures
    summary["failures"] = failures
    # bench-style rows: the decode throughput/latency series plus one
    # quality row per quantized variant, gated by tools/perf_report.py
    summary["sweep"] = [{
        "mode": "decode", "on_tpu": False, "batch": args.streams,
        "decode_tokens_sec": dec.get("decode_tokens_sec"),
        "decode_ttft_p99_ms": (dec.get("ttft_ms") or {}).get("p99"),
        "decode_itl_p99_ms": (dec.get("inter_token_ms") or {}).get("p99"),
        "streams": args.requests * 2,
        "zero_5xx": five_xx == 0,
        "compiles": compiles, "warmups": warmups,
        # slowest streams per class by client-minted trace_id: the
        # banked TTFT/ITL percentiles point at reproducible traces
        "slow_trace_ids": report.get("slowest"),
    }] + [{
        "mode": f"decode_quant_{variant}", "on_tpu": False, "batch": None,
        **quality[variant],
    } for variant in sorted(quality)]
    print(json.dumps(summary, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
