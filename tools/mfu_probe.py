"""MFU calibration probe: what fraction of the chip's peak is reachable,
and where the ResNet-50 step time actually goes.

Two question the bench sweep can't answer:

1. Is the ~197 TFLOP/s bf16 "peak" even reachable through this stack on
   this chip?  A plain large bf16 matmul is the upper bound any real
   model can hit; measuring it separates "the framework is slow" from
   "the ceiling is lower than the spec sheet".
2. Which segment of the training step eats the time?  Times forward-only,
   forward+loss+backward, and the full step (backward + optimizer) at the
   headline config, so the gap localizes to fwd / bwd / update.

Prints one JSON line per measurement with a platform stamp (`on_tpu`), so
a CPU run can never be mistaken for hardware numbers. Safe to run in any
healthy tunnel window (~3 min warm, dominated by two compiles).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

from bench import cache_dir
from deeplearning4j_tpu.util.env import env_flag, env_int

jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR", cache_dir()))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

if env_flag("DL4J_TPU_PROBE_ALLOW_CPU", default=False):
    # the axon plugin force-appends itself to jax_platforms at import,
    # overriding JAX_PLATFORMS=cpu — pin back BEFORE device init or a
    # wedged tunnel hangs the smoke inside jax.devices()
    jax.config.update("jax_platforms", "cpu")

DEV = jax.devices()[0]
ON_TPU = DEV.platform != "cpu"
PEAK_TFLOPS = 197.0  # TPU v5e bf16 (BASELINE.md north-star arithmetic)
BEST_OF = env_int("DL4J_TPU_PROBE_BEST_OF", 3)


def emit(row):
    row.update({"device_kind": DEV.device_kind, "on_tpu": ON_TPU})
    # mirror every numeric measurement into the telemetry registry so the
    # final metrics-summary line (and any /metrics scrape of a harness
    # embedding this probe) carries the same numbers as the log
    from deeplearning4j_tpu import monitor
    probe = str(row.get("segment") or row.get("kind") or "probe")
    for k, v in row.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            monitor.gauge(f"mfu_probe_{k}", "mfu_probe measurement",
                          labels=("probe",)).set(v, probe=probe)
    print(json.dumps(row), flush=True)


def timed_best(run):
    best = None
    for _ in range(BEST_OF):
        t = run()
        best = t if best is None else min(best, t)
    return best


def matmul_peak(n=8192):
    """Large square bf16 matmul chain — the practical compute ceiling.
    8 chained matmuls per call amortize dispatch through the tunnel."""
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.rand(n, n), jnp.bfloat16)
    b = jnp.asarray(rs.rand(n, n), jnp.bfloat16)
    chain = 8

    @jax.jit
    def mm(a, b):
        x = a
        for _ in range(chain):
            x = jnp.dot(x, b, preferred_element_type=jnp.bfloat16)
        return x

    x = mm(a, b)
    float(x[0, 0].astype(jnp.float32))  # host fetch = reliable barrier

    def run():
        t0 = time.perf_counter()
        y = mm(a, b)
        float(y[0, 0].astype(jnp.float32))
        return time.perf_counter() - t0

    t = timed_best(run)
    tflops = chain * 2 * n ** 3 / t / 1e12
    emit({"kind": "matmul-peak", "n": n, "chain": chain,
          "tflops": round(tflops, 1),
          "pct_of_peak": round(100 * tflops / PEAK_TFLOPS, 1),
          "wall_s": round(t, 3)})
    return tflops


def conv_micro(batch=128):
    """A single mid-network ResNet conv (3x3, 256->256 at 14x14... use the
    28x28x128 block: representative MXU-bound conv) chained 16x — conv MFU
    in isolation. If this is high while the full net is low, the gap is
    inter-op (BN/elementwise/memory), not the convs."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, 28, 28, 128), jnp.bfloat16)
    w = jnp.asarray(rs.rand(3, 3, 128, 128) * 0.1, jnp.bfloat16)
    chain = 16

    @jax.jit
    def convs(x, w):
        for _ in range(chain):
            x = jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.bfloat16)
        return x

    y = convs(x, w)
    float(y[0, 0, 0, 0].astype(jnp.float32))

    def run():
        t0 = time.perf_counter()
        y = convs(x, w)
        float(y[0, 0, 0, 0].astype(jnp.float32))
        return time.perf_counter() - t0

    t = timed_best(run)
    flops = chain * 2 * batch * 28 * 28 * 128 * 128 * 9
    tflops = flops / t / 1e12
    emit({"kind": "conv-micro", "batch": batch, "chain": chain,
          "tflops": round(tflops, 1),
          "pct_of_peak": round(100 * tflops / PEAK_TFLOPS, 1),
          "wall_s": round(t, 3)})


def resnet_segments(batch=128, hw=224):
    """Forward / forward+backward / full-step wall times at the headline
    bench config — same net construction as bench.py's resnet runner."""
    import dataclasses

    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    model = ResNet50(num_classes=1000, input_shape=(hw, hw, 3))
    conf = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    net = ComputationGraph(conf).init()
    tx = net._tx

    rs = np.random.RandomState(0)
    X = jnp.asarray(rs.rand(batch, hw, hw, 3).astype("float32"))
    Y = jnp.asarray(np.eye(1000, dtype="float32")[
        rs.randint(0, 1000, batch)])
    rng = jax.random.PRNGKey(0)

    def loss_fn(p, state):
        loss, (new_state, _) = net._score_fn(
            p, state, (X,), (Y,), None, None, True, rng)
        return loss, new_state

    fwd = jax.jit(lambda p, s: loss_fn(p, s)[0])

    @jax.jit
    def fwd_bwd(p, s):
        (loss, _), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, s)
        # fold grads so the backward can't be DCE'd, fetch one scalar
        return loss + sum(jnp.sum(g) for g in jax.tree_util.tree_leaves(
            grads)) * 0.0

    def full(p, o, s):
        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, s)
        updates, new_o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), new_o, new_state, loss

    # graftlint: disable=donated-aliasing -- p/o/s come from net.init() on-device; probes measure the raw step and an own_tree copy would distort the matmul-ceiling comparison
    jfull = jax.jit(full, donate_argnums=(0, 1, 2))

    p, o, s = net.params, net.opt_state, net.state
    reps = 5
    segs = {}
    for name, runner in (
        ("fwd", lambda: fwd(p, s)),
        ("fwd+bwd", lambda: fwd_bwd(p, s)),
    ):
        float(runner())   # compile + warm

        def run(runner=runner):
            t0 = time.perf_counter()
            for _ in range(reps):
                x = runner()
            float(x)
            return (time.perf_counter() - t0) / reps

        segs[name] = timed_best(run)

    p, o, s, loss = jfull(p, o, s)   # compile + warm
    float(loss)

    def run_full():
        nonlocal p, o, s
        t0 = time.perf_counter()
        for _ in range(reps):
            p, o, s, loss = jfull(p, o, s)
        float(loss)
        return (time.perf_counter() - t0) / reps

    segs["full-step"] = timed_best(run_full)

    gflops_img = 22.49   # XLA cost model, bench.py headline
    for name, t in segs.items():
        row = {"kind": "resnet-segment", "segment": name, "batch": batch,
               "ms": round(t * 1e3, 2)}
        if name == "full-step":
            row["imgs_sec"] = round(batch / t, 1)
            row["mfu_pct"] = round(
                100 * batch * gflops_img / 1e3 / t / PEAK_TFLOPS, 1)
        emit(row)
    return segs


if __name__ == "__main__":
    if not ON_TPU and not env_flag("DL4J_TPU_PROBE_ALLOW_CPU",
                                   default=False):
        print("need TPU (set DL4J_TPU_PROBE_ALLOW_CPU=1 for a tiny CPU "
              "smoke)", file=sys.stderr)
        sys.exit(2)
    if ON_TPU:
        matmul_peak()
        conv_micro()
        resnet_segments()
    else:
        matmul_peak(n=512)
        conv_micro(batch=2)
        resnet_segments(batch=2, hw=64)
    from deeplearning4j_tpu import monitor
    print(json.dumps({"kind": "metrics-summary",
                      "metrics": monitor.summary()}), flush=True)
