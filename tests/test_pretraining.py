"""Layerwise pretraining + center loss tests
(the analog of DL4J's pretrain-branch tests and CenterLossOutputLayerTest)."""
import numpy as np

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    AutoEncoder, CenterLossOutputLayer, DenseLayer, OutputLayer,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Sgd

RS = np.random.RandomState(7)


def _structured_data(n=240, f=12, c=3, noise=1.6):
    """Class information lives in a low-dim subspace + heavy noise — the
    regime where unsupervised feature learning helps a short fine-tune."""
    protos = RS.randn(c, f) * 2.0
    ys = RS.randint(0, c, n)
    X = protos[ys] + noise * RS.randn(n, f)
    return X.astype("float32"), np.eye(c, dtype="float32")[ys], ys


def _stacked_ae_conf(seed):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(5e-3))
            .list()
            .layer(AutoEncoder(n_out=8, activation="sigmoid",
                               corruption_level=0.2))
            .layer(AutoEncoder(n_out=6, activation="sigmoid",
                               corruption_level=0.2))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())


def test_fit_pretrain_trains_each_pretrainable_layer():
    X, Y, _ = _structured_data()
    net = MultiLayerNetwork(_stacked_ae_conf(0)).init()
    w0_before = np.asarray(net.params["0"]["W"]).copy()
    w1_before = np.asarray(net.params["1"]["W"]).copy()
    w2_before = np.asarray(net.params["2"]["W"]).copy()
    net.fit_pretrain((X, Y), epochs=5, batch_size=48)
    # both AE layers moved; the supervised head did NOT
    assert np.abs(np.asarray(net.params["0"]["W"]) - w0_before).max() > 1e-3
    assert np.abs(np.asarray(net.params["1"]["W"]) - w1_before).max() > 1e-3
    np.testing.assert_allclose(np.asarray(net.params["2"]["W"]), w2_before)
    assert np.isfinite(net.score())


def test_pretraining_beats_random_init():
    """Greedy AE pretraining must learn measurably better features than
    random init: a linear head trained on the pretrained stack's encoding
    beats the same head on the random stack's encoding (the point of the
    pretrain branch, with the end-to-end fine-tune seed noise factored
    out)."""
    X, Y, _ = _structured_data(n=600)

    def head_acc(feats):
        hc = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(0.05))
              .list()
              .layer(OutputLayer(n_out=3, activation="softmax",
                                 loss="mcxent"))
              .set_input_type(InputType.feed_forward(feats.shape[1]))
              .build())
        h = MultiLayerNetwork(hc).init()
        h.fit((feats, Y), epochs=30, batch_size=64)
        return h.evaluate((feats, Y)).accuracy()

    net = MultiLayerNetwork(_stacked_ae_conf(3)).init()
    random_feats = np.asarray(net.feed_forward(X)[1])
    net.fit_pretrain((X, Y), epochs=30, batch_size=64)
    pre_feats = np.asarray(net.feed_forward(X)[1])
    pre_acc, random_acc = head_acc(pre_feats), head_acc(random_feats)
    assert pre_acc > random_acc, (pre_acc, random_acc)


def test_vae_pretrain_via_driver():
    X, Y, _ = _structured_data()
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(3e-3))
            .list()
            .layer(VariationalAutoencoder(n_out=4, encoder_layer_sizes=(10,),
                                          decoder_layer_sizes=(10,)))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit_pretrain((X, Y), epochs=3, batch_size=48)
    s1 = net.score()
    net.fit_pretrain((X, Y), epochs=6, batch_size=48)
    assert net.score() < s1          # ELBO keeps improving
    net.fit((X, Y), epochs=3, batch_size=48)
    assert np.isfinite(net.score())


# ----------------------------------------------------------------- center loss
def _center_net(lmbda=0.01):
    conf = (NeuralNetConfiguration.Builder().seed(2).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=6, activation="tanh"))
            .layer(CenterLossOutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent", lambda_=lmbda))
            .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def test_center_loss_gradcheck():
    X, Y, _ = _structured_data(n=8)
    net = _center_net(lmbda=0.1)
    # give centers a nonzero start so their gradient is exercised
    import jax.numpy as jnp
    net.params["1"]["cL"] = jnp.asarray(RS.randn(3, 6).astype("float32"))
    res = check_gradients(net, X[:6], Y[:6], max_per_param=12)
    assert res.passed, (res.worst_param, res.max_rel_error, res.failures[:3])


def test_center_loss_tightens_class_clusters():
    X, Y, ys = _structured_data(noise=1.0)
    net = _center_net(lmbda=0.05)
    net.fit((X, Y), epochs=40, batch_size=48)
    assert net.evaluate((X, Y)).accuracy() > 0.8
    # centers moved from zero toward the class feature means
    centers = np.asarray(net.params["1"]["cL"])
    assert np.abs(centers).max() > 0.05
    feats = np.asarray(net.feed_forward(X)[0])
    # intra-class scatter around the learned center < scatter around origin
    for k in range(3):
        fk = feats[ys == k]
        around_center = np.mean(np.sum((fk - centers[k]) ** 2, axis=1))
        around_origin = np.mean(np.sum(fk ** 2, axis=1))
        assert around_center < around_origin


def test_center_loss_serde_round_trip():
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    net = _center_net()
    back = MultiLayerConfiguration.from_json(net.conf.to_json())
    assert back.layers[1] == net.conf.layers[1]
