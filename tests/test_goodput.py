"""Goodput ledger (monitor/goodput.py): fake-clock attribution goldens,
the exclusivity contract (categories sum to session wall exactly), the
step-time anomaly detector + cooldown, resume-replay accounting through
ResilientTrainer, stack-snapshot postmortems, and the zero-cost span
fast path."""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.monitor import flight, goodput, metrics, trace
from deeplearning4j_tpu.monitor.goodput import CATEGORIES, GoodputLedger
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.train import FaultPolicy, ResilientTrainer
from deeplearning4j_tpu.util.faults import FaultInjector, SimulatedCrash

FAST = FaultPolicy(backoff_base=0.001, backoff_max=0.004)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    monitor.REGISTRY.reset()
    monitor.disable_tracing()
    monitor.clear_trace()
    goodput.disable_goodput()
    flight.disable_flight()
    flight.clear()
    yield
    monitor.REGISTRY.reset()
    monitor.disable_tracing()
    monitor.clear_trace()
    goodput.disable_goodput()
    flight.disable_flight()
    flight.clear()


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _span(led, name, t0, t1, **attrs):
    led.on_span(name, t0, t1, attrs)


# --------------------------------------------------------------- goldens
def test_attribution_golden_fake_clock():
    """Every span family lands in its category and `other` is exactly the
    unattributed remainder — the deterministic waterfall."""
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    s = led.fit_begin("golden/fit")
    _span(led, "train/etl", 0.0, 1.0)
    _span(led, "train/device_wait", 1.0, 3.0)
    _span(led, "train/host_sync", 3.0, 3.5)
    _span(led, "train/step", 1.0, 3.5, iteration=0)  # residual 0
    _span(led, "xla/compile", 3.5, 4.0)
    _span(led, "resilience/checkpoint_save", 4.0, 5.0)
    _span(led, "resilience/eval_gate", 5.0, 5.5)
    _span(led, "train/resume_replay", 5.5, 6.0)
    clk.t = 8.0
    out = led.fit_end(s)
    assert out["kind"] == "golden/fit"
    assert out["wall_s"] == 8.0
    assert out["categories"] == {
        "step_compute": 2.0, "data_wait": 1.0, "host_sync": 0.5,
        "compile": 0.5, "checkpoint": 1.0, "eval_gate": 0.5,
        "resume_replay": 0.5, "other": 2.0}
    assert out["goodput_pct"] == 25.0
    assert out["steps"] == 1
    # the live families saw the same numbers
    fam = metrics.REGISTRY.collect("train_time_seconds_total")
    assert fam.value(category="data_wait") == 1.0
    assert fam.value(category="other") == 2.0
    assert metrics.REGISTRY.collect("train_goodput_pct").value() == 25.0
    assert led.last_session() == out


def test_step_residual_counts_as_step_compute():
    """A train/step extent minus its contained child spans is device
    execution the loop didn't bracket -> step_compute; spans outside the
    step window don't subtract."""
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    s = led.fit_begin()
    _span(led, "train/etl", 0.0, 1.0)          # before the step window
    _span(led, "train/device_wait", 1.0, 2.5)  # contained
    _span(led, "train/step", 1.0, 3.0)         # residual 0.5
    clk.t = 3.0
    out = led.fit_end(s)
    assert out["categories"]["data_wait"] == 1.0
    assert out["categories"]["step_compute"] == pytest.approx(2.0)
    assert out["categories"]["other"] == pytest.approx(0.0)


def test_exclusivity_categories_sum_to_wall():
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    s = led.fit_begin()
    _span(led, "train/etl", 0.0, 0.3)
    _span(led, "train/step", 0.3, 1.1)
    _span(led, "resilience/checkpoint_save", 1.1, 1.4)
    clk.t = 2.75
    out = led.fit_end(s)
    assert set(out["categories"]) == set(CATEGORIES)
    assert sum(out["categories"].values()) == pytest.approx(
        out["wall_s"], abs=1e-9)
    assert all(v >= 0.0 for v in out["categories"].values())


def test_sink_ignores_other_threads_and_nested_sessions():
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    s = led.fit_begin()
    assert led.fit_begin("nested") is None      # outer session owns wall
    done = threading.Event()

    def _other():
        _span(led, "train/etl", 0.0, 5.0)       # wrong thread: dropped
        done.set()

    threading.Thread(target=_other).start()
    assert done.wait(5.0)
    clk.t = 1.0
    out = led.fit_end(s)
    assert out["categories"]["data_wait"] == 0.0
    assert out["categories"]["other"] == pytest.approx(1.0)
    assert led.fit_end(None) is None


def test_barrier_wait_banks_outside_the_partition():
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    s = led.fit_begin()
    _span(led, "train/barrier_wait", 0.2, 0.5, shards=4)
    clk.t = 1.0
    out = led.fit_end(s)
    assert out["barrier_wait_s"] == pytest.approx(0.3)
    assert sum(out["categories"].values()) == pytest.approx(1.0)
    fam = metrics.REGISTRY.collect("train_barrier_wait_seconds_total")
    assert fam.value() == pytest.approx(0.3)


# --------------------------------------------------------------- anomaly
def _steady_steps(led, n, start=0.0, spacing=1.0, dur=0.1):
    """n train/step spans whose ENDS are `spacing` apart."""
    t_end = start
    for i in range(n):
        t_end += spacing
        _span(led, "train/step", t_end - dur, t_end, iteration=i)
    return t_end


def test_anomaly_trip_names_dominant_category_and_cools_down(tmp_path):
    flight.enable_flight(dump_dir=str(tmp_path))
    clk = _FakeClock()
    led = GoodputLedger(clock=clk, warmup_steps=4,
                        anomaly_cooldown_steps=32)
    s = led.fit_begin()
    t = _steady_steps(led, 8)                   # baseline: 1.0s spacing
    # spike: 5.0s iteration wall, 4.8s of it an ETL stall
    _span(led, "train/etl", t, t + 4.8)
    _span(led, "train/step", t + 4.8, t + 5.0, iteration=8)
    assert s.anomalies == 1
    assert metrics.REGISTRY.collect(
        "train_step_anomalies_total").value() == 1.0
    doc = flight.postmortems()[-1]
    assert doc["reason"] == "step_time_anomaly"
    assert doc["meta"]["dominant_category"] == "data_wait"
    assert doc["meta"]["step"] == 8
    assert doc["meta"]["iteration_wall_s"] == pytest.approx(5.0)
    assert doc["meta"]["dominant_seconds"] == pytest.approx(4.8)
    # a second spike inside the 32-step cooldown must NOT re-fire
    _span(led, "train/step", t + 11.8, t + 12.0, iteration=9)
    assert s.anomalies == 1
    clk.t = t + 12.0
    led.fit_end(s)


def test_anomaly_detector_stays_quiet_during_warmup():
    flight.enable_flight()
    clk = _FakeClock()
    led = GoodputLedger(clock=clk, warmup_steps=16)
    s = led.fit_begin()
    # a huge spike on step 3 — history too short, detector disarmed
    _span(led, "train/step", 0.9, 1.0, iteration=0)
    _span(led, "train/step", 1.9, 2.0, iteration=1)
    _span(led, "train/step", 41.9, 42.0, iteration=2)
    assert s.anomalies == 0
    clk.t = 42.0
    led.fit_end(s)


def test_anomaly_dominant_falls_back_to_other():
    """When the slow interval's time is unattributed (no span covered
    it), the postmortem says `other` instead of guessing."""
    flight.enable_flight()
    clk = _FakeClock()
    led = GoodputLedger(clock=clk, warmup_steps=4)
    s = led.fit_begin()
    t = _steady_steps(led, 8)
    _span(led, "train/step", t + 5.8, t + 6.0, iteration=8)  # naked gap
    assert s.anomalies == 1
    doc = flight.postmortems()[-1]
    assert doc["meta"]["dominant_category"] == "other"
    clk.t = t + 6.0
    led.fit_end(s)


# ---------------------------------------------------------- live surface
def test_live_stats_reports_pct_and_dominant_stall():
    clk = _FakeClock()
    led = GoodputLedger(clock=clk)
    assert led.live_stats() is None             # no session
    s = led.fit_begin()
    _span(led, "train/step", 0.0, 6.0)
    _span(led, "train/etl", 6.0, 9.0)
    clk.t = 10.0
    live = led.live_stats()
    assert live["goodput_pct"] == pytest.approx(60.0)
    assert live["dominant_stall"] == "data_wait"
    assert live["stall_seconds"] == pytest.approx(3.0)
    clk.t = 10.0
    led.fit_end(s)


def test_decode_note_aggregates_per_model_and_metric():
    led = GoodputLedger()
    led.decode_note("lm", "step_compute", 0.5)
    led.decode_note("lm", "step_compute", 0.25)
    led.decode_note("lm", "page_stall", 0.1)
    led.decode_note("other-lm", "idle", 0.2)
    led.decode_note("lm", "admission", 0.0)     # <=0 dropped
    totals = led.decode_totals()
    assert totals["lm"] == {"step_compute": 0.75, "page_stall": 0.1}
    assert totals["other-lm"] == {"idle": 0.2}
    fam = metrics.REGISTRY.collect("serving_decode_time_seconds_total")
    assert fam.value(model="lm", category="step_compute") == 0.75


# ------------------------------------------------------------- zero cost
def test_zero_cost_span_paths():
    """Disabled: span() hands back the shared null object. Goodput-only
    (tracing off): a _SinkSpan that feeds the sink. Both off again after
    disable_goodput()."""
    assert trace.span("x") is trace._NULL
    seen = []
    trace.set_span_sink(lambda name, t0, t1, attrs: seen.append(name))
    try:
        sp = trace.span("y")
        assert sp is not trace._NULL
        with sp:
            pass
        assert seen == ["y"]
        trace.add_span("z", 0.0, 1.0)
        assert seen == ["y", "z"]
    finally:
        trace.set_span_sink(None)
    assert trace.span("x2") is trace._NULL
    assert not trace.trace_events()             # nothing recorded


def test_device_wait_passthrough_and_block():
    assert goodput.device_wait("not-an-array") == "not-an-array"

    class _Arr:
        def __init__(self):
            self.blocked = 0

        def block_until_ready(self):
            self.blocked += 1

    a = _Arr()
    assert goodput.device_wait(a) is a          # disabled: bare block
    assert a.blocked == 1
    led = goodput.enable_goodput()
    s = led.fit_begin()
    goodput.device_wait(a)                      # active session, 0 shards
    assert a.blocked == 2
    led.fit_end(s)


# ------------------------------------------------------------ end to end
def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data():
    rs = np.random.RandomState(0)
    X = rs.randn(120, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 120)]
    return ArrayDataSetIterator(X, Y, batch_size=30)


def test_fit_report_carries_goodput_and_resume_replay(tmp_path):
    """A preempt->resume pair: both reports carry the goodput summary,
    the resumed run attributes its iterator fast-forward to
    resume_replay, and categories sum to wall within tolerance."""
    goodput.enable_goodput()
    # crash at 6 with saves every 2: the resume lands mid-epoch
    # (step_in_epoch 2 of 4), forcing the iterator fast-forward
    with pytest.raises(SimulatedCrash):
        ResilientTrainer(_net(), str(tmp_path), save_every_n_iterations=2,
                         policy=FAST, injector=FaultInjector(crash_at=6)
                         ).fit(_data(), epochs=3)
    rep = ResilientTrainer(_net(), str(tmp_path), save_every_n_iterations=2,
                           policy=FAST).fit(_data(), epochs=3)
    assert rep.resumed_from is not None
    assert rep.goodput_pct is not None and rep.goodput_pct > 0.0
    assert set(rep.time_by_category) == set(CATEGORIES)
    assert rep.time_by_category["resume_replay"] > 0.0
    wall = sum(rep.time_by_category.values())
    s = goodput.last_session()
    assert s["wall_s"] == pytest.approx(wall, abs=1e-3)
    assert s["steps"] > 0


def test_performance_listener_logs_goodput(caplog):
    from deeplearning4j_tpu.train.listeners import PerformanceListener
    goodput.enable_goodput()
    lis = PerformanceListener(frequency=1)
    net = _net()
    net.set_listeners(lis)
    import logging
    with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
        net.fit(_data(), epochs=2)
    recs = lis.history
    assert recs and all("goodput_pct" in r for r in recs)
    assert all(r["dominant_stall"] in CATEGORIES for r in recs)
    assert any("goodput:" in m for m in caplog.messages)


def test_etl_stall_attributes_to_data_wait(tmp_path):
    """The acceptance shape: a FaultInjector-throttled ETL fit shows the
    stall in data_wait and trips an anomaly postmortem naming it, with
    thread stacks attached."""
    goodput.enable_goodput(warmup_steps=8, anomaly_min_s=0.05)
    flight.enable_flight(dump_dir=str(tmp_path / "pm"))
    rep = ResilientTrainer(
        _net(), str(tmp_path / "ck"), save_every_n_iterations=10_000,
        policy=FAST,
        injector=FaultInjector(etl_stall_at=[10], etl_stall_s=0.4)
    ).fit(_data(), epochs=4)
    assert rep.time_by_category["data_wait"] >= 0.4
    docs = [d for d in flight.postmortems()
            if d["reason"] == "step_time_anomaly"]
    assert docs, "the injected stall must trip the detector"
    doc = docs[-1]
    assert doc["meta"]["dominant_category"] == "data_wait"
    assert doc["threads"], "postmortem carries thread stacks"
    th = doc["threads"][0]
    assert set(th) == {"name", "ident", "daemon", "stack"}
    assert 0 < len(th["stack"]) <= 20
    assert len(doc["threads"]) <= 32
    assert isinstance(doc["locks"], dict)
    dumps = list((tmp_path / "pm").glob("postmortem-*step_time_anomaly*"))
    assert dumps, "postmortem JSON auto-dumped to disk"


def test_goodput_session_survives_fit_exception():
    """fit() failing mid-flight still closes the session (finally path):
    a later fit can open a fresh one."""
    led = goodput.enable_goodput()
    net = _net()
    with pytest.raises(Exception):
        net.fit(object())                       # not an iterator
    assert led._session is None
    net.fit(_data(), epochs=1)
    assert goodput.last_session()["steps"] == 4
