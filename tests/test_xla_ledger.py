"""Compiled-program ledger tests — monitor/xla.py (program capture,
fingerprint dedup, MFU accounting, zero-cost-when-disabled), its fit-path
and serving integration, and the tools/perf_report.py regression gate."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import xla as xla_ledger
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ledger():
    """Fresh registry + disabled empty ledger around every test."""
    monitor.REGISTRY.reset()
    xla_ledger.disable_ledger()
    xla_ledger.clear_ledger()
    yield
    monitor.REGISTRY.reset()
    xla_ledger.disable_ledger()
    xla_ledger.clear_ledger()


def _small_net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _blobs(n=48, d=5, k=3, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, d).astype("float32")
    Y = np.eye(k, dtype="float32")[rs.randint(0, k, n)]
    return X, Y


# --------------------------------------------------------------- capture
def test_capture_dedups_by_fingerprint_but_counts_every_compile():
    xla_ledger.enable_ledger()
    f = jax.jit(lambda x: (x * 2.0).sum())
    x = np.ones((8, 4), "float32")
    r1 = xla_ledger.capture("t/prog", f, (x,))
    r2 = xla_ledger.capture("t/prog", f, (x,))   # recompile event, same fp
    assert r1 is not None and r2 is not None
    assert r1.fingerprint == r2.fingerprint
    assert len(xla_ledger.records()) == 1        # deduped to one entry
    assert r1.compiles == 2
    ctr = monitor.REGISTRY.collect("xla_compiles_total")
    assert ctr.value(program="t/prog") == 2      # ...but both counted
    assert monitor.REGISTRY.collect("xla_programs").value() == 1
    hist = monitor.REGISTRY.collect("xla_compile_seconds")
    assert hist.snapshot(program="t/prog")["count"] == 2


def test_distinct_shapes_get_distinct_fingerprints():
    xla_ledger.enable_ledger()
    f = jax.jit(lambda x: (x * 2.0).sum())
    r1 = xla_ledger.capture("t/prog", f, (np.ones((8, 4), "float32"),))
    r2 = xla_ledger.capture("t/prog", f, (np.ones((16, 4), "float32"),))
    assert r1.fingerprint != r2.fingerprint
    assert len(xla_ledger.records()) == 2


def test_capture_reads_cost_and_memory_analysis_on_cpu():
    xla_ledger.enable_ledger()
    f = jax.jit(lambda a, b: a @ b)
    args = (np.ones((32, 16), "float32"), np.ones((16, 8), "float32"))
    rec = xla_ledger.capture("t/matmul", f, args)
    assert rec.flops and rec.flops > 0
    assert rec.bytes_accessed and rec.bytes_accessed > 0
    assert rec.arithmetic_intensity == rec.flops / rec.bytes_accessed
    assert rec.hbm_peak_bytes and rec.hbm_peak_bytes > 0
    g = monitor.REGISTRY.collect("xla_hbm_peak_bytes")
    assert g.value(program="t/matmul",
                   fingerprint=rec.fingerprint) == rec.hbm_peak_bytes


def test_disabled_ledger_is_a_noop():
    f = jax.jit(lambda x: x + 1)
    assert xla_ledger.capture("t/p", f, (np.ones(3, "float32"),)) is None
    cache = {}
    assert xla_ledger.capture_cached(cache, "k", "t/p", f,
                                     (np.ones(3, "float32"),)) is None
    assert cache == {}                      # not even a negative entry
    xla_ledger.observe_step(None, 0.1)
    assert xla_ledger.records() == []
    assert not any(name.startswith("xla_") for name in monitor.dump())


def test_capture_cached_caches_failures_too():
    xla_ledger.enable_ledger()

    class NotJitted:                        # no .lower(): capture fails
        pass

    cache = {}
    assert xla_ledger.capture_cached(cache, "k", "t/bad", NotJitted(),
                                     ()) is None
    assert cache == {"k": None}             # probed once, not every step
    ctr = monitor.REGISTRY.collect("xla_analysis_unavailable_total")
    assert ctr.value(kind="lower") == 1


# ---------------------------------------------------------- fit paths
def test_fit_captures_per_call_and_scan_as_distinct_programs(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
    xla_ledger.enable_ledger()
    X, Y = _blobs()
    net = _small_net()
    net.fit((X, Y), epochs=1, batch_size=16, scan_steps=1)
    names = {r.name for r in xla_ledger.records()}
    assert "mln/train_step" in names
    per_call = [r for r in xla_ledger.records()
                if r.name == "mln/train_step"]
    net.fit((X, Y), epochs=1, batch_size=16, scan_steps=3)
    names = {r.name for r in xla_ledger.records()}
    assert "mln/scan_step" in names
    scan = [r for r in xla_ledger.records() if r.name == "mln/scan_step"]
    # the fused scan-of-K program is a different compiled artifact
    assert scan[0].fingerprint != per_call[0].fingerprint
    # XLA counts the scan body once; steps_per_call carries the K that
    # total_flops_per_call scales by
    assert scan[0].steps_per_call == 3
    assert per_call[0].steps_per_call == 1
    assert scan[0].total_flops_per_call > per_call[0].total_flops_per_call * 2
    # the MFU accountant went live off the measured steps
    assert monitor.REGISTRY.collect("train_mfu_pct").value() > 0
    assert xla_ledger.last_mfu("train") > 0


def test_fit_with_ledger_disabled_leaves_no_trace():
    X, Y = _blobs()
    net = _small_net()
    net.fit((X, Y), epochs=1, batch_size=16, scan_steps=1)
    assert xla_ledger.records() == []
    assert net._ledger_cache == {}
    assert not any(name.startswith("xla_") for name in monitor.dump())


def test_graph_fit_captures_program():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    xla_ledger.enable_ledger()
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(3)
                      .updater(Sgd(0.1)))
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(5)))
    g.add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
    g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    X, Y = _blobs()
    net.fit((X, Y), epochs=1, scan_steps=1)
    assert any(r.name == "graph/train_step" for r in xla_ledger.records())


def test_serving_forward_captured_with_serving_domain(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PEAK_FLOPS", "1e12")
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference,
    )
    xla_ledger.enable_ledger()
    net = _small_net()
    X, _ = _blobs(n=16)
    with ParallelInference(net, mode=InferenceMode.SEQUENTIAL) as pi:
        out = pi.output(X)      # debut: captured, MFU skipped (compile)
        out = pi.output(X)      # steady state: feeds serving_mfu_pct
    assert out.shape == (16, 3)
    recs = [r for r in xla_ledger.records() if r.domain == "serving"]
    assert recs and recs[0].name == "inference/forward"
    assert monitor.REGISTRY.collect("serving_mfu_pct").value() > 0


# ------------------------------------------------------------ persistence
def test_save_ledger_schema_and_atomicity(tmp_path):
    xla_ledger.enable_ledger(str(tmp_path / "ledger.json"))
    f = jax.jit(lambda x: (x * 2.0).sum())
    xla_ledger.capture("t/prog", f, (np.ones((8, 4), "float32"),))
    n = xla_ledger.save_ledger()
    assert n == 1
    doc = json.loads((tmp_path / "ledger.json").read_text())
    assert doc["version"] == xla_ledger.LEDGER_SCHEMA_VERSION
    for key in ("created_unix", "device_kind", "backend", "peak_flops",
                "hbm_bytes_per_sec", "programs"):
        assert key in doc
    prog = doc["programs"][0]
    for key in ("fingerprint", "name", "domain", "arg_shapes", "hlo_hash",
                "compile_seconds", "compiles", "flops", "bytes_accessed",
                "arithmetic_intensity", "hbm", "hbm_peak_bytes"):
        assert key in prog
    assert not [p for p in os.listdir(tmp_path)
                if ".tmp." in p]            # atomic write left no temp


def test_save_ledger_merge_existing_across_processes(tmp_path):
    """bench runs every sweep config in its own subprocess against ONE
    DL4J_TPU_PERF_LEDGER file — merge_existing folds prior programs in
    instead of overwriting them."""
    path = str(tmp_path / "ledger.json")
    xla_ledger.enable_ledger(path)
    f = jax.jit(lambda x: (x * 2.0).sum())
    xla_ledger.capture("t/a", f, (np.ones((8, 4), "float32"),))
    xla_ledger.save_ledger()
    # simulate the next config subprocess: fresh in-memory ledger
    xla_ledger.clear_ledger()
    xla_ledger.enable_ledger(path)
    xla_ledger.capture("t/b", f, (np.ones((16, 4), "float32"),))
    assert xla_ledger.save_ledger(merge_existing=True) == 2
    doc = json.loads((tmp_path / "ledger.json").read_text())
    assert {p["name"] for p in doc["programs"]} == {"t/a", "t/b"}
    # re-running the same config dedups by fingerprint, never duplicates
    assert xla_ledger.save_ledger(merge_existing=True) == 2


def test_save_ledger_without_path_raises():
    xla_ledger.enable_ledger()
    with pytest.raises(ValueError):
        xla_ledger.save_ledger()


# ------------------------------------------------------------ perf gate
def _bench_round(value, imgs_sec, on_tpu=False):
    return {"parsed": {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": value, "unit": "imgs/sec", "vs_baseline": None,
        "tpu_unavailable": not on_tpu,
        "sweep": [{"batch": 8, "mode": "per-call", "on_tpu": on_tpu,
                   "imgs_sec": imgs_sec}],
    }}


def _run_perf_report(directory, *extra):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "perf_report.py"),
         "--dir", str(directory), "--json", *extra],
        capture_output=True, text=True, timeout=60)


def test_perf_report_flags_synthetic_regression(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_round(100.0, 100.0)))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(_bench_round(80.0, 80.0)))       # -20% > 15% threshold
    r = _run_perf_report(tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert not report["ok"]
    assert len(report["regressions"]) == 2          # headline + sweep row
    assert report["regressions"][0]["delta_pct"] == -20.0


def test_perf_report_passes_small_delta_and_improvement(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_round(100.0, 100.0)))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(_bench_round(95.0, 120.0)))      # -5% and +20%
    r = _run_perf_report(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["ok"] and report["series_compared"] == 2


def test_perf_report_threshold_is_configurable(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_round(100.0, 100.0)))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(_bench_round(95.0, 95.0)))
    assert _run_perf_report(tmp_path).returncode == 0
    assert _run_perf_report(tmp_path,
                            "--threshold", "0.02").returncode == 2


def test_perf_report_roofline_from_ledger(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_round(100.0, 100.0)))
    ledger = {
        "version": 1, "created_unix": 0, "device_kind": "TPU v5 lite",
        "backend": "tpu", "peak_flops": 197e12, "hbm_bytes_per_sec": 819e9,
        "programs": [
            {"fingerprint": "aa", "name": "mln/train_step",
             "flops": 1e12, "arithmetic_intensity": 500.0,
             "hbm_peak_bytes": 1 << 30, "compile_seconds": 1.0},
            {"fingerprint": "bb", "name": "inference/forward",
             "flops": 1e9, "arithmetic_intensity": 2.0,
             "hbm_peak_bytes": 1 << 20, "compile_seconds": 0.5},
        ],
    }
    lpath = tmp_path / "perf_ledger.json"
    lpath.write_text(json.dumps(ledger))
    r = _run_perf_report(tmp_path, "--ledger", str(lpath))
    assert r.returncode == 0, r.stdout + r.stderr
    roof = json.loads(r.stdout)["roofline"]
    by_fp = {row["fingerprint"]: row for row in roof}
    # ridge = 197e12/819e9 ~= 240.5: AI 500 is compute-bound (ceiling
    # 100%), AI 2.0 is memory-bound with ceiling 2*819e9/197e12
    assert by_fp["aa"]["bound"] == "compute"
    assert by_fp["aa"]["mfu_ceiling_pct"] == 100.0
    assert by_fp["bb"]["bound"] == "memory"
    assert by_fp["bb"]["mfu_ceiling_pct"] == pytest.approx(0.8, abs=0.05)


def _decode_round(tokens_sec, itl_ms, calib_ms=None):
    doc = {"sweep": [{"batch": 4, "mode": "decode", "on_tpu": False,
                      "decode_tokens_sec": tokens_sec,
                      "decode_itl_p99_ms": itl_ms}],
           "tpu_unavailable": True}
    if calib_ms is not None:
        doc["calib_cpu_ms"] = calib_ms
    return doc


def test_perf_report_calibration_normalizes_host_drift(tmp_path):
    """A 2x slower host halves throughput and doubles latency; with both
    rounds calibrated the gate compares in host-normalized space and
    stays clean — a genuine regression on top of the drift still trips."""
    (tmp_path / "DECODE_r01.json").write_text(
        json.dumps(_decode_round(2000.0, 5.0, calib_ms=20.0)))
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(1000.0, 10.0, calib_ms=40.0)))
    r = _run_perf_report(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["ok"] and report["series_compared"] == 2
    for rec in report["comparisons"]:
        assert rec["calibration"]["host_speed_ratio"] == 2.0
        assert rec["delta_pct"] == 0.0
    # same drift + a real 30% code regression: the gate still fires
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(700.0, 10.0, calib_ms=40.0)))
    r = _run_perf_report(tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    reg = json.loads(r.stdout)["regressions"]
    assert [x["series"]["metric"] for x in reg] == ["decode_tokens_sec"]
    assert reg[0]["delta_pct"] == -30.0


def test_perf_report_calibration_excuses_never_convicts(tmp_path):
    """The matmul reference tracks compute speed, not dispatch overhead:
    a faster-calib host must not manufacture a regression out of a
    series whose RAW numbers held steady. Conviction requires the raw
    delta to exceed the threshold too."""
    (tmp_path / "DECODE_r01.json").write_text(
        json.dumps(_decode_round(2000.0, 5.0, calib_ms=40.0)))
    # host calib halved (2x faster matmul) but the code's raw numbers
    # are unchanged — normalized this looks like -50% throughput / 2x
    # latency, yet nothing actually regressed
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(2000.0, 5.0, calib_ms=20.0)))
    r = _run_perf_report(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["ok"]
    for rec in report["comparisons"]:
        assert rec["calibration"]["raw_delta_pct"] == 0.0
        assert not rec["regressed"]
    # a genuine raw regression on the same faster host still trips
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(1200.0, 5.0, calib_ms=20.0)))
    r = _run_perf_report(tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    reg = json.loads(r.stdout)["regressions"]
    assert [x["series"]["metric"] for x in reg] == ["decode_tokens_sec"]
    assert reg[0]["calibration"]["raw_delta_pct"] == -40.0


def test_perf_report_skips_uncalibrated_baselines(tmp_path):
    """A calibrated latest cannot be fairly judged by pre-calibration
    rounds: those are excluded and the series reports as skipped rather
    than gating on raw wall-clock."""
    (tmp_path / "DECODE_r01.json").write_text(
        json.dumps(_decode_round(2000.0, 5.0)))             # legacy round
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(1000.0, 10.0, calib_ms=40.0)))
    r = _run_perf_report(tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["ok"] and report["series_compared"] == 0
    assert {s["series"]["metric"] for s in report["series_skipped"]} \
        == {"decode_tokens_sec", "decode_itl_p99_ms"}
    assert all(s["reason"] == "no calibrated baseline round"
               for s in report["series_skipped"])


def test_perf_report_uncalibrated_latest_keeps_raw_comparison(tmp_path):
    """Legacy behavior is untouched when the LATEST round lacks a
    calibration reference — even if an earlier round has one."""
    (tmp_path / "DECODE_r01.json").write_text(
        json.dumps(_decode_round(2000.0, 5.0, calib_ms=20.0)))
    (tmp_path / "DECODE_r02.json").write_text(
        json.dumps(_decode_round(1000.0, 5.0)))             # raw -50%
    r = _run_perf_report(tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    reg = json.loads(r.stdout)["regressions"]
    assert [x["series"]["metric"] for x in reg] == ["decode_tokens_sec"]
    assert "calibration" not in reg[0]


def test_perf_report_banked_repo_trajectory_is_clean():
    """The acceptance gate: the repo's own banked BENCH history exits 0."""
    r = _run_perf_report(_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(r.stdout)
    assert report["ok"] and report["series_compared"] >= 1
