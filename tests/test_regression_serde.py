"""Serialization back-compat regression tests.

Parity: DL4J `deeplearning4j-core/.../regressiontest/RegressionTest{050,060,
071,080}.java` — archived model zips from earlier versions must keep
loading bit-identically, so a format change can never silently orphan old
checkpoints. The fixtures under tests/fixtures/ were produced by the
round-3 tree (format_version=1); every future round must keep them loading
with identical parameters AND identical outputs on the archived probes.

If a fixture fails here, the serialization change is backward-incompatible:
bump format_version, add a legacy-read path, and regenerate expectations —
never weaken these assertions.
"""
import os

import numpy as np

from deeplearning4j_tpu.util.serialization import load_model

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _expected():
    return np.load(os.path.join(FIXTURES, "golden_expected_v1.npz"))


def test_golden_cnn_checkpoint_loads_identically():
    exp = _expected()
    net = load_model(os.path.join(FIXTURES, "golden_cnn_v1.zip"))
    np.testing.assert_array_equal(np.asarray(net.params_flat()),
                                  exp["cnn_params"])
    out = np.asarray(net.output(exp["cnn_probe"]))
    np.testing.assert_allclose(out, exp["cnn_out"], rtol=1e-5, atol=1e-6)
    # updater state restored: one more fit step must not crash
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    rs = np.random.RandomState(0)
    X = rs.rand(8, 8, 8, 1).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 8)]
    net.fit(ArrayDataSetIterator(X, Y, batch_size=8), epochs=1)
    assert np.isfinite(net.score())


def test_golden_lstm_checkpoint_loads_identically():
    exp = _expected()
    net = load_model(os.path.join(FIXTURES, "golden_lstm_v1.zip"))
    np.testing.assert_array_equal(np.asarray(net.params_flat()),
                                  exp["lstm_params"])
    out = np.asarray(net.output(exp["lstm_probe"]))
    np.testing.assert_allclose(out, exp["lstm_out"], rtol=1e-5, atol=1e-6)


def test_golden_checkpoint_format_entries():
    """The zip layout itself is the contract: configuration.json +
    coefficients.npz + updaterState.bin (ModelSerializer.java:39-125)."""
    import zipfile
    with zipfile.ZipFile(os.path.join(FIXTURES, "golden_cnn_v1.zip")) as z:
        names = set(z.namelist())
    assert "configuration.json" in names
    assert any("coefficients" in n for n in names)
    assert any("updaterState" in n for n in names)


def test_round4_layer_conf_json_round_trip():
    """Every round-4 layer type survives the JSON conf round trip (the
    replication + persistence format)."""
    import numpy as np

    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import (
        MultiLayerConfiguration, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import (
        Cropping1D, DenseLayer, DropoutLayer, LocallyConnected1D,
        LocallyConnected2D, OutputLayer, PermuteLayer, RepeatVector,
        ReshapeLayer, Upsampling1D, ZeroPadding1DLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.regularization import SpatialDropout

    conf = (NeuralNetConfiguration.Builder().seed(9)
            .list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(RepeatVector(n=5))
            .layer(PermuteLayer(dims=(2, 1)))
            .layer(ReshapeLayer(target=(10, 6)))
            .layer(Cropping1D(cropping=(1, 1)))
            .layer(Upsampling1D(size=2))
            .layer(ZeroPadding1DLayer(padding=(0, 1)))
            .layer(LocallyConnected1D(n_out=4, kernel=3,
                                      activation="tanh"))
            .layer(DropoutLayer(dropout=SpatialDropout(p=0.3)))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back == conf
    # and the round-tripped conf still initializes + runs forward
    net = MultiLayerNetwork(back).init()
    out = np.asarray(net.output(np.zeros((2, 6), "float32")))
    assert out.shape == (2, 2)

    # 2D locally-connected round trip too
    conf2 = (NeuralNetConfiguration.Builder().seed(9).list()
             .layer(LocallyConnected2D(n_out=3, kernel=(2, 2)))
             .layer(OutputLayer(n_out=2, activation="softmax",
                                loss="mcxent"))
             .set_input_type(InputType.convolutional(4, 4, 2)).build())
    assert MultiLayerConfiguration.from_json(conf2.to_json()) == conf2


def test_yaml_conf_round_trip():
    """DL4J toYaml/fromYaml parity on both configuration classes."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import (
        ComputationGraphConfiguration, GraphBuilder,
        MultiLayerConfiguration, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(11).updater(Adam(2e-3))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu", dropout=0.25))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    assert MultiLayerConfiguration.from_yaml(conf.to_yaml()) == conf

    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(3)
                      .updater(Adam(1e-3)))
         .add_inputs("in").set_input_types(InputType.feed_forward(4)))
    g.add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"), "in")
    g.set_outputs("out")
    gconf = g.build()
    assert ComputationGraphConfiguration.from_yaml(gconf.to_yaml()) == gconf


def test_golden_yaml_fixture_loads():
    """Format-drift guard: the committed v1 YAML conf must keep loading
    (the same golden-fixture discipline as the JSON/zip artifacts)."""
    import os

    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    p = os.path.join(os.path.dirname(__file__), "fixtures",
                     "golden_conf_v1.yaml")
    conf = MultiLayerConfiguration.from_yaml(open(p).read())
    assert conf.seed == 2026
    assert len(conf.layers) == 2
    assert type(conf.layers[0]).__name__ == "DenseLayer"
    assert conf.layers[0].dropout == 0.1
    net = MultiLayerNetwork(conf).init()
    assert net.num_params() > 0


def test_round5_layer_conf_json_round_trip():
    """Round-5 parity closers survive the JSON round trip and run."""
    import numpy as np

    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import (
        MultiLayerConfiguration, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import (
        DenseLayer, ElementWiseMultiplicationLayer, OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(5).list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(ElementWiseMultiplicationLayer(n_out=8,
                                                  activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back == conf
    net = MultiLayerNetwork(back).init()
    assert np.asarray(net.output(np.zeros((2, 4), "float32"))).shape == (2, 3)

    # PoolHelperVertex graph conf round-trips too
    from deeplearning4j_tpu.nn.conf.graph_vertices import PoolHelperVertex
    from deeplearning4j_tpu.nn.conf.network import (
        ComputationGraphConfiguration, GraphBuilder,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(5))
         .add_inputs("in")
         .set_input_types(InputType.convolutional(5, 5, 2)))
    g.add_layer("c", ConvolutionLayer(n_out=2, kernel=(3, 3),
                                      convolution_mode="same"), "in")
    g.add_vertex("ph", PoolHelperVertex(), "c")
    g.add_layer("out", OutputLayer(n_out=2), "ph")
    g.set_outputs("out")
    gconf = g.build()
    gback = ComputationGraphConfiguration.from_json(gconf.to_json())
    assert gback == gconf
    gn = ComputationGraph(gback).init()
    out = gn.output(np.zeros((1, 5, 5, 2), "float32"))
    arr = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
    assert arr.shape == (1, 2)
