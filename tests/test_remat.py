"""Gradient checkpointing (jax.checkpoint rematerialization).

With gradient_checkpointing=True each layer/vertex recomputes its
activations in the backward pass instead of storing them — the TPU HBM
lever for deep nets and long sequences. Remat must not change the math:
training with it on and off must produce (near-)identical parameters.
"""
import dataclasses

import numpy as np

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, LSTM, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def _blobs(n=96, nc=3, nf=6, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nc, nf)) * 4
    X = np.concatenate([rng.normal(size=(n // nc, nf)) + c
                        for c in centers]).astype(np.float32)
    Y = np.eye(nc, dtype=np.float32)[
        np.repeat(np.arange(nc), n // nc)]
    return X, Y


def _mlp_conf(remat):
    b = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2)))
    if remat:
        b = b.gradient_checkpointing()
    return (b.list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())


def _params_flat(net):
    import jax
    return np.concatenate([np.asarray(x).ravel()
                           for x in jax.tree_util.tree_leaves(net.params)])


class TestRematParity:
    def test_mlp_training_identical_with_and_without(self):
        X, Y = _blobs()
        nets = []
        for remat in (False, True):
            net = MultiLayerNetwork(_mlp_conf(remat)).init()
            net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=3)
            nets.append(net)
        base, remat = nets
        np.testing.assert_allclose(_params_flat(base), _params_flat(remat),
                                   rtol=1e-5, atol=1e-6)
        # and it actually learns
        ev = remat.evaluate(ArrayDataSetIterator(X, Y, batch_size=32))
        assert ev.accuracy() > 0.8

    def test_rnn_training_identical_with_and_without(self):
        rs = np.random.RandomState(3)
        T, F = 12, 5
        X = rs.rand(24, T, F).astype(np.float32)
        Y = np.eye(4, dtype=np.float32)[
            rs.randint(0, 4, (24, T))]
        nets = []
        for remat in (False, True):
            b = NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-2))
            if remat:
                b = b.gradient_checkpointing()
            conf = (b.list()
                    .layer(LSTM(n_out=8, activation="tanh"))
                    .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                          loss="mcxent"))
                    .set_input_type(InputType.recurrent(F, T))
                    .build())
            net = MultiLayerNetwork(conf).init()
            net.fit(ArrayDataSetIterator(X, Y, batch_size=12), epochs=2)
            nets.append(net)
        np.testing.assert_allclose(_params_flat(nets[0]),
                                   _params_flat(nets[1]),
                                   rtol=1e-5, atol=1e-6)

    def test_graph_training_identical_with_and_without(self):
        X, Y = _blobs()
        nets = []
        for remat in (False, True):
            b = NeuralNetConfiguration.Builder().seed(9).updater(Adam(1e-2))
            gb = (b.graph_builder()
                  .add_inputs("in")
                  .add_layer("d1", DenseLayer(n_out=12, activation="relu"),
                             "in")
                  .add_layer("d2", DenseLayer(n_out=12, activation="relu"),
                             "d1")
                  .add_layer("out", OutputLayer(n_out=3,
                                                activation="softmax",
                                                loss="mcxent"), "d2")
                  .set_outputs("out")
                  .set_input_types(InputType.feed_forward(6)))
            conf = gb.build()
            if remat:
                conf = dataclasses.replace(conf,
                                           gradient_checkpointing=True)
            net = ComputationGraph(conf).init()
            net.fit(ArrayDataSetIterator(X, Y, batch_size=32), epochs=3)
            nets.append(net)
        np.testing.assert_allclose(_params_flat(nets[0]),
                                   _params_flat(nets[1]),
                                   rtol=1e-5, atol=1e-6)


class TestRematSerde:
    def test_flag_round_trips_json_and_builder(self):
        conf = _mlp_conf(True)
        assert conf.gradient_checkpointing is True
        from deeplearning4j_tpu.nn.conf.network import (
            MultiLayerConfiguration,
        )
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.gradient_checkpointing is True
        # default stays off and old JSON (no field) reads as off
        d = conf.to_dict()
        del d["gradient_checkpointing"]
        assert MultiLayerConfiguration.from_dict(
            d).gradient_checkpointing is False
