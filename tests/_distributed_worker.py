"""Worker for the REAL 2-process multi-host test (jax.distributed over
localhost — the TPU-native analog of the reference exercising its
distributed paths in-process with Spark local[N], SURVEY.md §4, and of
`SharedTrainingWrapper.java:206-244` forming the worker mesh).

Each OS process contributes 4 virtual CPU devices; the 2-process cluster
forms a global 8-device mesh and runs ParallelWrapper sync-DP.

Usage: python tests/_distributed_worker.py RANK NPROC COORD_PORT OUT.npz
"""
import os
import sys

rank, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
out_path = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=4").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import (  # noqa: E402
    DistributedConfig, initialize_distributed,
)

multi = initialize_distributed(DistributedConfig(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc, process_id=rank))
assert multi, "distributed runtime did not form"
assert jax.process_count() == nproc
assert jax.device_count() == 4 * nproc
assert jax.local_device_count() == 4

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf.base import InputType  # noqa: E402
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.updaters import Adam  # noqa: E402
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode  # noqa: E402


def blob_data(n=256, d=8, k=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


X, Y = blob_data()             # identical on every process (global batch)
conf = (NeuralNetConfiguration.Builder()
        .seed(11).updater(Adam(5e-2)).list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8)).build())

# optional phase for the elastic-recovery exercise (SURVEY.md §5.3:
# checkpoint + restart IS the multi-host failure story):
#   phase=first  : train 4 epochs, coordinator checkpoints, exit (the
#                  "crash" — the whole cluster goes down)
#   phase=resume : a NEW cluster restores the checkpoint and trains the
#                  remaining 4 epochs
#   (unset)      : uninterrupted 8 epochs — must end bit-identical
phase = os.environ.get("DL4J_TPU_WORKER_PHASE", "")
ckpt = os.environ.get("DL4J_TPU_WORKER_CKPT", "")

if phase == "resume":
    from deeplearning4j_tpu.util.serialization import load_model
    net = load_model(ckpt)
else:
    net = MultiLayerNetwork(conf).init()

wrapper = ParallelWrapper(net, mode=TrainingMode.SYNC_GRADIENTS)
assert wrapper.n_workers == 4 * nproc      # global mesh, not local
epochs = 4 if phase in ("first", "resume") else 8
wrapper.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=epochs)

if phase == "first":
    if rank == 0:              # coordinator saves (TrainingMaster role)
        from deeplearning4j_tpu.util.serialization import save_model
        save_model(net, ckpt)

acc = net.evaluate((X, Y)).accuracy()
np.savez(out_path,
         params=np.asarray(net.params_flat()),
         accuracy=acc,
         final_score=net.score(),
         process_count=jax.process_count(),
         device_count=jax.device_count())
print(f"rank {rank}: acc={acc:.3f} score={net.score():.4f}", flush=True)
