"""Unit tests for tools/tpu_watcher.sh's banking/derive logic.

Two earlier sessions lost measurement artifacts to exactly these
functions (a bank racing a concurrent commit; a /tmp wipe re-running a
banked stage and overwriting the analyzed artifact) — the script is
ops-critical, so its pure functions are tested hermetically against a
throwaway git repo via `source` + DL4J_TPU_WATCHER_REPO.
"""
import json
import os
import subprocess
from pathlib import Path

import pytest

SCRIPT = str(Path(__file__).resolve().parent.parent / "tools" /
             "tpu_watcher.sh")


def _sh(repo, body):
    """Source the watcher in `repo` then run `body` in the same shell."""
    return subprocess.run(
        ["bash", "-c", f'source "{SCRIPT}" && {body}'],
        env={**os.environ, "DL4J_TPU_WATCHER_REPO": str(repo)},
        capture_output=True, text=True, timeout=120)


@pytest.fixture()
def repo(tmp_path):
    r = tmp_path / "repo"
    r.mkdir()
    for cmd in (["git", "init", "-q"],
                ["git", "config", "user.email", "t@t"],
                ["git", "config", "user.name", "t"],
                ["git", "commit", "-q", "--allow-empty", "-m", "root"]):
        subprocess.run(cmd, cwd=r, check=True)
    return r


def _head_paths(repo):
    out = subprocess.run(["git", "show", "--name-only", "--format=",
                          "HEAD"], cwd=repo, capture_output=True,
                         text=True, check=True)
    return out.stdout.split()


class TestBank:
    def test_commits_only_the_artifact(self, repo, tmp_path):
        (repo / "unrelated.txt").write_text("staged by someone else")
        subprocess.run(["git", "add", "unrelated.txt"], cwd=repo,
                       check=True)
        src = tmp_path / "result.json"
        src.write_text('{"value": 1}')
        r = _sh(repo, f'bank "{src}" ART.json "bank it"')
        assert r.returncode == 0, r.stderr
        assert _head_paths(repo) == ["ART.json"]
        # the concurrent session's staged file is still staged, uncommitted
        st = subprocess.run(["git", "status", "--porcelain"], cwd=repo,
                            capture_output=True, text=True).stdout
        assert "A  unrelated.txt" in st

    def test_idempotent_when_content_at_head(self, repo, tmp_path):
        src = tmp_path / "result.json"
        src.write_text('{"value": 2}')
        assert _sh(repo, f'bank "{src}" ART.json "first"').returncode == 0
        n1 = subprocess.run(["git", "rev-list", "--count", "HEAD"],
                            cwd=repo, capture_output=True,
                            text=True).stdout.strip()
        assert _sh(repo, f'bank "{src}" ART.json "second"').returncode == 0
        n2 = subprocess.run(["git", "rev-list", "--count", "HEAD"],
                            cwd=repo, capture_output=True,
                            text=True).stdout.strip()
        assert n1 == n2              # no new commit for identical content


class TestMeasuredRow:
    def _sweep(self, tmp_path, rows):
        p = tmp_path / "sweep.json"
        p.write_text(json.dumps({"sweep": rows}))
        return p

    def test_measured_row_true_for_on_tpu_result(self, repo, tmp_path):
        p = self._sweep(tmp_path, [
            {"mode": "char-lstm", "on_tpu": True, "chars_sec": 1e6}])
        assert _sh(repo, f'measured_row "{p}" char-lstm').returncode == 0

    def test_error_and_skipped_rows_do_not_count(self, repo, tmp_path):
        p = self._sweep(tmp_path, [
            {"kind": "char-lstm", "on_tpu": True, "error": "rc=1"},
            {"kind": "char-lstm", "skipped": "tunnel wedged"},
            {"mode": "char-lstm", "on_tpu": False, "chars_sec": 5.0}])
        assert _sh(repo, f'measured_row "{p}" char-lstm').returncode != 0


class TestStageOneDerive:
    ART = "BENCH_TPU_MEASURED_r05.json"
    GOOD = json.dumps({"value": 123.0, "tpu_unavailable": False})

    def test_committed_artifact_marks_done(self, repo):
        (repo / self.ART).write_text(self.GOOD)
        subprocess.run(["git", "add", self.ART], cwd=repo, check=True)
        subprocess.run(["git", "commit", "-q", "-m", "bank"], cwd=repo,
                       check=True)
        assert _sh(repo, "true").returncode == 0
        assert (repo / ".watcher" / "bench_tpu_done").exists()

    def test_uncommitted_stranded_copy_keeps_stage_live(self, repo):
        (repo / self.ART).write_text(self.GOOD)   # stranded, not committed
        assert _sh(repo, "true").returncode == 0
        assert not (repo / ".watcher" / "bench_tpu_done").exists()

    def test_cpu_fallback_artifact_keeps_stage_live(self, repo):
        (repo / self.ART).write_text(
            json.dumps({"value": 2.8, "tpu_unavailable": True}))
        subprocess.run(["git", "add", self.ART], cwd=repo, check=True)
        subprocess.run(["git", "commit", "-q", "-m", "cpu"], cwd=repo,
                       check=True)
        assert _sh(repo, "true").returncode == 0
        assert not (repo / ".watcher" / "bench_tpu_done").exists()


class TestBankWindowed:
    def test_dedupes_identical_payload_and_seeds_from_repo(self, repo,
                                                           tmp_path):
        src = tmp_path / "rows.jsonl"
        src.write_text('{"on_tpu": true, "x": 1}\n')
        acc = tmp_path / "acc.jsonl"
        body = f'bank_windowed "{src}" "{acc}" WIN.jsonl "w1"'
        assert _sh(repo, body).returncode == 0
        n1 = subprocess.run(["git", "rev-list", "--count", "HEAD"],
                            cwd=repo, capture_output=True,
                            text=True).stdout.strip()
        # identical payload again: no append, no new commit
        assert _sh(repo, body).returncode == 0
        n2 = subprocess.run(["git", "rev-list", "--count", "HEAD"],
                            cwd=repo, capture_output=True,
                            text=True).stdout.strip()
        assert n1 == n2
        banked = (repo / "WIN.jsonl").read_text()
        assert banked.count('"x": 1') == 1
        # fresh shell with an EMPTY accumulator (simulated /tmp wipe) and a
        # NEW payload: seeds from the repo copy so the old row survives
        src.write_text('{"on_tpu": true, "x": 2}\n')
        acc2 = tmp_path / "acc2.jsonl"
        assert _sh(repo,
                   f'bank_windowed "{src}" "{acc2}" WIN.jsonl "w2"'
                   ).returncode == 0
        banked = (repo / "WIN.jsonl").read_text()
        assert '"x": 1' in banked and '"x": 2' in banked
