"""Fault-tolerant training: resilient fit loop, atomic checkpoint/resume,
preemption, per-step fault policy — all driven through the deterministic
fault-injection harness (util/faults.py). No sleep exceeds the backoff
floor (FaultPolicy backoff_base is set to ~1ms throughout)."""
import json
import os
import signal
import threading
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import (
    GraphBuilder, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.train import (
    CheckpointManager, FaultPolicy, ResilientTrainer, TrainingDivergedError,
    TrainingListener,
)
from deeplearning4j_tpu.util.faults import (
    FaultInjector, SimulatedCrash, TransientFaultError,
    attach_transport_faults,
)
from deeplearning4j_tpu.util.serialization import load_model

rs = np.random.RandomState(0)
X = rs.randn(120, 6).astype("float32")
Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 120)]

FAST = FaultPolicy(backoff_base=0.001, backoff_max=0.004)


def _net(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(shuffle=False):
    return ArrayDataSetIterator(X, Y, batch_size=30, shuffle=shuffle, seed=5)


def _flat(net):
    return np.asarray(net.params_flat())


def _reference_params(tmp_path, epochs=3):
    """Params/score of an uninterrupted resilient fit (the parity target)."""
    net = _net()
    ResilientTrainer(net, str(tmp_path / "ref"), save_every_n_iterations=100,
                     policy=FAST).fit(_data(), epochs=epochs)
    return _flat(net), net.score(), net.iteration_count


# --------------------------------------------------------------- resume parity
def test_resume_parity_after_crash(tmp_path):
    """Kill-at-k + auto-resume reaches bitwise-identical params, updater
    state effects, RNG stream, and final score vs an uninterrupted run —
    including an epoch-dependent shuffling iterator."""
    ref = _net()
    ResilientTrainer(ref, str(tmp_path / "a"), save_every_n_iterations=100,
                     policy=FAST).fit(_data(shuffle=True), epochs=3)

    crashed = _net()
    with pytest.raises(SimulatedCrash):
        ResilientTrainer(crashed, str(tmp_path / "b"),
                         save_every_n_iterations=2, policy=FAST,
                         injector=FaultInjector(crash_at=5)
                         ).fit(_data(shuffle=True), epochs=3)

    resumed = _net()
    rep = ResilientTrainer(resumed, str(tmp_path / "b"),
                           save_every_n_iterations=2, policy=FAST
                           ).fit(_data(shuffle=True), epochs=3)
    assert rep.resumed_from is not None
    np.testing.assert_array_equal(_flat(ref), _flat(resumed))
    assert ref.score() == resumed.score()
    assert ref.iteration_count == resumed.iteration_count
    assert ref.epoch_count == resumed.epoch_count


def test_resume_parity_computation_graph(tmp_path):
    def graph():
        g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(3)
                          .updater(Adam(1e-2)))
             .add_inputs("in").set_input_types(InputType.feed_forward(6)))
        g.add_layer("d", DenseLayer(n_out=12), "in")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d")
        g.set_outputs("out")
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph(g.build()).init()

    ref = graph()
    ResilientTrainer(ref, str(tmp_path / "a"), save_every_n_iterations=100,
                     policy=FAST).fit(_data(), epochs=2)
    crashed = graph()
    with pytest.raises(SimulatedCrash):
        ResilientTrainer(crashed, str(tmp_path / "b"),
                         save_every_n_iterations=2, policy=FAST,
                         injector=FaultInjector(crash_at=3)
                         ).fit(_data(), epochs=2)
    resumed = graph()
    rep = ResilientTrainer(resumed, str(tmp_path / "b"),
                           save_every_n_iterations=2, policy=FAST
                           ).fit(_data(), epochs=2)
    assert rep.resumed_from is not None
    np.testing.assert_array_equal(_flat(ref), _flat(resumed))


def test_resume_parity_parallel_wrapper(tmp_path):
    from deeplearning4j_tpu.parallel import ParallelWrapper
    ref = _net()
    ResilientTrainer(ParallelWrapper(ref), str(tmp_path / "a"),
                     save_every_n_iterations=100, policy=FAST
                     ).fit(_data(), epochs=2)
    crashed = _net()
    with pytest.raises(SimulatedCrash):
        ResilientTrainer(ParallelWrapper(crashed), str(tmp_path / "b"),
                         save_every_n_iterations=2, policy=FAST,
                         injector=FaultInjector(crash_at=3)
                         ).fit(_data(), epochs=2)
    resumed = _net()
    rep = ResilientTrainer(ParallelWrapper(resumed), str(tmp_path / "b"),
                           save_every_n_iterations=2, policy=FAST
                           ).fit(_data(), epochs=2)
    assert rep.resumed_from is not None
    np.testing.assert_array_equal(_flat(ref), _flat(resumed))


def test_resume_under_streaming_lands_on_exact_shard_offset(tmp_path):
    """ShardDataSetIterator + kill + auto-resume: bitwise parity with the
    uninterrupted run AND the resume SEEKS to the checkpointed shard
    offset (banked in resilience.json as `stream`) instead of replaying
    the stream prefix — the resumed iterator reads only the remaining
    batches."""
    from deeplearning4j_tpu.data.shards import (
        ShardDataSetIterator, write_shards,
    )
    shard_dir = str(tmp_path / "shards")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=30, drop_last=False),
                 shard_dir, shard_records=32)

    def _shard_it():
        return ShardDataSetIterator(shard_dir, batch_size=30,
                                    shuffle=True, seed=5)

    ref = _net()
    ResilientTrainer(ref, str(tmp_path / "a"), save_every_n_iterations=100,
                     policy=FAST).fit(_shard_it(), epochs=3)

    crashed = _net()
    with pytest.raises(SimulatedCrash):
        # 4 batches/epoch: checkpoint lands at step-in-epoch 2, the
        # crash hits before the epoch completes — the newest checkpoint
        # is MID-epoch, mid-stream
        ResilientTrainer(crashed, str(tmp_path / "b"),
                         save_every_n_iterations=2, policy=FAST,
                         injector=FaultInjector(crash_at=3)
                         ).fit(_shard_it(), epochs=3)

    # the checkpoint banks the exact stream position the next batch
    # starts at (shard file + record offset), not just a step count
    entry = CheckpointManager(str(tmp_path / "b")).latest_valid()
    with zipfile.ZipFile(entry["path"]) as zf:
        extra = json.loads(zf.read("resilience.json"))
    assert extra["step_in_epoch"] == 2
    assert extra["stream"]["next_batch"] == 2
    assert extra["stream"]["record_offset"] % 30 == 0
    assert extra["stream"]["shard_file"].endswith(".shard")

    resumed = _net()
    it = _shard_it()
    rep = ResilientTrainer(resumed, str(tmp_path / "b"),
                           save_every_n_iterations=2, policy=FAST
                           ).fit(it, epochs=3)
    assert rep.resumed_from is not None
    np.testing.assert_array_equal(_flat(ref), _flat(resumed))
    assert ref.score() == resumed.score()
    # exact-offset resume: 4 batches/epoch x 3 epochs = 12 total; 2 were
    # stepped before the crash and must NOT be re-read on resume
    assert it.batches_read == 12 - 2


def test_preempt_refit_same_process_multiproc_pipeline(tmp_path):
    """Preempt a worker-mode MultiProcessDataSetIterator fit at EPOCH 1,
    then re-fit the SAME trainer state with the SAME live pipeline: the
    ring resumes at its internal position, so resilience must take the
    seek path (the replay fast-forward would discard step_in_epoch MORE
    batches — checkpoint-counted but never trained), and the
    replay-resets loop must skip the epoch resets the live source
    already consumed in-fit (blind replay would seek into epoch 2's
    shuffle permutation while training epoch 1)."""
    from deeplearning4j_tpu.data.pipeline import (
        MultiProcessDataSetIterator, ShardBatchLoader,
    )
    from deeplearning4j_tpu.data.shards import write_shards
    shard_dir = str(tmp_path / "shards")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=30, drop_last=False),
                 shard_dir, shard_records=32)

    def _pipe():
        return MultiProcessDataSetIterator(
            ShardBatchLoader(shard_dir, 30, shuffle=True, seed=5,
                             drop_last=False), num_workers=2)

    ref = _net()
    with _pipe() as p:
        ResilientTrainer(ref, str(tmp_path / "a"),
                         save_every_n_iterations=100, policy=FAST
                         ).fit(p, epochs=2)

    net = _net()
    ckpt = str(tmp_path / "b")
    with _pipe() as p:
        rep = ResilientTrainer(net, ckpt, save_every_n_iterations=1,
                               policy=FAST,
                               injector=FaultInjector(preempt_at=5)
                               ).fit(p, epochs=2)
        assert rep.preempted
        # 4 batches/epoch: dispatch 5 = epoch 1, step-in-epoch 1 —
        # mid-epoch past the first in-fit reset, position retained
        assert p.tell() == 1 and p.stream_state()["epoch"] == 1
        rep2 = ResilientTrainer(net, ckpt, policy=FAST).fit(p, epochs=2)
        assert rep2.resumed_from is not None
        # 4 batches/epoch x 2 epochs = 8; 5 trained before preemption
        assert rep2.applied_steps == 8 - 5
    np.testing.assert_array_equal(_flat(ref), _flat(net))


def test_completed_run_does_not_retrain_on_rerun(tmp_path):
    net = _net()
    t = ResilientTrainer(net, str(tmp_path), save_every_n_iterations=100,
                         policy=FAST)
    t.fit(_data(), epochs=2)
    before = _flat(net)
    ckpts_before = sorted(f for f in os.listdir(str(tmp_path))
                          if f.startswith("ckpt_"))
    rerun = _net()
    rep = ResilientTrainer(rerun, str(tmp_path), policy=FAST
                           ).fit(_data(), epochs=2)
    assert rep.applied_steps == 0 and rep.resumed_from is not None
    np.testing.assert_array_equal(before, _flat(rerun))
    # a no-op rerun must not write duplicate final checkpoints (they would
    # rotate real training history out of keep_last)
    assert sorted(f for f in os.listdir(str(tmp_path))
                  if f.startswith("ckpt_")) == ckpts_before


# ---------------------------------------------------------------- fault policy
def test_nan_steps_skipped_without_crashing(tmp_path):
    net = _net()
    rep = ResilientTrainer(net, str(tmp_path), save_every_n_iterations=100,
                           policy=FAST,
                           injector=FaultInjector(nan_at=(3, 7))
                           ).fit(_data(), epochs=3)
    assert rep.skipped_steps == 2
    assert rep.applied_steps == 10          # 12 batches - 2 skipped
    assert not rep.diverged
    assert np.isfinite(_flat(net)).all()
    assert np.isfinite(net.score())
    # skipped batches don't count as optimizer steps (DL4J iteration
    # semantics: one iteration = one applied update)
    assert net.iteration_count == 10


def test_consecutive_skip_threshold_restores_last_good_checkpoint(tmp_path):
    net = _net()
    rep = ResilientTrainer(
        net, str(tmp_path), save_every_n_iterations=2,
        policy=FaultPolicy(max_consecutive_skips=2, backoff_base=0.001),
        injector=FaultInjector(nan_at=range(4, 50))).fit(_data(), epochs=3)
    assert rep.diverged
    assert rep.restored_checkpoint is not None
    # graceful degradation: the model holds the checkpointed (good) params
    ck = load_model(rep.restored_checkpoint)
    np.testing.assert_array_equal(np.asarray(ck.params_flat()), _flat(net))
    assert np.isfinite(_flat(net)).all()


def test_unrecoverable_raise_mode(tmp_path):
    net = _net()
    with pytest.raises(TrainingDivergedError):
        ResilientTrainer(
            net, str(tmp_path), save_every_n_iterations=2,
            policy=FaultPolicy(max_consecutive_skips=2, backoff_base=0.001,
                               on_unrecoverable="raise"),
            injector=FaultInjector(nan_at=range(4, 50))
        ).fit(_data(), epochs=3)
    assert np.isfinite(_flat(net)).all()    # restored before raising


def test_transient_retry_is_transparent(tmp_path):
    """A retried step is bitwise-identical to an unfaulted one (same RNG
    sub-key, same batch, pre-step snapshot restored)."""
    clean = _net()
    ResilientTrainer(clean, str(tmp_path / "a"), save_every_n_iterations=100,
                     policy=FAST).fit(_data(), epochs=3)
    faulted = _net()
    inj = FaultInjector(transient_at=(2, 5))
    rep = ResilientTrainer(faulted, str(tmp_path / "b"),
                           save_every_n_iterations=100, policy=FAST,
                           injector=inj).fit(_data(), epochs=3)
    assert rep.retries == 2 and inj.transients_injected == 2
    np.testing.assert_array_equal(_flat(clean), _flat(faulted))


def test_retry_exhaustion_checkpoints_then_raises(tmp_path):
    net = _net()
    trainer = ResilientTrainer(
        net, str(tmp_path), save_every_n_iterations=100,
        policy=FaultPolicy(max_retries=1, backoff_base=0.001,
                           backoff_max=0.002),
        # same step keeps faulting across retries: three distinct
        # dispatch indices all scheduled
        injector=_AlwaysTransient())
    with pytest.raises(TransientFaultError):
        trainer.fit(_data(), epochs=1)
    # the pre-fault state was checkpointed for a later resume
    assert trainer.ckpt.latest_valid() is not None


class _AlwaysTransient(FaultInjector):
    def before_step(self, step):
        raise TransientFaultError(f"flaky forever at step {step}")


class _StuckStep(FaultInjector):
    """One step that fails on EVERY attempt (retry cannot save it)."""

    def __init__(self, step):
        super().__init__()
        self._stuck = step

    def before_step(self, step):
        if step == self._stuck:
            raise TransientFaultError(f"stuck at step {step}")


def test_resume_parity_after_retry_exhaustion(tmp_path):
    """The emergency checkpoint written when retries run out must rewind
    the RNG carry to the failed step, so a resumed run re-derives the SAME
    subkey for it — bitwise parity holds across the failure."""
    ref_params, ref_score, _ = _reference_params(tmp_path)
    faulted = _net()
    with pytest.raises(TransientFaultError):
        ResilientTrainer(faulted, str(tmp_path / "b"),
                         save_every_n_iterations=100,
                         policy=FaultPolicy(max_retries=1,
                                            backoff_base=0.001,
                                            backoff_max=0.002),
                         injector=_StuckStep(5)).fit(_data(), epochs=3)
    resumed = _net()
    rep = ResilientTrainer(resumed, str(tmp_path / "b"),
                           save_every_n_iterations=100, policy=FAST
                           ).fit(_data(), epochs=3)
    assert rep.resumed_from is not None
    np.testing.assert_array_equal(ref_params, _flat(resumed))
    assert ref_score == resumed.score()


# ----------------------------------------------------------------- preemption
def test_preemption_via_sigterm_checkpoints_and_resumes(tmp_path):
    ref_params, ref_score, _ = _reference_params(tmp_path)

    class Kick(TrainingListener):
        def iteration_done(self, model, iteration, epoch, score,
                           etl_ms=0.0, batch_size=0):
            if iteration == 4:
                os.kill(os.getpid(), signal.SIGTERM)

    net = _net()
    net.set_listeners(Kick())
    rep = ResilientTrainer(net, str(tmp_path / "p"),
                           save_every_n_iterations=100, policy=FAST
                           ).fit(_data(), epochs=3)
    assert rep.preempted
    # resumable: a fresh run completes to parity with the uninterrupted one
    resumed = _net()
    rep2 = ResilientTrainer(resumed, str(tmp_path / "p"),
                            save_every_n_iterations=100, policy=FAST
                            ).fit(_data(), epochs=3)
    assert rep2.resumed_from is not None and not rep2.preempted
    np.testing.assert_array_equal(ref_params, _flat(resumed))
    assert ref_score == resumed.score()


def test_preemption_via_injector(tmp_path):
    ref_params, _, _ = _reference_params(tmp_path)
    net = _net()
    rep = ResilientTrainer(net, str(tmp_path / "p"),
                           save_every_n_iterations=100, policy=FAST,
                           injector=FaultInjector(preempt_at=5)
                           ).fit(_data(), epochs=3)
    assert rep.preempted and rep.checkpoints_written >= 1
    resumed = _net()
    ResilientTrainer(resumed, str(tmp_path / "p"),
                     save_every_n_iterations=100, policy=FAST
                     ).fit(_data(), epochs=3)
    np.testing.assert_array_equal(ref_params, _flat(resumed))


# --------------------------------------------------------- checkpoint manager
def test_corrupted_checkpoint_falls_back_to_previous(tmp_path):
    net = _net()
    trainer = ResilientTrainer(net, str(tmp_path), save_every_n_iterations=2,
                               policy=FAST)
    trainer.fit(_data(), epochs=1)
    mgr = trainer.ckpt
    entries = mgr._read_manifest()["checkpoints"]
    assert len(entries) >= 2
    newest = os.path.join(str(tmp_path), entries[-1]["file"])
    with open(newest, "wb") as f:
        f.write(b"truncated garbage")       # kill-mid-write simulation
    best = mgr.latest_valid()
    assert best is not None
    assert best["file"] == entries[-2]["file"]
    # resume still works from the fallback
    resumed = _net()
    rep = ResilientTrainer(resumed, str(tmp_path), policy=FAST
                           ).fit(_data(), epochs=1)
    assert rep.resumed_from.endswith(entries[-2]["file"])


def test_manager_pruning_ignores_foreign_files(tmp_path):
    foreign = tmp_path / "exported_model.zip"
    foreign.write_bytes(b"user data, not ours")
    notes = tmp_path / "NOTES.txt"
    notes.write_text("keep me")
    net = _net()
    ResilientTrainer(net, str(tmp_path), save_every_n_iterations=1,
                     keep_last=2, policy=FAST).fit(_data(), epochs=1)
    assert foreign.exists() and notes.exists()
    ckpts = [f for f in os.listdir(str(tmp_path)) if f.startswith("ckpt_")]
    assert len(ckpts) == 2                  # keep_last enforced
    # no temp residue from the atomic writes
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp." in f]


def test_checkpoint_zip_carries_rng_and_counters(tmp_path):
    net = _net()
    trainer = ResilientTrainer(net, str(tmp_path), save_every_n_iterations=3,
                               policy=FAST)
    trainer.fit(_data(), epochs=1)
    entry = trainer.ckpt.latest_valid()
    with zipfile.ZipFile(entry["path"]) as zf:
        names = set(zf.namelist())
        assert {"configuration.json", "coefficients.npz", "state.npz",
                "updaterState.bin", "metadata.json",
                "resilience.json"} <= names
        extra = json.loads(zf.read("resilience.json"))
    assert "rng" in extra and "step_in_epoch" in extra
    assert entry["sha256"]


def test_checkpoint_restores_normalizer(tmp_path):
    from deeplearning4j_tpu.data.normalization import NormalizerStandardize
    norm = NormalizerStandardize().fit(_data())
    src = _data().set_pre_processor(norm)
    net = _net()
    ResilientTrainer(net, str(tmp_path), save_every_n_iterations=100,
                     policy=FAST, normalizer=norm).fit(src, epochs=1)
    t2 = ResilientTrainer(_net(), str(tmp_path), policy=FAST)
    t2.fit(_data(), epochs=1)               # resume restores the normalizer
    assert t2.normalizer is not None
    np.testing.assert_allclose(t2.normalizer.feature_mean,
                               norm.feature_mean)


# --------------------------------------------------- CheckpointListener (sat.)
def test_checkpoint_listener_atomic_and_foreign_tolerant(tmp_path):
    from deeplearning4j_tpu.train import CheckpointListener
    foreign = tmp_path / "precious_export.zip"
    foreign.write_bytes(b"do not delete")
    # a stale checkpoint from a previous run participates in retention
    # (ordering is by the iteration number in the name — monotone across
    # resumes — not by mtime)
    stale = tmp_path / "checkpoint_iter_0.zip"
    stale.write_bytes(b"old run")
    net = _net()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                             keep_last=2)
    net.set_listeners(lst)
    # 10 iterations -> saves at 2,4,6,8: enough for retention to engage
    net.fit(ArrayDataSetIterator(X, Y, batch_size=12), epochs=1)
    names = sorted(os.listdir(str(tmp_path)))
    assert "precious_export.zip" in names       # foreign file untouched
    assert not [n for n in names if ".tmp." in n]   # atomic: no residue
    own = [n for n in names if n.startswith("checkpoint_")]
    assert len(own) == 2                         # stale file pruned away
    assert "checkpoint_iter_0.zip" not in own
    assert own == ["checkpoint_iter_6.zip", "checkpoint_iter_8.zip"]
    restored = load_model(os.path.join(str(tmp_path), own[-1]))
    assert np.isfinite(np.asarray(restored.params_flat())).sum()


# ----------------------------------------------------------- transport (sat.)
def test_transport_connect_deadline_names_peer():
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    tr = SocketTransport(0, 2, base_port=29750, connect_timeout=0.3)
    try:
        t0 = time.monotonic()
        with pytest.raises(ConnectionError) as ei:
            tr.broadcast(0, (np.array([0], np.int32),
                             np.array([0], np.int8), 0.0))
        elapsed = time.monotonic() - t0
        assert elapsed < 3.0                    # bounded, not 30s default
        msg = str(ei.value)
        assert "peer 1" in msg and "127.0.0.1:29751" in msg
        assert "attempts" in msg
    finally:
        tr.close()


def test_transport_close_idempotent_and_concurrent():
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    a = SocketTransport(0, 2, base_port=29760, connect_timeout=5)
    b = SocketTransport(1, 2, base_port=29760, connect_timeout=5)
    msg = (np.array([1, 2], np.int32), np.array([1, -1], np.int8), 0.5)
    a.broadcast(0, msg)
    assert len(b.recv(1, timeout=10)) == 1
    # close concurrently from several threads, twice each — no deadlock,
    # no exception, reader threads unblocked
    threads = [threading.Thread(target=t.close)
               for t in (a, b) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads)
    a.close(), b.close()                        # idempotent
    with pytest.raises(RuntimeError):
        a.broadcast(0, msg)


def test_transport_fault_injected_message_drop():
    from deeplearning4j_tpu.parallel.transport import SocketTransport
    a = SocketTransport(0, 2, base_port=29770, connect_timeout=5)
    b = SocketTransport(1, 2, base_port=29770, connect_timeout=5)
    inj = FaultInjector(drop_send_at=(0,))
    attach_transport_faults(a, inj)
    msg = (np.array([1], np.int32), np.array([1], np.int8), 0.25)
    try:
        a.broadcast(0, msg)                     # dropped
        with pytest.raises(TimeoutError):
            b.recv(1, timeout=0.3)
        a.broadcast(0, msg)                     # delivered
        assert len(b.recv(1, timeout=10)) == 1
        assert inj.sends_dropped == 1
    finally:
        a.close(), b.close()


# ------------------------------------------------------------ faults harness
def test_fault_injector_from_env(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_FAULTS",
                       "nan_at=3,4; transient_every=5; crash_at=11")
    inj = FaultInjector.from_env()
    assert inj.nan_at == {3, 4}
    assert inj.transient_every == 5 and inj.crash_at == 11
    monkeypatch.setenv("DL4J_TPU_FAULTS", "")
    assert FaultInjector.from_env() is None
    monkeypatch.setenv("DL4J_TPU_FAULTS", "bogus_key=1")
    with pytest.raises(ValueError):
        FaultInjector.from_env()


def test_fault_injector_fires_once_per_step():
    inj = FaultInjector(transient_at=(2,))
    with pytest.raises(TransientFaultError):
        inj.before_step(2)
    inj.before_step(2)          # retry of the same step passes
    inj.before_step(3)
