"""CJK tokenizer packs + utility iterators (DL4J
deeplearning4j-nlp-{chinese,japanese,korean} and
deeplearning4j-utility-iterators parity)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    ArrayDataSetIterator, AsyncMultiDataSetIterator, DataSet,
    DataSetIteratorSplitter, EarlyTerminationDataSetIterator,
    IteratorDataSetIterator, MultiDataSet, MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.text import (
    ChineseTokenizerFactory, JapaneseTokenizerFactory,
    KoreanTokenizerFactory, TfidfVectorizer,
)


# ------------------------------------------------------------------- CJK
def test_chinese_tokenizer_lexicon_longest_match():
    tf = ChineseTokenizerFactory(lexicon=["北京", "大学", "北京大学"])
    toks = tf.tokenize("我在北京大学学习 machine learning 2024")
    assert "北京大学" in toks           # longest match wins over 北京+大学
    assert "machine" in toks and "learning" in toks
    assert "2024" in toks
    assert "我" in toks                 # OOV han chars fall back to unigrams


def test_chinese_tokenizer_bigrams_without_lexicon():
    tf = ChineseTokenizerFactory()
    toks = tf.tokenize("中文分词")
    assert {"中", "文", "分", "词"}.issubset(toks)
    assert "中文" in toks and "分词" in toks     # bigram emission


def test_japanese_tokenizer_script_boundaries():
    tf = JapaneseTokenizerFactory()
    toks = tf.tokenize("私はカタカナとKanjiをtokenizeします")
    assert "カタカナ" in toks           # katakana run kept whole
    assert "tokenize" in toks
    assert "は" in toks or "私" in toks


def test_korean_tokenizer_strips_particles():
    tf = KoreanTokenizerFactory()
    # 고양이(cat)+가(subject particle), 집(house)+에서(locative)
    toks = tf.tokenize("고양이가 집에서 잔다")
    assert "고양이" in toks
    assert "집" in toks
    # single-syllable particles are ambiguous: both forms are kept, so a
    # BARE noun ending in a particle syllable still shares a token with
    # its inflected form (고양이 vs 고양이가 both emit 고양이)
    assert "고양이가" in toks
    bare = tf.tokenize("고양이")
    assert "고양이" in bare
    tf2 = KoreanTokenizerFactory(strip_particles=False)
    toks2 = tf2.tokenize("고양이가 집에서 잔다")
    assert "고양이가" in toks2 and "고양이" not in toks2


def test_cjk_feeds_vectorizer_pipeline():
    """The factory contract matches the vectorizers (the nlp-chinese
    module's purpose: tokenization feeding the same pipelines)."""
    docs = [("北京 大学 研究", "edu"), ("上海 市场 金融", "fin"),
            ("大学 教育 研究", "edu"), ("金融 市场 投资", "fin")]
    tv = TfidfVectorizer(docs, tokenizer_factory=ChineseTokenizerFactory(
        lexicon=["北京", "大学", "研究", "上海", "市场", "金融", "教育",
                 "投资"]))
    tv.fit()
    assert "金融" in tv.vocab and "大学" in tv.vocab
    ds = tv.vectorize()
    assert ds.features.shape[0] == 4


# -------------------------------------------------------- utility iterators
def _source(n=10, bs=4):
    rs = np.random.RandomState(0)
    X = rs.rand(n * bs, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[rs.randint(0, 2, n * bs)]
    return ArrayDataSetIterator(X, Y, batch_size=bs)


def test_early_termination_iterator():
    it = EarlyTerminationDataSetIterator(_source(n=10), max_batches=3)
    assert len(list(it)) == 3
    it.reset()
    assert len(list(it)) == 3
    with pytest.raises(ValueError):
        EarlyTerminationDataSetIterator(_source(), 0)


def test_multiple_epochs_iterator():
    it = MultipleEpochsIterator(_source(n=4), n_epochs=3)
    assert len(list(it)) == 12


def test_splitter_partitions_batches():
    sp = DataSetIteratorSplitter(_source(n=10), total_batches=10, ratio=0.7)
    train = list(sp.train_iterator)
    test = list(sp.test_iterator)
    assert len(train) == 7 and len(test) == 3
    # the partitions are disjoint: first train batch != first test batch
    assert not np.allclose(np.asarray(train[0].features),
                           np.asarray(test[0].features))


def test_sampling_iterator_shapes_and_reseed():
    ds = DataSet(np.arange(20, dtype="float32").reshape(10, 2),
                 np.eye(2, dtype="float32")[np.arange(10) % 2])
    it = SamplingDataSetIterator(ds, batch_size=4, total_batches=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].features.shape == (4, 2)
    batches2 = list(it)          # different epoch -> different draw
    assert not all(np.array_equal(a.features, b.features)
                   for a, b in zip(batches, batches2))


def test_iterator_dataset_iterator_wraps_iterable():
    items = [DataSet(np.zeros((2, 3), "float32"),
                     np.zeros((2, 2), "float32")) for _ in range(4)]
    it = IteratorDataSetIterator(items)
    assert len(list(it)) == 4
    assert len(list(it)) == 4    # re-iterable


def test_async_multi_iterator_prefetches_and_propagates_errors():
    mds = [MultiDataSet((np.zeros((2, 3), "float32"),),
                        (np.zeros((2, 2), "float32"),)) for _ in range(6)]
    it = AsyncMultiDataSetIterator(mds, queue_size=2)
    assert len(list(it)) == 6

    def boom():
        yield mds[0]
        raise RuntimeError("source failed")

    with pytest.raises(RuntimeError, match="source failed"):
        list(AsyncMultiDataSetIterator(boom()))


def test_utility_iterators_compose_with_fit():
    """Early-termination wrapping feeds net.fit like any iterator."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Sgd
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Sgd(1e-1))
            .list().layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(3)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(EarlyTerminationDataSetIterator(_source(n=8), 2), epochs=2)
    assert net.iteration_count == 4


def test_splitter_and_async_robust_to_early_break():
    """Early break must not corrupt the sibling split view or leak the
    async worker thread (round-3 review findings)."""
    import threading
    sp = DataSetIteratorSplitter(_source(n=10), total_batches=10, ratio=0.7)
    for ds in sp.train_iterator:
        break                                  # abandon mid-epoch
    test = list(sp.test_iterator)
    assert len(test) == 3                      # partition still correct
    before = threading.active_count()
    mds = [MultiDataSet((np.zeros((2, 3), "float32"),),
                        (np.zeros((2, 2), "float32"),)) for _ in range(50)]
    it = AsyncMultiDataSetIterator(mds, queue_size=2)
    for item in it:
        break                                  # abandon: generator closed
    import time
    time.sleep(0.5)
    assert threading.active_count() <= before + 1
