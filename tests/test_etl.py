"""Line-rate data plane: shard format round-trips, exact-position seek,
multi-process shared-memory ring parity, the hot-image-path delegation,
and the prefetch-depth env contract (data/shards.py, data/pipeline.py,
data/async_iterator.py)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.data.async_iterator import (
    AsyncDataSetIterator, prefetch_depth, prefetch_iterable,
)
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.data.pipeline import (
    ImageFileBatchLoader, MultiProcessDataSetIterator, ShardBatchLoader,
    etl_workers,
)
from deeplearning4j_tpu.data.records import (
    ImageRecordReader, RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.data.shards import (
    ShardDataSetIterator, ShardWriter, read_footer, write_shards,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _image_data(n=90, h=8, w=8, c=1, classes=5, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randint(0, 256, (n, h, w, c), dtype=np.uint8)
    Y = np.eye(classes, dtype="float32")[rs.randint(0, classes, n)]
    return X, Y


def _write(tmp_path, X, Y, shard_records=32, batch=30):
    d = str(tmp_path / "shards")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=batch,
                                      drop_last=False),
                 d, shard_records=shard_records)
    return d


# ------------------------------------------------------------- shard format
def test_shard_roundtrip_bitwise(tmp_path):
    X, Y = _image_data()
    d = _write(tmp_path, X, Y)
    it = ShardDataSetIterator(d, batch_size=30)
    got = list(it)
    assert len(got) == 3
    for i, ds in enumerate(got):
        np.testing.assert_array_equal(ds.features, X[i * 30:(i + 1) * 30])
        assert ds.features.dtype == np.uint8     # raw over the wire
        np.testing.assert_array_equal(ds.labels, Y[i * 30:(i + 1) * 30])
        assert ds.labels.dtype == np.float32


def test_shard_footer_and_compact_labels(tmp_path):
    X, Y = _image_data(n=70)
    d = _write(tmp_path, X, Y, shard_records=32)
    with open(os.path.join(d, "index.json")) as f:
        index = json.load(f)
    # exact one-hot labels stored as int32 ids + num_classes
    assert index["num_classes"] == 5
    assert np.dtype(index["labels"]["dtype"]) == np.int32
    assert index["n_records"] == 70
    assert [s["records"] for s in index["shards"]] == [32, 32, 6]
    footer = read_footer(os.path.join(d, index["shards"][0]["file"]))
    assert footer["records"] == 32
    assert tuple(footer["features"]["shape"]) == (8, 8, 1)


def test_shard_crosses_boundaries_and_ragged_tail(tmp_path):
    X, Y = _image_data(n=100)
    d = _write(tmp_path, X, Y, shard_records=32)
    it = ShardDataSetIterator(d, batch_size=48, drop_last=False)
    got = list(it)
    assert [b.features.shape[0] for b in got] == [48, 48, 4]
    np.testing.assert_array_equal(got[1].features, X[48:96])    # 2 shards
    np.testing.assert_array_equal(got[2].features, X[96:])


def test_shard_noncompact_labels_verbatim(tmp_path):
    rs = np.random.RandomState(3)
    X = rs.randn(40, 6).astype("float32")
    Y = rs.randn(40, 2).astype("float32")        # regression targets
    d = str(tmp_path / "s")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=20), d)
    got = list(ShardDataSetIterator(d, batch_size=20))
    np.testing.assert_array_equal(got[0].features, X[:20])
    np.testing.assert_array_equal(got[0].labels, Y[:20])


def test_shard_reiterate_replays_like_other_iterators(tmp_path):
    # an exhausted iterator replays on the next __iter__ (advancing the
    # epoch's shuffle order) — same contract as ArrayDataSetIterator —
    # while seek() pins the very next pass to the current epoch's
    # remainder, even when that remainder is empty (exact-end resume)
    X, Y = _image_data(n=100)
    d = _write(tmp_path, X, Y)
    it = ShardDataSetIterator(d, batch_size=25, shuffle=True, seed=3)
    e0 = [np.array(b.features) for b in it]
    e1 = [np.array(b.features) for b in it]
    assert len(e0) == len(e1) == 4
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    np.testing.assert_array_equal(np.sort(np.concatenate(e0), axis=0),
                                  np.sort(np.concatenate(e1), axis=0))
    it2 = ShardDataSetIterator(d, batch_size=25)
    it2.seek(it2.n_batches)
    assert list(it2) == []              # resumed-at-end: nothing left
    assert len(list(it2)) == 4          # ...then the next epoch replays


def test_write_shards_mixed_label_kinds(tmp_path):
    X, Y = _image_data(n=20)
    soft = np.full((10, 5), 0.2, dtype=np.float32)
    # one-hot first, soft later: schema is locked to int32 ids by batch
    # 0, so the writer must fail loudly (not with a schema mismatch)
    with pytest.raises(ValueError, match="compact_labels=False"):
        write_shards(iter([DataSet(X[:10], Y[:10]),
                           DataSet(X[10:], soft)]),
                     str(tmp_path / "mixed"))
    # soft first: compaction locks OFF and everything stores verbatim
    d = str(tmp_path / "soft_first")
    write_shards(iter([DataSet(X[:10], soft), DataSet(X[10:], Y[10:])]), d)
    got = list(ShardDataSetIterator(d, batch_size=10, drop_last=False))
    np.testing.assert_array_equal(got[0].labels, soft)
    np.testing.assert_array_equal(got[1].labels, Y[10:])


def test_empty_shard_set_stream_state_sentinel(tmp_path):
    d = str(tmp_path / "empty")
    with ShardWriter(d):
        pass
    it = ShardDataSetIterator(d, batch_size=8)
    state = it.stream_state()           # must not IndexError
    assert state["shard_file"] is None
    assert state["record_offset"] == 0
    assert list(it) == []


def test_shard_writer_schema_mismatch(tmp_path):
    w = ShardWriter(str(tmp_path / "s"))
    w.add(np.zeros((4, 4), np.uint8), np.int32(1))
    with pytest.raises(ValueError, match="schema mismatch"):
        w.add(np.zeros((5, 4), np.uint8), np.int32(0))
    with pytest.raises(ValueError, match="cannot mix"):
        w.add(np.zeros((4, 4), np.uint8))


def test_shard_writer_crash_leaves_no_index(tmp_path):
    # a conversion that raises mid-stream must NOT finalize a readable
    # (truncated) dataset — the index is only written on clean close
    d = str(tmp_path / "s")
    with pytest.raises(RuntimeError, match="boom"):
        with ShardWriter(d, shard_records=4) as w:
            for i in range(6):      # one full shard flushed, one partial
                w.add(np.full((2, 2), i, np.uint8), np.int32(0))
            raise RuntimeError("boom")
    assert not os.path.exists(os.path.join(d, "index.json"))
    with pytest.raises(FileNotFoundError):
        ShardDataSetIterator(d, batch_size=2)


def test_shard_writer_closed_and_aborted_guards(tmp_path):
    w = ShardWriter(str(tmp_path / "s"), shard_records=4)
    w.add(np.zeros((2, 2), np.uint8), np.int32(0))
    idx = w.close()
    assert w.close() == idx         # idempotent: the index on disk
    with pytest.raises(RuntimeError, match="closed"):
        w.add(np.zeros((2, 2), np.uint8), np.int32(0))
    with pytest.raises(RuntimeError, match="closed"):
        w.add_batch(np.zeros((1, 2, 2), np.uint8),
                    np.zeros((1,), np.int32))
    assert idx["n_records"] == 1    # the rejected records never count
    # aborted writer (__exit__ on exception): a later defensive close()
    # must not return a success-looking index for an index-less dataset
    w2 = ShardWriter(str(tmp_path / "s2"), shard_records=4)
    with pytest.raises(RuntimeError, match="boom"):
        with w2:
            w2.add(np.zeros((2, 2), np.uint8), np.int32(0))
            raise RuntimeError("boom")
    with pytest.raises(RuntimeError, match="aborted"):
        w2.close()


def test_shard_seek_tell_stream_state(tmp_path):
    X, Y = _image_data(n=120)
    d = _write(tmp_path, X, Y)
    it = ShardDataSetIterator(d, batch_size=30, shuffle=True, seed=7)
    it.reset()      # epoch 1's shuffle
    full = [np.array(b.features) for b in it]
    it2 = ShardDataSetIterator(d, batch_size=30, shuffle=True, seed=7)
    it2.reset()
    it2.seek(2)
    assert it2.tell() == 2
    state = it2.stream_state()
    assert state["next_batch"] == 2
    assert state["record_offset"] % 30 == 0
    assert state["shard_file"].endswith(".shard")
    tail = [np.array(b.features) for b in it2]
    assert len(tail) == 2
    for a, b in zip(full[2:], tail):
        np.testing.assert_array_equal(a, b)
    # the seeked run read ONLY the tail — no prefix replay
    assert it2.batches_read == 2


# ------------------------------------------------------ multi-process ring
def test_pipeline_bitwise_parity_and_order(tmp_path):
    X, Y = _image_data(n=300, seed=2)
    d = _write(tmp_path, X, Y, shard_records=64)
    ref = list(ShardDataSetIterator(d, batch_size=32, shuffle=True, seed=9))
    with MultiProcessDataSetIterator(
            ShardBatchLoader(d, 32, shuffle=True, seed=9),
            num_workers=2) as pipe:
        got = [(np.array(b.features, copy=True),
                np.array(b.labels, copy=True)) for b in pipe]
        assert len(got) == len(ref)
        for (f, l), r in zip(got, ref):
            np.testing.assert_array_equal(f, r.features)
            np.testing.assert_array_equal(l, r.labels)
        # replay-on-exhaustion: re-iterating without reset() serves the
        # NEXT epoch's order, matching ShardDataSetIterator semantics
        it = ShardDataSetIterator(d, batch_size=32, shuffle=True, seed=9)
        list(it)                        # epoch 0
        ref2 = list(it)                 # re-__iter__ auto-advances: epoch 1
        seen = []
        for i, b in enumerate(pipe):    # pipe auto-advances too: epoch 1
            seen.append(np.array(b.features, copy=True))
            if i == 1:
                break
        for f, r in zip(seen, ref2):
            np.testing.assert_array_equal(f, r.features)
        # abandoned epoch (early break) must not corrupt the next one
        pipe.reset()                    # abandoned epoch 1 -> epoch 2
        it.reset()                      # epoch 2
        ref3 = list(it)
        got3 = [np.array(b.features, copy=True) for b in pipe]
        assert len(got3) == len(ref3)
        for f, r in zip(got3, ref3):
            np.testing.assert_array_equal(f, r.features)
    # per-worker ETL series exported with worker labels
    from deeplearning4j_tpu import monitor
    fam = monitor.REGISTRY.collect("etl_worker_batches_total")
    assert fam is not None and fam.label_names == ("worker",)


def test_pipeline_worker_error_surfaces(tmp_path):
    X, Y = _image_data(n=64)
    d = _write(tmp_path, X, Y, shard_records=32)
    loader = ShardBatchLoader(d, 32)
    loader.shard_dir = str(tmp_path / "missing")    # workers will fail
    with MultiProcessDataSetIterator(loader, num_workers=1) as pipe:
        with pytest.raises(RuntimeError, match="ETL worker"):
            list(pipe)


def test_pipeline_closed_raises_on_reuse(tmp_path):
    # iterating a closed-but-previously-started pipeline must fail with
    # the intended guard, not an obscure mp.Queue error or a stall
    X, Y = _image_data(n=64)
    d = _write(tmp_path, X, Y, shard_records=32)
    pipe = MultiProcessDataSetIterator(ShardBatchLoader(d, 32),
                                       num_workers=1)
    with pipe:
        next(iter(pipe))            # started, partially consumed
    with pytest.raises(RuntimeError, match="pipeline is closed"):
        next(iter(pipe))


def test_fit_consumes_pipeline(tmp_path):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    rs = np.random.RandomState(1)
    X = rs.randn(200, 6).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 200)]
    d = str(tmp_path / "s")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=50), d)
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    with MultiProcessDataSetIterator(ShardBatchLoader(d, 50),
                                     num_workers=2) as pipe:
        net = MultiLayerNetwork(conf).init()
        net.fit(pipe, epochs=2)     # default wrap consumes the ring
        assert np.isfinite(net.score())
        assert net.iteration_count == 8


def test_scan_fit_over_ring_matches_inprocess(tmp_path):
    """The stacking (scan) fit holds K live batches before one transfer;
    ring batches are slot views recycled on the next pull — fit() must
    flip the ring into copy mode (mark_copy_for_stacking) or the stacked
    chunk trains on corrupted data. Proven by parameter parity with the
    in-process iterator."""
    from deeplearning4j_tpu.data.shards import ShardDataSetIterator
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    rs = np.random.RandomState(4)
    X = rs.randn(240, 5).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 240)]
    d = str(tmp_path / "s")
    write_shards(ArrayDataSetIterator(X, Y, batch_size=40), d)

    def _conf():
        return (NeuralNetConfiguration.Builder().seed(2)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())

    ref = MultiLayerNetwork(_conf()).init()
    ref.fit(ShardDataSetIterator(d, batch_size=40), epochs=1,
            scan_steps=3)
    # copy=False: the expert view-batch mode — exactly the mode the
    # stacking fit must flip to copy for the fit's duration
    with MultiProcessDataSetIterator(ShardBatchLoader(d, 40),
                                     num_workers=2, copy=False) as pipe:
        assert pipe.view_batches
        net = MultiLayerNetwork(_conf()).init()
        net.fit(pipe, epochs=1, scan_steps=3)
        assert pipe._copy is False      # restored after the fit
    np.testing.assert_array_equal(np.asarray(ref.params_flat()),
                                  np.asarray(net.params_flat()))


def test_pipeline_worker_kill_switch_sync_mode(tmp_path, monkeypatch):
    """DL4J_TPU_ETL_WORKERS=0 (and the auto rule resolving to 0) on a
    num_workers=None pipeline runs the loader in-process — no worker
    processes, identical stream. This is the escape hatch the dead-pool
    error message points at, so it must actually disable the pool."""
    X, Y = _image_data(n=100)
    d = _write(tmp_path, X, Y)
    ref = [(np.array(b.features), np.array(b.labels))
           for b in ShardDataSetIterator(d, batch_size=16, shuffle=True,
                                         seed=5, drop_last=False)]
    monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", "0")
    with MultiProcessDataSetIterator(
            ShardBatchLoader(d, 16, shuffle=True, seed=5,
                             drop_last=False)) as pipe:
        assert pipe._workers_n == 0 and not pipe._procs
        got = [(np.array(b.features), np.array(b.labels)) for b in pipe]
        assert len(got) == len(ref)
        for (f, l), (rf, rl) in zip(got, ref):
            np.testing.assert_array_equal(f, rf)
            np.testing.assert_array_equal(l, rl)
        pipe.reset()
        assert len(list(pipe)) == len(ref)      # epoch replay still works
    monkeypatch.delenv("DL4J_TPU_ETL_WORKERS")
    with MultiProcessDataSetIterator(ShardBatchLoader(d, 16)) as p2:
        assert p2._workers_n == 0               # auto: below the floor
        assert len(list(p2)) == 100 // 16


def test_pipeline_position_parity_sync_vs_workers(tmp_path):
    """The =0 kill switch must deliver the IDENTICAL stream to worker
    mode, position semantics included: a partially-consumed epoch
    resumes at its position on re-__iter__ (never re-serving delivered
    batches), and a fully-consumed one advances to the next epoch's
    shuffle order. Sync mode once restarted at record 0 mid-epoch and
    replayed the same order forever — this pins the fix."""
    X, Y = _image_data(n=200, seed=6)
    d = _write(tmp_path, X, Y, shard_records=64)
    streams = {}
    for w in (0, 2):
        with MultiProcessDataSetIterator(
                ShardBatchLoader(d, 20, shuffle=True, seed=5),
                num_workers=w) as pipe:
            seq = []
            it = iter(pipe)
            for _ in range(3):              # partial pass, then abandon
                seq.append(np.array(next(it).features, copy=True))
            del it
            assert pipe.tell() == 3
            seq += [np.array(b.features, copy=True) for b in pipe]
            assert pipe.tell() == pipe.n_batches
            # full re-__iter__ without reset(): next epoch's order
            seq += [np.array(b.features, copy=True) for b in pipe]
            streams[w] = seq
    assert len(streams[0]) == 2 * (200 // 20)
    for a, b in zip(streams[0], streams[2]):
        np.testing.assert_array_equal(a, b)
    half = len(streams[0]) // 2
    e0 = np.sort(np.concatenate(streams[0][:half]), axis=None)
    np.testing.assert_array_equal(e0, np.sort(X, axis=None))  # full epoch
    assert not all(np.array_equal(a, b) for a, b in
                   zip(streams[0][:half], streams[0][half:]))  # reshuffled


def test_pipeline_seek_tell_stream_state(tmp_path):
    """ShardDataSetIterator's seek surface on the ring (both modes):
    supports_seek routes ResilientTrainer onto seek-instead-of-replay —
    without it the fast-forward discarded step_in_epoch batches that a
    position-resuming iterator had already skipped past (silent data
    loss on same-process re-fit after preemption)."""
    X, Y = _image_data(n=120, seed=8)
    d = _write(tmp_path, X, Y)
    for w in (0, 2):
        with MultiProcessDataSetIterator(
                ShardBatchLoader(d, 30, shuffle=True, seed=3),
                num_workers=w) as pipe:
            assert pipe.supports_seek
            ref = [np.array(b.features, copy=True) for b in pipe]
            assert pipe.stream_state() == {"epoch": 0, "next_batch": 4}
            # exact-end pin: resume landing on the epoch end stays empty
            pipe.seek(pipe.n_batches)
            assert list(pipe) == []
            assert pipe._epoch == 0         # pinned, not auto-advanced
            # seek back mid-epoch: serves exactly the remainder
            pipe.seek(2)
            tail = [np.array(b.features, copy=True) for b in pipe]
            assert len(tail) == 2
            for a, b in zip(ref[2:], tail):
                np.testing.assert_array_equal(a, b)
            with pytest.raises(IndexError):
                pipe.seek(pipe.n_batches + 1)


# ------------------------------------------------------- hot image path
def _png_tree(tmp_path, n_per_class=30, classes=2, hw=10):
    from PIL import Image
    rs = np.random.RandomState(0)
    root = tmp_path / "imgs"
    for ci in range(classes):
        d = root / f"class{ci}"
        d.mkdir(parents=True)
        for i in range(n_per_class):
            arr = rs.randint(0, 256, (hw, hw), dtype=np.uint8)
            Image.fromarray(arr, mode="L").save(d / f"{i:03d}.png")
    return str(root)


def test_image_pipeline_delegation_parity(tmp_path, monkeypatch):
    root = _png_tree(tmp_path)

    def batches(workers):
        monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", workers)
        rr = ImageRecordReader(10, 10, 1).initialize(root)
        it = RecordReaderDataSetIterator(rr, batch_size=16, label_index=-1,
                                         num_classes=rr.num_labels())
        try:
            return [(np.array(b.features, copy=True),
                     np.array(b.labels, copy=True)) for b in it]
        finally:
            if it._mp_pipe is not None:
                it._mp_pipe.close()

    inproc = batches("0")
    piped = batches("2")
    assert len(piped) == len(inproc) == 4   # 60 imgs / b16, ragged tail
    for (f1, l1), (f2, l2) in zip(inproc, piped):
        np.testing.assert_array_equal(f1, f2)
        np.testing.assert_array_equal(l1, l2)
    assert inproc[0][0].dtype == np.uint8


def test_image_delegation_reiter_restarts_epoch(tmp_path, monkeypatch):
    """An abandoned pass over RecordReaderDataSetIterator restarts at the
    first file on re-__iter__ — the in-process decode loop always did;
    the delegated ring once resumed at its saved position instead,
    silently dropping the already-served prefix from the epoch."""
    root = _png_tree(tmp_path)

    def first_twice(workers):
        monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", workers)
        rr = ImageRecordReader(10, 10, 1).initialize(root)
        it = RecordReaderDataSetIterator(rr, batch_size=16, label_index=-1,
                                         num_classes=rr.num_labels())
        try:
            a = np.array(next(iter(it)).features, copy=True)
            # no reset() between the abandoned pass and the next one
            b = np.array(next(iter(it)).features, copy=True)
            return a, b
        finally:
            if it._mp_pipe:
                it._mp_pipe.close()

    a0, b0 = first_twice("0")
    a2, b2 = first_twice("2")
    np.testing.assert_array_equal(a0, b0)
    np.testing.assert_array_equal(a2, b2)   # delegated path restarts too
    np.testing.assert_array_equal(a0, a2)


def test_scan_fit_over_image_delegation_parity(tmp_path, monkeypatch):
    """Stacking (scan) fit over the AUTO-delegated image ring: the ring
    yields owned copies (copy=True), so the stacked chunk must train on
    intact pixels — parity with the in-process path proves it."""
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    root = _png_tree(tmp_path, n_per_class=32)      # 64 imgs, b16 = 4

    def _conf():
        return (NeuralNetConfiguration.Builder().seed(3)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(10, 10, 1))
                .build())

    def _fit(workers):
        monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", workers)
        rr = ImageRecordReader(10, 10, 1).initialize(root)
        it = RecordReaderDataSetIterator(rr, batch_size=16, label_index=-1,
                                         num_classes=2)
        net = MultiLayerNetwork(_conf()).init()
        try:
            net.fit(it, epochs=1, scan_steps=2)
        finally:
            if it._mp_pipe:
                it._mp_pipe.close()
        return np.asarray(net.params_flat())

    np.testing.assert_array_equal(_fit("0"), _fit("2"))


def test_image_prealloc_matches_stack(tmp_path):
    # the preallocated fill must equal the old np.stack construction
    root = _png_tree(tmp_path, n_per_class=8)
    os.environ["DL4J_TPU_ETL_WORKERS"] = "0"
    try:
        rr = ImageRecordReader(10, 10, 1).initialize(root)
        it = RecordReaderDataSetIterator(rr, batch_size=5, label_index=-1,
                                         num_classes=2)
        got = list(it)
        imgs = [img for img, _ in rr.records()]
        np.testing.assert_array_equal(got[0].features, np.stack(imgs[:5]))
        assert got[-1].features.shape[0] == 1   # 16 % 5 ragged tail kept
    finally:
        del os.environ["DL4J_TPU_ETL_WORKERS"]


def test_etl_workers_auto_rule(monkeypatch):
    monkeypatch.delenv("DL4J_TPU_ETL_WORKERS", raising=False)
    assert etl_workers(100) == 0            # below the auto floor
    assert etl_workers(10_000) >= 1
    monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", "")
    assert etl_workers(10_000) >= 1         # "" = unset, same as
    monkeypatch.setenv("DL4J_TPU_ETL_MIN_RECORDS", "")  # PREFETCH_DEPTH
    assert etl_workers(100) == 0
    monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", "0")
    assert etl_workers(10_000) == 0         # kill switch
    monkeypatch.setenv("DL4J_TPU_ETL_WORKERS", "3")
    assert etl_workers(None) == 3


# ------------------------------------------------------ prefetch depth env
def test_prefetch_depth_env(monkeypatch):
    monkeypatch.delenv("DL4J_TPU_PREFETCH_DEPTH", raising=False)
    assert prefetch_depth() == 2            # double-buffered default
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "5")
    assert prefetch_depth() == 5
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "0")
    assert prefetch_depth() == 0


def test_prefetch_depth_zero_sync_but_staged(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "0")
    items = [1, 2, 3]
    out = list(prefetch_iterable(iter(items), transform=lambda x: x * 10))
    assert out == [10, 20, 30]
    # the async wrap still stages (device placement) synchronously
    X = np.random.RandomState(0).randn(8, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[np.zeros(8, int)]
    wrapped = AsyncDataSetIterator(
        ArrayDataSetIterator(X, Y, batch_size=4))
    assert wrapped._queue_size == 0
    got = list(wrapped)
    assert len(got) == 2
    import jax
    assert isinstance(got[0].features, jax.Array)


def test_async_default_queue_from_env(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "7")
    it = AsyncDataSetIterator(ArrayDataSetIterator(
        np.zeros((4, 2), "float32"), np.zeros((4, 2), "float32"),
        batch_size=2))
    assert it._queue_size == 7


def test_fit_prefetch_kill_switch_contract(monkeypatch):
    """DL4J_TPU_FIT_PREFETCH follows the one =='0'-disables contract:
    unset, empty, and any other value leave the default fit() wrap ON.
    The gates once disabled on anything != '1', so exporting '' (the
    'treat as unset' convention of every other data-plane knob) silently
    serialized host ETL with device compute."""
    from deeplearning4j_tpu.data.async_iterator import fit_prefetch_enabled
    monkeypatch.delenv("DL4J_TPU_FIT_PREFETCH", raising=False)
    assert fit_prefetch_enabled()
    for v in ("", "1", "true", "2"):
        monkeypatch.setenv("DL4J_TPU_FIT_PREFETCH", v)
        assert fit_prefetch_enabled(), v
    monkeypatch.setenv("DL4J_TPU_FIT_PREFETCH", "0")
    assert not fit_prefetch_enabled()


# ---------------------------------------------------------------- CI smoke
@pytest.mark.slow
def test_etl_smoke_tool(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "etl_smoke.py")],
        cwd=_REPO, capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    summary = json.loads(r.stdout.splitlines()[-1])
    assert summary["ok"]
    assert summary["parity_batches"] > 0
    assert summary["etl_fetch_wait_exported"]
