"""Memory reports (DL4J nn/conf/memory/LayerMemoryReport.java:22 parity,
exceeded with exact XLA compiled-step numbers)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


def _lenet(updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(0).updater(updater or Adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=20, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=120, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())


def test_memory_report_analytic_structure():
    net = MultiLayerNetwork(_lenet()).init()
    rep = net.memory_report(batch_size=16, with_compiled=False)
    assert len(rep.layers) == 6
    # conv1: 20 params of 5*5*1 + bias = 520 floats
    conv1 = rep.layers[0]
    assert conv1.params_bytes == 520 * 4
    # Adam: 2 state arrays per param leaf
    assert conv1.updater_state_bytes == 2 * conv1.params_bytes
    # conv1 output 24x24x20 per sample
    assert conv1.activation_bytes == 16 * 24 * 24 * 20 * 4
    # params total matches the network
    assert rep.total_params_bytes == net.num_params() * 4
    assert "analytic train total" in rep.summary()


def test_memory_report_sgd_has_no_updater_state():
    net = MultiLayerNetwork(_lenet(updater=Sgd(0.1))).init()
    rep = net.memory_report(batch_size=8, with_compiled=False)
    assert rep.total_updater_bytes == 0


def test_memory_report_compiled_within_2x_of_analytic():
    """The analytic estimate must be within 2x of XLA's own accounting for
    the compiled training step (the review contract from round-2 VERDICT
    item 7)."""
    net = MultiLayerNetwork(_lenet()).init()
    rep = net.memory_report(batch_size=16)
    if rep.compiled is None:
        pytest.skip("backend exposes no memory_analysis")
    truth = rep.compiled_total_bytes
    est = rep.total_train_bytes
    assert truth > 0
    ratio = est / truth
    assert 0.5 <= ratio <= 2.0, (est, truth, ratio)


def test_memory_report_graph():
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    net = ComputationGraph(ResNet50(num_classes=10,
                                    input_shape=(32, 32, 3)).conf()).init()
    rep = net.memory_report(batch_size=4, with_compiled=False)
    assert rep.total_params_bytes == net.num_params() * 4
    names = [r.name for r in rep.layers]
    assert "stem_conv" in names and "output" in names
    assert rep.total_train_bytes > rep.total_inference_bytes


def test_memory_analysis_backend_fallback_is_counted_not_silent(
        monkeypatch, caplog):
    """A backend without memory_analysis degrades to compiled=None — the
    documented not-a-lowering-bug path: the analytic report still lands,
    a warning names the capability gap, and
    xla_analysis_unavailable_total{kind="memory"} increments so the
    degradation is visible on /metrics instead of silent."""
    import logging

    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.util import memory as memory_mod

    monitor.REGISTRY.reset()

    def _no_support(compiled):
        raise RuntimeError("memory_analysis unimplemented on this backend")

    monkeypatch.setattr(memory_mod, "_read_memory_analysis", _no_support)
    net = MultiLayerNetwork(_lenet()).init()
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        rep = net.memory_report(batch_size=8, with_compiled=True)
    assert rep.compiled is None                 # degraded, not crashed
    assert rep.compiled_total_bytes is None
    assert rep.total_train_bytes > 0            # analytic half intact
    assert any("memory analysis unavailable" in r.message
               for r in caplog.records)
    ctr = monitor.REGISTRY.collect("xla_analysis_unavailable_total")
    assert ctr is not None and ctr.value(kind="memory") == 1
    monitor.REGISTRY.reset()


def test_memory_analysis_none_result_also_counted(monkeypatch):
    """Some backends return None instead of raising — same counted
    fallback."""
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.util import memory as memory_mod

    monitor.REGISTRY.reset()
    monkeypatch.setattr(memory_mod, "_read_memory_analysis",
                        lambda compiled: None)
    net = MultiLayerNetwork(_lenet()).init()
    rep = net.memory_report(batch_size=8, with_compiled=True)
    assert rep.compiled is None
    ctr = monitor.REGISTRY.collect("xla_analysis_unavailable_total")
    assert ctr is not None and ctr.value(kind="memory") == 1
    monitor.REGISTRY.reset()
