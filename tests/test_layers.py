"""Per-layer behavior tests: shape inference matches actual forward shapes,
basic semantics (masking, pooling values, BN statistics)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.base import InputType, Kind, layer_from_dict, layer_to_dict
from deeplearning4j_tpu.nn.layers import (
    LSTM, ActivationLayer, AutoEncoder, BatchNormalization, Bidirectional,
    Convolution1DLayer, ConvolutionLayer, Cropping2D, Deconvolution2D,
    DenseLayer, DepthwiseConvolution2D, DropoutLayer, EmbeddingLayer,
    GlobalPoolingLayer, GravesLSTM, LastTimeStep, LocalResponseNormalization,
    LossLayer, OutputLayer, RnnOutputLayer, SeparableConvolution2D,
    SimpleRnn, SpaceToDepthLayer, SubsamplingLayer, Upsampling2D,
    VariationalAutoencoder, ZeroPaddingLayer,
)

KEY = jax.random.PRNGKey(0)


def run_layer(layer, input_type, batch=2, train=False, rng=None, mask=None,
              x=None):
    params, state = layer.init(KEY, input_type)
    if x is None:
        x = jax.random.normal(jax.random.PRNGKey(1), (batch,) + input_type.shape)
    y, new_state = layer.apply(params, state, x, train=train, rng=rng, mask=mask)
    return y, params, new_state


FF_CASES = [
    (DenseLayer(n_out=16, activation="relu"), InputType.feed_forward(8)),
    (OutputLayer(n_out=5), InputType.feed_forward(8)),
    (ActivationLayer(activation="tanh"), InputType.feed_forward(8)),
    (AutoEncoder(n_out=4), InputType.feed_forward(8)),
    (VariationalAutoencoder(n_out=3, encoder_layer_sizes=(8,),
                            decoder_layer_sizes=(8,)), InputType.feed_forward(6)),
]

CNN_CASES = [
    (ConvolutionLayer(n_out=4, kernel=(3, 3), convolution_mode="same"),
     InputType.convolutional(8, 8, 2)),
    (ConvolutionLayer(n_out=4, kernel=(3, 3), stride=(2, 2),
                      convolution_mode="truncate"),
     InputType.convolutional(9, 9, 2)),
    (ConvolutionLayer(n_out=4, kernel=(3, 3), dilation=(2, 2),
                      convolution_mode="same"), InputType.convolutional(8, 8, 2)),
    (Deconvolution2D(n_out=3, kernel=(2, 2), stride=(2, 2),
                     convolution_mode="same"), InputType.convolutional(4, 4, 2)),
    (SeparableConvolution2D(n_out=6, kernel=(3, 3), convolution_mode="same"),
     InputType.convolutional(8, 8, 4)),
    (DepthwiseConvolution2D(depth_multiplier=2, kernel=(3, 3),
                            convolution_mode="same"),
     InputType.convolutional(8, 8, 3)),
    (SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
     InputType.convolutional(8, 8, 3)),
    (SubsamplingLayer(kernel=(2, 2), stride=(2, 2), pooling_type="avg"),
     InputType.convolutional(8, 8, 3)),
    (Upsampling2D(size=(2, 2)), InputType.convolutional(4, 4, 3)),
    (ZeroPaddingLayer(padding=(1, 2, 3, 4)), InputType.convolutional(8, 8, 2)),
    (Cropping2D(cropping=(1, 1, 2, 2)), InputType.convolutional(8, 8, 2)),
    (SpaceToDepthLayer(block_size=2), InputType.convolutional(8, 8, 3)),
    (LocalResponseNormalization(), InputType.convolutional(6, 6, 8)),
    (BatchNormalization(), InputType.convolutional(6, 6, 4)),
]

RNN_CASES = [
    (LSTM(n_out=12), InputType.recurrent(5, 7)),
    (GravesLSTM(n_out=12), InputType.recurrent(5, 7)),
    (SimpleRnn(n_out=6), InputType.recurrent(5, 7)),
    (Bidirectional(layer=LSTM(n_out=4)), InputType.recurrent(5, 7)),
    (RnnOutputLayer(n_out=9), InputType.recurrent(5, 7)),
    (Convolution1DLayer(n_out=6, kernel=3), InputType.recurrent(5, 7)),
]


@pytest.mark.parametrize("layer,itype", FF_CASES + CNN_CASES + RNN_CASES,
                         ids=lambda v: type(v).__name__ if hasattr(v, "apply")
                         else str(v.shape))
def test_shape_inference_matches_forward(layer, itype):
    out_t = layer.output_type(itype)
    y, _, _ = run_layer(layer, itype, batch=2)
    assert y.shape == (2,) + out_t.shape, \
        f"{type(layer).__name__}: inferred {out_t.shape}, got {y.shape[1:]}"
    assert jnp.all(jnp.isfinite(y))


@pytest.mark.parametrize("layer,itype", FF_CASES + CNN_CASES + RNN_CASES,
                         ids=lambda v: type(v).__name__ if hasattr(v, "apply")
                         else str(v.shape))
def test_serde_roundtrip(layer, itype):
    d = layer_to_dict(layer)
    back = layer_from_dict(d)
    assert back == layer


class TestMaxPoolValues:
    def test_known(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        layer = SubsamplingLayer(kernel=(2, 2), stride=(2, 2))
        y, _, _ = run_layer(layer, InputType.convolutional(4, 4, 1), x=x)
        np.testing.assert_allclose(y[0, :, :, 0], [[5, 7], [13, 15]])

    def test_avg(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        layer = SubsamplingLayer(kernel=(2, 2), stride=(2, 2), pooling_type="avg")
        y, _, _ = run_layer(layer, InputType.convolutional(4, 4, 1), x=x)
        np.testing.assert_allclose(y[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])


class TestBatchNorm:
    def test_train_normalizes(self):
        layer = BatchNormalization()
        itype = InputType.feed_forward(4)
        x = jax.random.normal(jax.random.PRNGKey(2), (256, 4)) * 5 + 3
        params, state = layer.init(KEY, itype)
        y, new_state = layer.apply(params, state, x, train=True)
        np.testing.assert_allclose(jnp.mean(y, axis=0), jnp.zeros(4), atol=1e-4)
        np.testing.assert_allclose(jnp.std(y, axis=0), jnp.ones(4), atol=1e-2)
        # running stats moved toward batch stats
        assert float(jnp.max(jnp.abs(new_state["mean"]))) > 0

    def test_inference_uses_running_stats(self):
        layer = BatchNormalization(decay=0.0)   # running = batch stats directly
        itype = InputType.feed_forward(4)
        x = jax.random.normal(jax.random.PRNGKey(2), (256, 4)) * 5 + 3
        params, state = layer.init(KEY, itype)
        _, state1 = layer.apply(params, state, x, train=True)
        y, _ = layer.apply(params, state1, x, train=False)
        np.testing.assert_allclose(jnp.mean(y, axis=0), jnp.zeros(4), atol=1e-2)


class TestRecurrentSemantics:
    def test_mask_stops_state(self):
        """Masked steps must output zeros and zero the cell state
        (DL4J LSTMHelpers.java:355-357 semantics)."""
        layer = LSTM(n_out=4)
        itype = InputType.recurrent(3, 6)
        params, state = layer.init(KEY, itype)
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 6, 3))
        mask = jnp.array([[1, 1, 1, 0, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.float32)
        y, _ = layer.apply(params, state, x, mask=mask)
        np.testing.assert_allclose(y[0, 3:], jnp.zeros((3, 4)), atol=1e-6)
        assert float(jnp.max(jnp.abs(y[1, 3:]))) > 0

    def test_rnn_step_matches_full_forward(self):
        """Streaming rnn_step must reproduce the full-sequence forward
        (rnnTimeStep contract, MultiLayerNetwork.java:2806)."""
        layer = GravesLSTM(n_out=5)
        itype = InputType.recurrent(4, 8)
        params, state = layer.init(KEY, itype)
        x = jax.random.normal(jax.random.PRNGKey(6), (3, 8, 4))
        full, _ = layer.apply(params, state, x)
        carry = None
        for t in range(8):
            step_out, carry = layer.rnn_step(params, x[:, t, :], carry)
            np.testing.assert_allclose(step_out, full[:, t, :], rtol=1e-5,
                                       atol=1e-5)

    def test_apply_seq_chunks_match_full(self):
        """tBPTT chunking must equal the unchunked forward."""
        layer = LSTM(n_out=4)
        itype = InputType.recurrent(3, 8)
        params, state = layer.init(KEY, itype)
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 8, 3))
        full, _ = layer.apply(params, state, x)
        y1, carry = layer.apply_seq(params, x[:, :4], None)
        y2, _ = layer.apply_seq(params, x[:, 4:], carry)
        chunked = jnp.concatenate([y1, y2], axis=1)
        np.testing.assert_allclose(chunked, full, rtol=1e-5, atol=1e-5)

    def test_bidirectional_concat_width(self):
        layer = Bidirectional(layer=LSTM(n_out=4), mode="concat")
        y, _, _ = run_layer(layer, InputType.recurrent(3, 6))
        assert y.shape == (2, 6, 8)

    def test_last_time_step_mask(self):
        inner = SimpleRnn(n_out=3)
        layer = LastTimeStep(layer=inner)
        itype = InputType.recurrent(2, 5)
        params, state = layer.init(KEY, itype)
        x = jax.random.normal(jax.random.PRNGKey(8), (2, 5, 2))
        mask = jnp.array([[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
        y, _ = layer.apply(params, state, x, mask=mask)
        full, _ = inner.apply(params, {}, x, mask=mask)
        np.testing.assert_allclose(y[0], full[0, 1], rtol=1e-5)
        np.testing.assert_allclose(y[1], full[1, 4], rtol=1e-5)


class TestEmbedding:
    def test_lookup(self):
        layer = EmbeddingLayer(n_in=10, n_out=4)
        params, state = layer.init(KEY, InputType.feed_forward(10))
        idx = jnp.array([0, 3, 9])
        y, _ = layer.apply(params, state, idx)
        np.testing.assert_allclose(y, params["W"][jnp.array([0, 3, 9])])


class TestDropout:
    def test_train_vs_inference(self):
        layer = DropoutLayer(dropout=0.5)
        x = jnp.ones((4, 100))
        y_inf, _ = layer.apply({}, {}, x, train=False)
        np.testing.assert_allclose(y_inf, x)
        y_tr, _ = layer.apply({}, {}, x, train=True, rng=jax.random.PRNGKey(0))
        frac_zero = float(jnp.mean(y_tr == 0))
        assert 0.3 < frac_zero < 0.7
        # inverted scaling preserves expectation
        assert abs(float(jnp.mean(y_tr)) - 1.0) < 0.1


class TestGlobalPooling:
    def test_rnn_masked_avg(self):
        layer = GlobalPoolingLayer(pooling_type="avg")
        x = jnp.stack([jnp.ones((4, 3)), 2 * jnp.ones((4, 3))])
        mask = jnp.array([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
        y, _ = layer.apply({}, {}, x, mask=mask)
        np.testing.assert_allclose(y[0], jnp.ones(3))
        np.testing.assert_allclose(y[1], 2 * jnp.ones(3))


class TestVAE:
    def test_pretrain_score_finite_and_differentiable(self):
        layer = VariationalAutoencoder(n_out=3, encoder_layer_sizes=(8,),
                                       decoder_layer_sizes=(8,))
        params, _ = layer.init(KEY, InputType.feed_forward(6))
        x = jax.random.normal(jax.random.PRNGKey(9), (10, 6))
        score = layer.pretrain_score(params, x, jax.random.PRNGKey(10))
        assert jnp.isfinite(score)
        grads = jax.grad(lambda p: layer.pretrain_score(p, x, jax.random.PRNGKey(10)))(params)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(jnp.all(jnp.isfinite(g)) for g in flat)


def test_gru_accepts_cnn_input_via_preprocessor():
    """GRU registered in _KIND_BY_CLASS: a CNN input ahead of a GRU gets
    the automatic CNN->RNN preprocessor exactly like LSTM does."""
    import numpy as np
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import GRU, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(GRU(n_out=6))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(5, 3, 2))
            .build())
    net = MultiLayerNetwork(conf).init()
    X = np.random.RandomState(0).rand(4, 5, 3, 2).astype("float32")
    out = np.asarray(net.output(X))
    # CNN->RNN preprocessor: (B, 5, 3, 2) -> (B, 5*3=15 steps, 2 features)
    assert out.shape == (4, 15, 2)
    Y = np.eye(2, dtype="float32")[np.random.RandomState(1)
                                   .randint(0, 2, (4, 15))]
    net.fit(ArrayDataSetIterator(X, Y, batch_size=4), epochs=1)
    assert np.isfinite(net.score())
