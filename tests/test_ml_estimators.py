"""sklearn-contract estimator tests (dl4j-spark-ml analog:
SparkDl4jNetworkTest.java / AutoEncoderNetworkTest.java)."""
import numpy as np
import pytest

from deeplearning4j_tpu.ml import (
    AutoEncoderTransformer, DL4JClassifier, DL4JRegressor,
)


def _cls_data(n=240, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(3, 6) * 3
    X = np.concatenate([centers[i] + rs.randn(n // 3, 6)
                        for i in range(3)]).astype("float32")
    y = np.repeat(["a", "b", "c"], n // 3)      # string labels
    perm = rs.permutation(n)
    return X[perm], y[perm]


def test_classifier_fit_predict_score():
    X, y = _cls_data()
    clf = DL4JClassifier(hidden=(24,), epochs=30, batch_size=48, seed=3)
    clf.fit(X, y)
    assert set(clf.predict(X[:10])) <= {"a", "b", "c"}
    proba = clf.predict_proba(X)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert clf.score(X, y) > 0.9            # ClassifierMixin accuracy
    with pytest.raises(RuntimeError):
        DL4JClassifier().predict(X)


def test_regressor_learns_linear_map():
    rs = np.random.RandomState(1)
    X = rs.randn(256, 5).astype("float32")
    w = rs.randn(5)
    y = X @ w + 0.01 * rs.randn(256)
    reg = DL4JRegressor(hidden=(32,), epochs=60, batch_size=64, seed=2)
    reg.fit(X, y)
    assert reg.score(X, y) > 0.95           # RegressorMixin R^2
    assert reg.predict(X).shape == (256,)


def test_sklearn_pipeline_and_grid_search_integration():
    sklearn = pytest.importorskip("sklearn")
    from sklearn.model_selection import GridSearchCV
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler
    X, y = _cls_data(n=120)
    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("net", DL4JClassifier(hidden=(16,), epochs=15, batch_size=40)),
    ])
    pipe.fit(X, y)
    assert pipe.score(X, y) > 0.8
    gs = GridSearchCV(DL4JClassifier(epochs=10, batch_size=40),
                      {"hidden": [(8,), (16,)]}, cv=2, n_jobs=1)
    gs.fit(X, y)
    assert set(gs.best_params_) == {"hidden"}


def test_autoencoder_transformer_reduces_dim():
    X, _ = _cls_data(n=150)
    tf = AutoEncoderTransformer(n_components=4, epochs=20, batch_size=50)
    Z = tf.fit_transform(X)
    assert Z.shape == (150, 4)
    assert np.isfinite(Z).all()


def test_estimator_pickle_round_trip():
    """joblib/pickle persistence of fitted estimators rides the
    checkpoint-zip format (optax closures don't pickle directly)."""
    import pickle

    X, y = _cls_data(n=90)
    clf = DL4JClassifier(hidden=(8,), epochs=10, batch_size=30).fit(X, y)
    back = pickle.loads(pickle.dumps(clf))
    assert (back.predict(X) == clf.predict(X)).all()
    np.testing.assert_allclose(back.predict_proba(X), clf.predict_proba(X),
                               atol=1e-6)
    # fitted-and-restored estimator can keep training
    back.fit(X, y)
    # unfitted estimators round-trip too (GridSearchCV clones pickle)
    assert not hasattr(pickle.loads(pickle.dumps(DL4JClassifier())),
                       "network_")
