"""DL4J artifact bridge tests.

These are cross-LAYOUT tests: fixture zips are built in the reference's
on-disk format (ModelSerializer.java:109-173 zip entries; f-order dense
weights, bias-first 'c'-order NCHW conv weights, IFOG LSTM gate blocks —
per the reference param initializers), and the imported network's forward
pass is checked against an independent NumPy oracle that implements the
REFERENCE's semantics (NCHW conv, IFOG gates, NCHW 'c'-order flatten).
Passing means the layout conversions in modelimport/dl4j.py are right, not
merely self-consistent.
"""
import io
import json
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    UnsupportedLayerError, read_nd4j_array, restore_multilayer_network,
    save_dl4j_model, write_nd4j_array,
)
from deeplearning4j_tpu.nn.conf.base import InputType

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "dl4j")


# ----------------------------------------------------------------- helpers

def _act_relu():
    return {"@class": "org.nd4j.linalg.activations.impl.ActivationReLU"}


def _act(name):
    return {"@class": f"org.nd4j.linalg.activations.impl.Activation{name}"}


def _adam(lr=1e-3):
    return {"@class": "org.nd4j.linalg.learning.config.Adam",
            "learningRate": lr, "beta1": 0.9, "beta2": 0.999,
            "epsilon": 1e-8}


def _conf_json(layer_entries, **top):
    confs = []
    for kind, body in layer_entries:
        if "updater" not in body:          # legacy bodies carry the enum
            body.setdefault("iUpdater", _adam())
        confs.append({"layer": {kind: body}, "seed": 12345,
                      "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
                      "miniBatch": True, "minimize": True})
    d = {"backprop": True, "backpropType": "Standard", "pretrain": False,
         "confs": confs}
    d.update(top)
    return json.dumps(d)


def _zip_bytes(conf_json, flat, updater=None):
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("configuration.json", conf_json)
        b = io.BytesIO()
        write_nd4j_array(b, np.asarray(flat, np.float32))
        zf.writestr("coefficients.bin", b.getvalue())
        if updater is not None:
            b = io.BytesIO()
            write_nd4j_array(b, np.asarray(updater, np.float32))
            zf.writestr("updaterState.bin", b.getvalue())
    buf.seek(0)
    return buf


def _softmax(z):
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


# ----------------------------------------------------------------- codec

def test_nd4j_codec_roundtrip():
    rs = np.random.RandomState(0)
    for shape in [(1, 7), (3, 4), (2, 3, 4, 5), (10,)]:
        a = rs.randn(*shape).astype(np.float32)
        buf = io.BytesIO()
        write_nd4j_array(buf, a)
        buf.seek(0)
        b = read_nd4j_array(buf)
        np.testing.assert_array_equal(
            b, a.reshape(1, -1) if a.ndim == 1 else a)


def test_nd4j_codec_reads_f_order():
    """A reference-produced 'f'-ordered array must come back transposed
    correctly (shape-info order char honored)."""
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    # hand-build an f-order stream: shapeInfo rank2 [3,4], strides [1,3]
    import struct
    buf = io.BytesIO()

    def utf(s):
        b = s.encode()
        buf.write(struct.pack(">H", len(b)) + b)

    utf("DIRECT")
    si = [2, 3, 4, 1, 3, 0, 1, ord("f")]
    buf.write(struct.pack(">i", len(si)))
    utf("INT")
    buf.write(np.asarray(si, ">i4").tobytes())
    utf("DIRECT")
    buf.write(struct.pack(">i", 12))
    utf("FLOAT")
    buf.write(a.ravel(order="F").astype(">f4").tobytes())
    buf.seek(0)
    np.testing.assert_array_equal(read_nd4j_array(buf), a)


# ----------------------------------------------------------------- MLP

def _mlp_fixture(rs):
    """4 -> 5 relu dense -> 3 softmax output, flat in reference order."""
    W1 = rs.randn(4, 5).astype(np.float32)
    b1 = rs.randn(5).astype(np.float32)
    W2 = rs.randn(5, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2])
    cj = _conf_json([
        ("dense", {"activationFn": _act_relu(), "nin": 4, "nout": 5,
                   "hasBias": True, "layerName": "l0"}),
        ("output", {"activationFn": _act("Softmax"), "nin": 5, "nout": 3,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    return (W1, b1, W2, b2), cj, flat


def test_mlp_import_forward_parity():
    rs = np.random.RandomState(1)
    (W1, b1, W2, b2), cj, flat = _mlp_fixture(rs)
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    x = rs.randn(6, 4).astype(np.float32)
    ours = np.asarray(net.output(x))
    oracle = _softmax(np.maximum(x @ W1 + b1, 0) @ W2 + b2)
    np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)


def test_updater_state_adam_grafts():
    rs = np.random.RandomState(2)
    _, cj, flat = _mlp_fixture(rs)
    n = flat.size
    m = rs.randn(n).astype(np.float32)
    v = np.abs(rs.randn(n)).astype(np.float32)
    net = restore_multilayer_network(
        _zip_bytes(cj, flat, updater=np.concatenate([m, v])))
    import optax
    adam = [s for s in net.opt_state
            if isinstance(s, optax.ScaleByAdamState)][0]
    # dense-0 W occupies the first 20 slots of m, f-order (4,5)
    np.testing.assert_allclose(
        np.asarray(adam.mu["0"]["W"]),
        m[:20].reshape((4, 5), order="F"), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(adam.nu["1"]["b"]), v[-3:], rtol=1e-6)


def test_updater_state_length_mismatch_skipped():
    rs = np.random.RandomState(3)
    _, cj, flat = _mlp_fixture(rs)
    net = restore_multilayer_network(
        _zip_bytes(cj, flat, updater=np.zeros(5, np.float32)))
    # import succeeded; state untouched (zeros from init)
    import optax
    adam = [s for s in net.opt_state
            if isinstance(s, optax.ScaleByAdamState)][0]
    assert float(np.abs(np.asarray(adam.mu["0"]["W"])).sum()) == 0.0


# ----------------------------------------------------------------- CNN

def _conv2d_nchw(x, W, b, stride=1):
    """Reference-semantics conv: x (B,C,H,W), W (O,I,kh,kw), valid."""
    B, C, H, Wd = x.shape
    O, _, kh, kw = W.shape
    oh = (H - kh) // stride + 1
    ow = (Wd - kw) // stride + 1
    out = np.zeros((B, O, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + kh,
                      j * stride:j * stride + kw]            # (B,C,kh,kw)
            out[:, :, i, j] = np.einsum("bchw,ochw->bo", patch, W)
    return out + b[None, :, None, None]


def _maxpool_nchw(x, k=2, s=2):
    B, C, H, W = x.shape
    oh, ow = (H - k) // s + 1, (W - k) // s + 1
    out = np.zeros((B, C, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * s:i * s + k,
                                j * s:j * s + k].max((2, 3))
    return out


def test_cnn_import_forward_parity():
    """conv(2->3, 3x3) relu -> maxpool 2x2 -> output softmax, 8x8 input.
    Exercises: bias-first conv segment, OIhw->HWIO kernel transpose, and
    the NCHW->NHWC dense-row permutation at the flatten boundary."""
    rs = np.random.RandomState(4)
    Wc = rs.randn(3, 2, 3, 3).astype(np.float32)     # (O,I,kh,kw)
    bc = rs.randn(3).astype(np.float32)
    # after conv 8x8->6x6, pool ->3x3: flatten 3*3*3=27 (NCHW c-order)
    Wd = rs.randn(27, 4).astype(np.float32)
    bd = rs.randn(4).astype(np.float32)
    flat = np.concatenate([bc, Wc.ravel(order="C"),
                           Wd.ravel(order="F"), bd])
    cj = _conf_json([
        ("convolution", {"activationFn": _act_relu(), "nin": 2, "nout": 3,
                         "kernelSize": [3, 3], "stride": [1, 1],
                         "padding": [0, 0], "convolutionMode": "Truncate",
                         "hasBias": True}),
        ("subsampling", {"kernelSize": [2, 2], "stride": [2, 2],
                         "padding": [0, 0], "poolingType": "MAX",
                         "convolutionMode": "Truncate"}),
        ("output", {"activationFn": _act("Softmax"), "nin": 27, "nout": 4,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(
        _zip_bytes(cj, flat), input_type=InputType.convolutional(8, 8, 2))

    x_nchw = rs.randn(2, 2, 8, 8).astype(np.float32)
    h = np.maximum(_conv2d_nchw(x_nchw, Wc, bc), 0)
    h = _maxpool_nchw(h)
    oracle = _softmax(h.reshape(2, -1) @ Wd + bd)    # NCHW c-order flatten

    ours = np.asarray(net.output(x_nchw.transpose(0, 2, 3, 1)))  # NHWC feed
    np.testing.assert_allclose(ours, oracle, rtol=1e-4, atol=1e-5)


def test_bn_import_inference_parity():
    rs = np.random.RandomState(5)
    n = 6
    gamma = rs.rand(n).astype(np.float32) + 0.5
    beta = rs.randn(n).astype(np.float32)
    mean = rs.randn(n).astype(np.float32)
    var = rs.rand(n).astype(np.float32) + 0.5
    Wo = rs.randn(n, 3).astype(np.float32)
    bo = rs.randn(3).astype(np.float32)
    flat = np.concatenate([gamma, beta, mean, var, Wo.ravel(order="F"), bo])
    cj = _conf_json([
        ("batchNormalization", {"nin": n, "nout": n, "eps": 1e-5,
                                "decay": 0.9, "gamma": 1.0, "beta": 0.0,
                                "lockGammaBeta": False}),
        ("output", {"activationFn": _act("Softmax"), "nin": n, "nout": 3,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(
        _zip_bytes(cj, flat), input_type=InputType.feed_forward(n))
    x = rs.randn(4, n).astype(np.float32)
    norm = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    oracle = _softmax(norm @ Wo + bo)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------------- LSTM

def _lstm_oracle_ifog(x, W, R, b, H):
    """Reference LSTM forward (LSTMHelpers.activateHelper, no peepholes):
    gate blocks in IFOG order, sigmoid gates, tanh cell."""
    def sig(z):
        return 1.0 / (1.0 + np.exp(-z))
    B, T, _ = x.shape
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    hs = np.zeros((B, T, H), np.float32)
    for t in range(T):
        z = x[:, t] @ W + h @ R + b
        i = sig(z[:, :H])
        f = sig(z[:, H:2 * H])
        o = sig(z[:, 2 * H:3 * H])
        g = np.tanh(z[:, 3 * H:])
        c = f * c + i * g
        h = o * np.tanh(c)
        hs[:, t] = h
    return hs


def test_lstm_import_gate_permutation():
    rs = np.random.RandomState(6)
    nin, H, T, B = 3, 4, 5, 2
    W = rs.randn(nin, 4 * H).astype(np.float32)
    R = rs.randn(H, 4 * H).astype(np.float32)
    b = rs.randn(4 * H).astype(np.float32)
    Wo = rs.randn(H, 2).astype(np.float32)
    bo = rs.randn(2).astype(np.float32)
    flat = np.concatenate([W.ravel(order="F"), R.ravel(order="F"), b,
                           Wo.ravel(order="F"), bo])
    cj = _conf_json([
        ("LSTM", {"activationFn": _act("TanH"), "nin": nin, "nout": H,
                  "gateActivationFn": _act("Sigmoid"),
                  "forgetGateBiasInit": 1.0}),
        ("rnnoutput", {"activationFn": _act("Softmax"), "nin": H, "nout": 2,
                       "lossFn": {"@class":
                                  "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(
        _zip_bytes(cj, flat), input_type=InputType.recurrent(nin, T))
    x = rs.randn(B, T, nin).astype(np.float32)
    hs = _lstm_oracle_ifog(x, W, R, b, H)
    oracle = _softmax(hs @ Wo + bo)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-4, atol=1e-5)


def test_graves_lstm_rejected():
    cj = _conf_json([("gravesLSTM", {"nin": 3, "nout": 4,
                                     "activationFn": _act("TanH")})])
    with pytest.raises(UnsupportedLayerError, match="peephole"):
        restore_multilayer_network(_zip_bytes(cj, np.zeros(1)))


# ----------------------------------------------------------------- export

def test_export_import_roundtrip(tmp_path):
    """our net -> DL4J zip -> import -> identical forward + updater state."""
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.data.dataset import DataSet

    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=3, kernel=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(8)
    X = rs.rand(4, 8, 8, 2).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)]
    net.fit(DataSet(X, Y))                       # non-trivial updater state

    p = tmp_path / "model.zip"
    save_dl4j_model(net, p)
    with zipfile.ZipFile(p) as zf:
        assert {"configuration.json", "coefficients.bin",
                "updaterState.bin"} <= set(zf.namelist())
    net2 = restore_multilayer_network(
        p, input_type=InputType.convolutional(8, 8, 2))
    np.testing.assert_allclose(np.asarray(net.output(X)),
                               np.asarray(net2.output(X)),
                               rtol=1e-5, atol=1e-6)
    import optax
    a1 = [s for s in net.opt_state
          if isinstance(s, optax.ScaleByAdamState)][0]
    a2 = [s for s in net2.opt_state
          if isinstance(s, optax.ScaleByAdamState)][0]
    np.testing.assert_allclose(np.asarray(a1.mu["2"]["W"]),
                               np.asarray(a2.mu["2"]["W"]),
                               rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------- golden

def test_golden_dl4j_fixture():
    """Regression: a committed reference-format zip must import with
    byte-stable outputs (tests/fixtures/dl4j/, generated once by
    tools/make_dl4j_fixture.py — NOT by the serializer under test)."""
    path = os.path.join(FIXDIR, "mlp_mnistlike.zip")
    expected = os.path.join(FIXDIR, "mlp_mnistlike_expected.json")
    assert os.path.exists(path), "golden DL4J fixture missing"
    net = restore_multilayer_network(path)
    with open(expected) as f:
        exp = json.load(f)
    x = np.asarray(exp["input"], np.float32)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, np.asarray(exp["output"], np.float32),
                               rtol=1e-5, atol=1e-6)


def test_elementwise_mult_import():
    rs = np.random.RandomState(9)
    n = 5
    W1 = rs.randn(4, n).astype(np.float32)
    b1 = rs.randn(n).astype(np.float32)
    w = rs.randn(n).astype(np.float32)
    bw = rs.randn(n).astype(np.float32)
    Wo = rs.randn(n, 2).astype(np.float32)
    bo = rs.randn(2).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1, w, bw,
                           Wo.ravel(order="F"), bo])
    cj = _conf_json([
        ("dense", {"activationFn": _act_relu(), "nin": 4, "nout": n,
                   "hasBias": True}),
        ("ElementWiseMult", {"activationFn": _act("TanH"), "nin": n,
                             "nout": n}),
        ("output", {"activationFn": _act("Softmax"), "nin": n, "nout": 2,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    x = rs.randn(3, 4).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0)
    h = np.tanh(h * w + bw)
    oracle = _softmax(h @ Wo + bo)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-5, atol=1e-6)


def test_center_loss_import():
    """CenterLossParamInitializer order [W | b | centers(nOut x nIn, c)];
    lambda/alpha come through, forward parity vs a numpy oracle."""
    rs = np.random.RandomState(11)
    nin, nout = 5, 3
    W1 = rs.randn(4, nin).astype(np.float32)
    b1 = rs.randn(nin).astype(np.float32)
    Wo = rs.randn(nin, nout).astype(np.float32)
    bo = rs.randn(nout).astype(np.float32)
    centers = rs.randn(nout, nin).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           Wo.ravel(order="F"), bo,
                           centers.ravel(order="C")])
    cj = _conf_json([
        ("dense", {"activationFn": _act_relu(), "nin": 4, "nout": nin,
                   "hasBias": True}),
        ("CenterLossOutputLayer", {
            "activationFn": _act("Softmax"), "nin": nin, "nout": nout,
            "hasBias": True, "alpha": 0.1, "lambda": 0.25,
            "lossFn": {"@class":
                       "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    head = net.conf.layers[-1]
    assert head.lambda_ == 0.25 and head.alpha == 0.1
    np.testing.assert_allclose(np.asarray(net.params["1"]["cL"]), centers,
                               rtol=1e-6)
    x = rs.randn(3, 4).astype(np.float32)
    oracle = _softmax(np.maximum(x @ W1 + b1, 0) @ Wo + bo)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-5, atol=1e-6)


def test_dropout_l1_l2_import_mapping():
    """DL4J iDropout p is the RETAIN probability; l1/l2 must land on the
    param-carrying layer, not be silently dropped."""
    rs = np.random.RandomState(10)
    flat = np.concatenate([rs.randn(4 * 5).astype(np.float32),
                           rs.randn(5).astype(np.float32),
                           rs.randn(5 * 2).astype(np.float32),
                           rs.randn(2).astype(np.float32)])
    cj = _conf_json([
        ("dense", {"activationFn": _act_relu(), "nin": 4, "nout": 5,
                   "hasBias": True, "l1": 1e-4, "l2": 1e-3,
                   "iDropout": {"@class":
                                "org.deeplearning4j.nn.conf.dropout.Dropout",
                                "p": 0.8}}),
        ("output", {"activationFn": _act("Softmax"), "nin": 5, "nout": 2,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    d0 = net.layers[0]
    assert abs(d0.dropout - 0.2) < 1e-9      # 1 - retain(0.8)
    assert d0.l1 == pytest.approx(1e-4) and d0.l2 == pytest.approx(1e-3)
    # and the export direction writes it back in DL4J's convention
    import io as _io
    import json as _json
    buf = _io.BytesIO()
    save_dl4j_model(net, buf, save_updater=False)
    buf.seek(0)
    with zipfile.ZipFile(buf) as zf:
        conf = _json.loads(zf.read("configuration.json"))
    dense_body = conf["confs"][0]["layer"]["dense"]
    assert dense_body["iDropout"]["p"] == pytest.approx(0.8)
    assert dense_body["l1"] == pytest.approx(1e-4)


def test_adadelta_updater_state():
    rs = np.random.RandomState(11)
    W1 = rs.randn(4, 5).astype(np.float32)
    b1 = rs.randn(5).astype(np.float32)
    W2 = rs.randn(5, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2])
    ad = {"@class": "org.nd4j.linalg.learning.config.AdaDelta",
          "rho": 0.95, "epsilon": 1e-6}
    cj = _conf_json([
        ("dense", {"activationFn": _act_relu(), "nin": 4, "nout": 5,
                   "hasBias": True, "iUpdater": ad}),
        ("output", {"activationFn": _act("Softmax"), "nin": 5, "nout": 3,
                    "hasBias": True, "iUpdater": ad,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    n = flat.size
    msg = np.abs(rs.randn(n)).astype(np.float32)
    msdx = np.abs(rs.randn(n)).astype(np.float32)
    net = restore_multilayer_network(
        _zip_bytes(cj, flat, updater=np.concatenate([msg, msdx])))
    import optax
    st = [s for s in net.opt_state
          if isinstance(s, optax.ScaleByAdaDeltaState)][0]
    np.testing.assert_allclose(np.asarray(st.e_g["0"]["W"]),
                               msg[:20].reshape((4, 5), order="F"),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st.e_x["1"]["b"]), msdx[-3:],
                               rtol=1e-6)


def test_regression_head_identity_survives_import():
    """Explicit ActivationIdentity + LossMSE (the standard DL4J regression
    head) must NOT be rewritten to softmax on import."""
    rs = np.random.RandomState(12)
    W = rs.randn(3, 1).astype(np.float32)
    b = rs.randn(1).astype(np.float32)
    flat = np.concatenate([W.ravel(order="F"), b])
    cj = _conf_json([
        ("output", {"activationFn": _act("Identity"), "nin": 3, "nout": 1,
                    "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMSE"}}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    assert net.layers[0].activation == "identity"
    assert net.layers[0].loss == "mse"
    x = rs.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)), x @ W + b,
                               rtol=1e-5, atol=1e-6)
    # absent activationFn still defaults to softmax
    cj2 = _conf_json([
        ("output", {"nin": 3, "nout": 2, "hasBias": True,
                    "lossFn": {"@class":
                               "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    flat2 = np.concatenate([rs.randn(6).astype(np.float32),
                            rs.randn(2).astype(np.float32)])
    net2 = restore_multilayer_network(_zip_bytes(cj2, flat2))
    assert net2.layers[0].activation == "softmax"


# ----------------------------------------------------------- graph import

def _graph_zip(vertices, vertex_inputs, inputs, outputs, flat,
               updater=None):
    conf = {"networkInputs": inputs, "networkOutputs": outputs,
            "vertices": vertices, "vertexInputs": vertex_inputs,
            "backprop": True, "backpropType": "Standard"}
    return _zip_bytes(json.dumps(conf), flat, updater)


def _layer_vertex(kind, body):
    body = dict(body)
    body.setdefault("iUpdater", _adam())
    return {"LayerVertex": {"layerConf": {"layer": {kind: body},
                                          "seed": 12345}}}


def test_graph_import_merge_topology():
    """Branching graph: in -> d1, in -> d2, merge(d1,d2) -> output. Flat
    params follow the reference's Kahn topological order (in,d1,d2,out)."""
    from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph

    rs = np.random.RandomState(20)
    W1 = rs.randn(4, 3).astype(np.float32)
    b1 = rs.randn(3).astype(np.float32)
    W2 = rs.randn(4, 5).astype(np.float32)
    b2 = rs.randn(5).astype(np.float32)
    Wo = rs.randn(8, 2).astype(np.float32)
    bo = rs.randn(2).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2,
                           Wo.ravel(order="F"), bo])
    vertices = {
        "d1": _layer_vertex("dense", {"activationFn": _act("TanH"),
                                      "nin": 4, "nout": 3,
                                      "hasBias": True}),
        "d2": _layer_vertex("dense", {"activationFn": _act_relu(),
                                      "nin": 4, "nout": 5,
                                      "hasBias": True}),
        "m": {"MergeVertex": {}},
        "out": _layer_vertex("output", {
            "activationFn": _act("Softmax"), "nin": 8, "nout": 2,
            "hasBias": True,
            "lossFn": {"@class":
                       "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    }
    vertex_inputs = {"d1": ["in"], "d2": ["in"], "m": ["d1", "d2"],
                     "out": ["m"]}
    gnet = restore_computation_graph(_graph_zip(
        vertices, vertex_inputs, ["in"], ["out"], flat))
    x = rs.randn(6, 4).astype(np.float32)
    h1 = np.tanh(x @ W1 + b1)
    h2 = np.maximum(x @ W2 + b2, 0)
    oracle = _softmax(np.concatenate([h1, h2], 1) @ Wo + bo)
    out = gnet.output(x)
    ours = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
    np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)


def test_graph_import_updater_state_and_elementwise_vertex():
    from deeplearning4j_tpu.modelimport.dl4j import restore_computation_graph

    rs = np.random.RandomState(21)
    W1 = rs.randn(4, 4).astype(np.float32)
    b1 = rs.randn(4).astype(np.float32)
    Wo = rs.randn(4, 2).astype(np.float32)
    bo = rs.randn(2).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           Wo.ravel(order="F"), bo])
    vertices = {
        "d1": _layer_vertex("dense", {"activationFn": _act("TanH"),
                                      "nin": 4, "nout": 4,
                                      "hasBias": True}),
        "res": {"ElementWiseVertex": {"op": "Add"}},
        "out": _layer_vertex("output", {
            "activationFn": _act("Softmax"), "nin": 4, "nout": 2,
            "hasBias": True,
            "lossFn": {"@class":
                       "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    }
    vertex_inputs = {"d1": ["in"], "res": ["d1", "in"], "out": ["res"]}
    n = flat.size
    m = rs.randn(n).astype(np.float32)
    v = np.abs(rs.randn(n)).astype(np.float32)
    gnet = restore_computation_graph(
        _graph_zip(vertices, vertex_inputs, ["in"], ["out"], flat,
                   updater=np.concatenate([m, v])))
    x = rs.randn(3, 4).astype(np.float32)
    h = np.tanh(x @ W1 + b1) + x                   # residual add
    oracle = _softmax(h @ Wo + bo)
    out = gnet.output(x)
    ours = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
    np.testing.assert_allclose(ours, oracle, rtol=1e-5, atol=1e-6)
    import optax
    adam = [s for s in gnet.opt_state
            if isinstance(s, optax.ScaleByAdamState)][0]
    np.testing.assert_allclose(np.asarray(adam.mu["d1"]["W"]),
                               m[:16].reshape((4, 4), order="F"), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(adam.nu["out"]["b"]), v[-2:],
                               rtol=1e-6)


# ----------------------------------------------------------- normalizer

def test_normalizer_bin_roundtrip(tmp_path):
    from deeplearning4j_tpu.data.normalization import (
        NormalizerMinMaxScaler, NormalizerStandardize,
    )
    from deeplearning4j_tpu.modelimport import (
        add_normalizer_to_model, restore_normalizer,
    )

    rs = np.random.RandomState(30)
    _, cj, flat = _mlp_fixture(rs)
    p = tmp_path / "model.zip"
    with open(p, "wb") as fh:
        fh.write(_zip_bytes(cj, flat).getvalue())

    assert restore_normalizer(p) is None       # no entry yet

    norm = NormalizerStandardize(fit_labels=True)
    norm.feature_mean = rs.randn(4).astype(np.float32)
    norm.feature_std = (np.abs(rs.randn(4)) + 0.5).astype(np.float32)
    norm.label_mean = rs.randn(3).astype(np.float32)
    norm.label_std = (np.abs(rs.randn(3)) + 0.5).astype(np.float32)
    add_normalizer_to_model(p, norm)

    back = restore_normalizer(p)
    np.testing.assert_allclose(back.feature_mean, norm.feature_mean)
    np.testing.assert_allclose(back.label_std, norm.label_std)
    # the model entries survived the in-place rewrite
    net = restore_multilayer_network(p)
    x = rs.randn(2, 4).astype(np.float32)
    assert np.asarray(net.output(x)).shape == (2, 3)

    # min-max variant with target range
    mm = NormalizerMinMaxScaler(lo=-1.0, hi=1.0)
    mm.feature_min = rs.randn(4).astype(np.float32)
    mm.feature_max = mm.feature_min + 2.0
    add_normalizer_to_model(p, mm)             # replaces the entry
    back2 = restore_normalizer(p)
    assert isinstance(back2, NormalizerMinMaxScaler)
    assert back2.lo == -1.0 and back2.hi == 1.0
    np.testing.assert_allclose(back2.feature_max, mm.feature_max)


def test_normalizer_bin_reference_layout():
    """Byte-level check of the STANDARDIZE strategy layout: UTF type tag,
    boolean fitLabel, then Nd4j arrays — so a reference-produced stream
    parses correctly."""
    import struct as _struct

    from deeplearning4j_tpu.modelimport.dl4j import (
        read_normalizer, write_nd4j_array,
    )

    buf = io.BytesIO()
    tag = b"STANDARDIZE"
    buf.write(_struct.pack(">H", len(tag)) + tag)
    buf.write(bytes([0]))                      # fitLabel = false
    write_nd4j_array(buf, np.asarray([1.0, 2.0], np.float32))
    write_nd4j_array(buf, np.asarray([0.5, 0.25], np.float32))
    buf.seek(0)
    norm = read_normalizer(buf)
    np.testing.assert_allclose(norm.feature_mean, [1.0, 2.0])
    np.testing.assert_allclose(norm.feature_std, [0.5, 0.25])


def test_golden_cnn_fixture():
    """Committed reference-format CNN zip: NCHW fixture input is fed NHWC
    here; outputs must match the NumPy NCHW oracle byte-stably."""
    net = restore_multilayer_network(
        os.path.join(FIXDIR, "cnn_mnistlike.zip"),
        input_type=InputType.convolutional(10, 10, 1))
    with open(os.path.join(FIXDIR, "cnn_mnistlike_expected.json")) as f:
        exp = json.load(f)
    x = np.asarray(exp["input_nchw"], np.float32).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(exp["output"], np.float32),
                               rtol=1e-4, atol=1e-5)


def test_golden_lstm_fixture():
    net = restore_multilayer_network(
        os.path.join(FIXDIR, "lstm_chars.zip"),
        input_type=InputType.recurrent(3, 6))
    with open(os.path.join(FIXDIR, "lstm_chars_expected.json")) as f:
        exp = json.load(f)
    np.testing.assert_allclose(
        np.asarray(net.output(np.asarray(exp["input"], np.float32))),
        np.asarray(exp["output"], np.float32), rtol=1e-4, atol=1e-5)


def test_normalizer_minmax_fitlabel_consumed_and_warned(caplog):
    """fitLabel=true MIN_MAX streams parse fully (label arrays consumed)
    and warn that label stats are dropped."""
    import logging
    import struct as _struct

    from deeplearning4j_tpu.modelimport.dl4j import (
        read_normalizer, write_nd4j_array,
    )
    buf = io.BytesIO()
    tag = b"MIN_MAX"
    buf.write(_struct.pack(">H", len(tag)) + tag)
    buf.write(bytes([1]))                          # fitLabel = true
    buf.write(_struct.pack(">d", 0.0))
    buf.write(_struct.pack(">d", 1.0))
    for arr in ([1.0, 2.0], [3.0, 4.0], [0.0], [1.0]):
        write_nd4j_array(buf, np.asarray(arr, np.float32))
    buf.seek(0)
    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        norm = read_normalizer(buf)
    np.testing.assert_allclose(norm.feature_max, [3.0, 4.0])
    assert buf.read() == b""                       # fully consumed
    assert any("fitLabel" in r.message for r in caplog.records)


def test_legacy_pre09_config_import():
    """Pre-0.9 release zips: layer carries "updater": "ADAM" (enum) with
    flat learningRate/adamMeanDecay/adamVarDecay fields, a legacy
    "dropOut" retain-probability double, and "activationFunction" as a
    plain string — the formats the reference's own RegressionTest050/060
    suites deserialize (migrated by BaseNetConfigDeserializer)."""
    rs = np.random.RandomState(40)
    W1 = rs.randn(4, 5).astype(np.float32)
    b1 = rs.randn(5).astype(np.float32)
    W2 = rs.randn(5, 3).astype(np.float32)
    b2 = rs.randn(3).astype(np.float32)
    flat = np.concatenate([W1.ravel(order="F"), b1,
                           W2.ravel(order="F"), b2])
    cj = _conf_json([
        ("dense", {"activationFunction": "relu", "nin": 4, "nout": 5,
                   "updater": "ADAM", "learningRate": 0.005,
                   "adamMeanDecay": 0.9, "adamVarDecay": 0.999,
                   "epsilon": 1e-8, "rho": 0.0,
                   "dropOut": 0.75, "l2": 5e-4}),
        ("output", {"activationFunction": "softmax", "nin": 5, "nout": 3,
                    "updater": "ADAM", "learningRate": 0.005,
                    "adamMeanDecay": 0.9, "adamVarDecay": 0.999,
                    "lossFunction": "MCXENT"}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    from deeplearning4j_tpu.nn.updaters import Adam
    assert isinstance(net.conf.updater, Adam)
    assert net.conf.updater.learning_rate == pytest.approx(0.005)
    d0 = net.layers[0]
    assert d0.dropout == pytest.approx(0.25)    # 1 - retain(0.75)
    assert d0.l2 == pytest.approx(5e-4)
    x = rs.randn(3, 4).astype(np.float32)
    oracle = _softmax(np.maximum(x @ W1 + b1, 0) @ W2 + b2)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-5, atol=1e-6)


def test_legacy_nesterovs_enum():
    rs = np.random.RandomState(41)
    flat = np.concatenate([rs.randn(6).astype(np.float32),
                           rs.randn(2).astype(np.float32)])
    cj = _conf_json([
        ("output", {"activationFunction": "softmax", "nin": 3, "nout": 2,
                    "updater": "NESTEROVS", "learningRate": 0.02,
                    "momentum": 0.85,
                    "lossFunction": "MCXENT"}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    from deeplearning4j_tpu.nn.updaters import Nesterovs
    assert isinstance(net.conf.updater, Nesterovs)
    assert net.conf.updater.momentum == pytest.approx(0.85)


def test_bidirectional_lstm_import():
    """Bidirectional(LSTM) — BidirectionalParamInitializer layout
    [fwd flat | bwd flat]; the backward half runs on the time-reversed
    sequence and is flipped back (CONCAT mode)."""
    rs = np.random.RandomState(50)
    nin, H, T, B = 3, 4, 5, 2

    def lstm_params():
        return (rs.randn(nin, 4 * H).astype(np.float32),
                rs.randn(H, 4 * H).astype(np.float32),
                rs.randn(4 * H).astype(np.float32))

    Wf, Rf, bf = lstm_params()
    Wb, Rb, bb = lstm_params()
    Wo = rs.randn(2 * H, 2).astype(np.float32)
    bo = rs.randn(2).astype(np.float32)
    inner = lambda W, R, b: np.concatenate(
        [W.ravel(order="F"), R.ravel(order="F"), b])
    flat = np.concatenate([inner(Wf, Rf, bf), inner(Wb, Rb, bb),
                           Wo.ravel(order="F"), bo])
    lstm_body = {"activationFn": _act("TanH"), "nin": nin, "nout": H,
                 "gateActivationFn": _act("Sigmoid"),
                 "forgetGateBiasInit": 1.0}
    cj = _conf_json([
        ("Bidirectional", {"mode": "CONCAT",
                           "fwd": {"LSTM": dict(lstm_body)},
                           "bwd": {"LSTM": dict(lstm_body)}}),
        ("rnnoutput", {"activationFn": _act("Softmax"), "nin": 2 * H,
                       "nout": 2,
                       "lossFn": {"@class":
                                  "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(
        _zip_bytes(cj, flat), input_type=InputType.recurrent(nin, T))
    x = rs.randn(B, T, nin).astype(np.float32)
    hf = _lstm_oracle_ifog(x, Wf, Rf, bf, H)
    hb = _lstm_oracle_ifog(x[:, ::-1], Wb, Rb, bb, H)[:, ::-1]
    hs = np.concatenate([hf, hb], -1)
    oracle = _softmax(hs @ Wo + bo)
    np.testing.assert_allclose(np.asarray(net.output(x)), oracle,
                               rtol=1e-4, atol=1e-5)


def test_bidirectional_updater_state_grafts():
    rs = np.random.RandomState(51)
    nin, H = 2, 3
    inner_n = nin * 4 * H + H * 4 * H + 4 * H
    n = 2 * inner_n + (2 * H) * 2 + 2
    flat = rs.randn(n).astype(np.float32)
    m = rs.randn(n).astype(np.float32)
    v = np.abs(rs.randn(n)).astype(np.float32)
    lstm_body = {"activationFn": _act("TanH"), "nin": nin, "nout": H,
                 "gateActivationFn": _act("Sigmoid")}
    cj = _conf_json([
        ("Bidirectional", {"mode": "CONCAT",
                           "fwd": {"LSTM": dict(lstm_body)},
                           "bwd": {"LSTM": dict(lstm_body)}}),
        ("rnnoutput", {"activationFn": _act("Softmax"), "nin": 2 * H,
                       "nout": 2,
                       "lossFn": {"@class":
                                  "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"}}),
    ])
    net = restore_multilayer_network(
        _zip_bytes(cj, flat, updater=np.concatenate([m, v])),
        input_type=InputType.recurrent(nin, 4))
    import optax
    adam = [s for s in net.opt_state
            if isinstance(s, optax.ScaleByAdamState)][0]
    # fwd W occupies the first nin*4H slots of m (f-order, ifog->ifgo)
    from deeplearning4j_tpu.modelimport.dl4j import _ifog_to_ifgo
    exp = _ifog_to_ifgo(m[:nin * 4 * H].reshape((nin, 4 * H), order="F"),
                        H, 1)
    np.testing.assert_allclose(np.asarray(adam.mu["0"]["fwd"]["W"]), exp,
                               rtol=1e-6)
    # bwd b is the tail of the first bidirectional block
    exp_b = _ifog_to_ifgo(m[2 * inner_n - 4 * H:2 * inner_n], H, 0)
    np.testing.assert_allclose(np.asarray(adam.mu["0"]["bwd"]["b"]),
                               exp_b, rtol=1e-6)


def test_bidirectional_export_roundtrip(tmp_path):
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        Bidirectional, LSTM, RnnOutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(9).updater(Adam(1e-3))
            .list()
            .layer(Bidirectional(layer=LSTM(n_out=4, activation="tanh"),
                                 mode="concat"))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(3, 5))
            .build())
    net = MultiLayerNetwork(conf).init()
    p = tmp_path / "bidi.zip"
    save_dl4j_model(net, p, save_updater=True)
    net2 = restore_multilayer_network(
        p, input_type=InputType.recurrent(3, 5))
    rs = np.random.RandomState(0)
    x = rs.randn(2, 5, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)),
                               rtol=1e-5, atol=1e-6)


def test_legacy_lr_zero_and_unknown_enum():
    rs = np.random.RandomState(60)
    flat = np.concatenate([rs.randn(6).astype(np.float32),
                           rs.randn(2).astype(np.float32)])
    cj = _conf_json([
        ("output", {"activationFunction": "softmax", "nin": 3, "nout": 2,
                    "updater": "SGD", "learningRate": 0.0,
                    "lossFunction": "MCXENT"}),
    ])
    net = restore_multilayer_network(_zip_bytes(cj, flat))
    assert net.conf.updater.learning_rate == 0.0       # explicit 0 survives
    # unknown enum: warn + default updater, model still loads
    cj2 = _conf_json([
        ("output", {"activationFunction": "softmax", "nin": 3, "nout": 2,
                    "updater": "CUSTOM", "lossFunction": "MCXENT"}),
    ])
    net2 = restore_multilayer_network(_zip_bytes(cj2, flat))
    x = rs.randn(2, 3).astype(np.float32)
    assert np.asarray(net2.output(x)).shape == (2, 2)


def test_bidirectional_average_mode_maps():
    from deeplearning4j_tpu.modelimport.dl4j import _parse_layer
    out = _parse_layer("Bidirectional", {
        "mode": "AVERAGE",
        "fwd": {"LSTM": {"activationFn": _act("TanH"), "nin": 2,
                         "nout": 3}}})
    assert out[0].mode == "ave"


def test_export_preserves_parameter_free_layer_config(tmp_path):
    """GlobalPooling/ZeroPadding/Upsampling config must survive export ->
    import (a trained avg-pooling net must not come back max-pooling)."""
    import dataclasses as _dc

    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer, Upsampling2D,
        ZeroPaddingLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder().seed(4).list()
            .layer(ZeroPaddingLayer(padding=(1, 2, 1, 2)))
            .layer(ConvolutionLayer(n_out=2, kernel=(3, 3),
                                    convolution_mode="same"))
            .layer(Upsampling2D(size=(2, 2)))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(5, 5, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    p = tmp_path / "pfree.zip"
    save_dl4j_model(net, p, save_updater=False)
    net2 = restore_multilayer_network(
        p, input_type=InputType.convolutional(5, 5, 1))
    by_type = {type(l).__name__: l for l in net2.layers}
    assert by_type["GlobalPoolingLayer"].pooling_type == "avg"
    assert by_type["ZeroPaddingLayer"].padding == (1, 2, 1, 2)
    assert by_type["Upsampling2D"].size == (2, 2)
    rs = np.random.RandomState(0)
    x = rs.rand(2, 5, 5, 1).astype(np.float32)
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(net2.output(x)),
                               rtol=1e-5, atol=1e-6)
