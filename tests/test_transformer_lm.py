"""TransformerLM coverage (VERDICT §2.8 gap): fit smoke + the paged-KV
greedy-decode parity proof.

The parity contract the decode runtime (serving/decode.py) ships under:

- PREFILL logits are BITWISE-equal to full-sequence recompute (the same
  primitive calls as the stock layers, padding masked out exactly);
- each DECODE step's logits match full-sequence recompute to within a few
  float32 ulp (XLA picks a different matmul reduction strategy for
  1-token queries than for full sequences — same math, different
  rounding order), and the GREEDY TOKEN SEQUENCE is exactly equal — the
  product-level guarantee that the paged cache never changes what the
  model says.
"""
import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.serving.decode import DecodeConfig, DecodeEngine


@pytest.fixture(scope="module")
def tiny_lm():
    net = TransformerLM(vocab_size=32, seq_length=32, n_layers=2,
                        n_embd=32, n_heads=4, learning_rate=3e-3,
                        seed=11).init()
    return net


def test_transformer_lm_fit_smoke(tiny_lm):
    """A few steps of next-token training must run and reduce the loss
    (the quick-gate sibling of the slow test_transformer_lm_trains)."""
    rs = np.random.RandomState(0)
    x = rs.randint(0, 32, (16, 32)).astype("float32")
    y = np.eye(32, dtype="float32")[(x.astype(int) + 1) % 32]
    losses = []
    for _ in range(6):
        tiny_lm.fit((x, y), epochs=1, batch_size=8)
        losses.append(tiny_lm.score())
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


@pytest.fixture(scope="module")
def engine(tiny_lm):
    eng = DecodeEngine(tiny_lm,
                       DecodeConfig(slots=2, page_size=8, seed=3),
                       name="parity-lm")
    eng.warm()
    return eng


def test_prefill_logits_bitwise_equal_full_recompute(tiny_lm, engine):
    """Bucket-padded prefill == unpadded full recompute, bit for bit, and
    both == the model's own output() (post-softmax)."""
    prompt = np.array([3, 7, 1, 9, 4], np.int32)      # pads 5 -> bucket 8
    slot = engine.cache.admit(len(prompt))
    try:
        tok, logits = engine.prefill(slot, prompt, 0.0, 0)
        full = engine.logits_full(prompt[None])[0, len(prompt) - 1]
        assert np.array_equal(logits, full)
        # versus the MODEL's forward: softmax(engine logits) must equal
        # net.output()'s probabilities bitwise
        probs = np.asarray(jax.nn.softmax(logits))
        ref = np.asarray(tiny_lm.output(
            prompt[None].astype("float32")))[0, len(prompt) - 1]
        assert np.array_equal(probs, ref)
        assert tok == int(np.argmax(full))
    finally:
        engine.cache.release(slot)


def test_greedy_decode_parity_with_full_recompute(tiny_lm, engine):
    """24 greedy tokens through the paged-KV incremental forward produce
    the exact token sequence of per-step full recompute, with per-step
    logits equal to a few float32 ulp."""
    prompt = np.array([5, 2, 8, 1], np.int32)
    slot = engine.cache.admit(len(prompt))
    try:
        tok, _ = engine.prefill(slot, prompt, 0.0, 0)
        seq = list(prompt) + [tok]
        for _ in range(24):
            toks, act, logits = engine.step()
            assert act[slot]
            full = engine.logits_full(np.array([seq], np.int32))[0, -1]
            np.testing.assert_allclose(logits[slot], full, rtol=0,
                                       atol=2e-5)
            # the product-level contract: greedy tokens NEVER diverge —
            # against the engine oracle and against the model itself
            assert int(toks[slot]) == int(np.argmax(full))
            ref = np.asarray(tiny_lm.output(
                np.array([seq], "float32")))[0, -1]
            assert int(toks[slot]) == int(np.argmax(ref))
            seq.append(int(toks[slot]))
    finally:
        engine.cache.release(slot)


def test_decode_crosses_page_boundaries(engine):
    """Generation that spans several 8-token pages keeps appending into
    freshly allocated pages (the on-demand allocator engages)."""
    prompt = np.array([1, 2, 3, 4, 5, 6, 7], np.int32)   # page 0 almost full
    slot = engine.cache.admit(len(prompt))
    try:
        pages_before = engine.cache.describe()["pages_used"]
        engine.prefill(slot, prompt, 0.0, 0)
        for _ in range(10):                              # crosses 8 and 16
            _, act, _ = engine.step()
            assert act[slot]
        assert engine.cache.describe()["pages_used"] > pages_before
        assert int(engine.cache.seq_lens[slot]) == len(prompt) + 10
    finally:
        engine.cache.release(slot)
    assert engine.cache.describe()["pages_used"] == 0
