"""SLO engine tests — monitor/timeseries.py + monitor/slo.py and their
serving endpoints.

The windowed math (rates, percentiles, counter resets) is validated
against a numpy oracle on a fake clock; the alert state machine
(pending -> firing -> resolved, flap suppression, multi-window
AND-gating) is driven entirely by injected time — no sleeps, no
sampler threads. Endpoint tests cover /v1/slo (including the router's
fleet aggregation) and the opt-in OpenMetrics exposition.
"""
import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import slo as slo_mod
from deeplearning4j_tpu.monitor import timeseries as ts_mod
from deeplearning4j_tpu.monitor.metrics import MetricsRegistry
from deeplearning4j_tpu.monitor.slo import (
    DEFAULT_RULES, BurnRule, Objective, SLOEngine, _Alert,
)
from deeplearning4j_tpu.monitor.timeseries import TimeSeriesRing


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Fresh global registry and no default ring/engine around every
    test (the engine exports slo_* gauges into the global registry)."""
    slo_mod.disable_slo()
    ts_mod.disable_timeseries()
    monitor.REGISTRY.reset()
    yield
    slo_mod.disable_slo()
    ts_mod.disable_timeseries()
    monitor.REGISTRY.reset()


def _ring(reg=None, clock=None, **kw):
    clock = clock or FakeClock()
    reg = reg or MetricsRegistry()
    return reg, clock, TimeSeriesRing(registry=reg, time_fn=clock,
                                      wall_fn=clock, **kw)


# --------------------------------------------------- windowed counter math
def test_counter_rate_matches_numpy_oracle_on_fake_clock():
    reg, clock, ring = _ring()
    c = reg.counter("reqs_total", "r", labels=("code",))
    rs = np.random.RandomState(0)
    increments = rs.poisson(5, size=60).astype(float)
    for inc in increments:
        c.inc(inc, code="200")
        clock.advance(1.0)
        ring.sample()
    # samples at t = 1001..1060; a 30 s window spans [1030, 1060] — the
    # t=1030 sample is the baseline, so the oracle is increments[30:]
    oracle = increments[30:].sum()
    assert ring.increase("reqs_total", 30.0) == pytest.approx(oracle)
    assert ring.rate("reqs_total", 30.0) == pytest.approx(oracle / 30.0)
    # full-history window: the first sample is the baseline
    assert ring.increase("reqs_total", 1e9) == pytest.approx(
        increments[1:].sum())


def test_counter_reset_across_restart_counts_post_reset_value():
    reg, clock, ring = _ring()
    reg.counter("reqs_total", "r").inc(100.0)
    clock.advance(1.0)
    ring.sample()
    reg.counter("reqs_total", "r").inc(50.0)     # 150 cumulative
    clock.advance(1.0)
    ring.sample()
    # replica restart: the counter starts over at 0 and climbs to 7
    reg.reset()
    reg.counter("reqs_total", "r").inc(7.0)
    clock.advance(1.0)
    ring.sample()
    # prometheus increase() semantics: 50 before the reset, then the
    # post-reset value in full — never a negative delta
    assert ring.increase("reqs_total", 60.0) == pytest.approx(57.0)


def test_increase_by_groups_one_label():
    reg, clock, ring = _ring()
    c = reg.counter("reqs_total", "r", labels=("code", "model"))
    for code in ("200", "500", "429"):
        c.inc(0, code=code, model="m")
    ring.sample()
    for code, n in (("200", 30), ("500", 7), ("429", 3)):
        c.inc(n, code=code, model="m")
    c.inc(9, code="503", model="m")   # series born after the baseline
    clock.advance(5.0)
    ring.sample()
    by = ring.increase_by("reqs_total", 60.0, "code")
    # a series first seen mid-window is its own baseline: its initial
    # value is not an increase (prometheus-style birth semantics)
    assert by == {"200": 30.0, "500": 7.0, "429": 3.0, "503": 0.0}
    # label pinning filters children
    assert ring.increase_by("reqs_total", 60.0, "code", model="other") == {}


def test_unknown_series_and_thin_windows_return_none():
    reg, clock, ring = _ring()
    reg.counter("reqs_total", "r").inc()
    ring.sample()
    assert ring.increase("nope_total", 60.0) is None
    assert ring.rate("reqs_total", 60.0) is None        # one sample only
    clock.advance(100.0)
    ring.sample()
    assert ring.increase("reqs_total", 10.0) is None    # window too short


# ------------------------------------------------- windowed histogram math
def test_hist_window_deltas_match_numpy_histogram():
    bounds = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
    reg, clock, ring = _ring()
    h = reg.histogram("lat_seconds", "l", buckets=bounds)
    rs = np.random.RandomState(1)
    per_second = []
    for _ in range(40):
        obs = rs.gamma(2.0, 0.03, size=8)
        for v in obs:
            h.observe(float(v))
        per_second.append(obs)
        clock.advance(1.0)
        ring.sample()
    win = ring.hist_window("lat_seconds", 20.0)
    # window [t_end-20, t_end]: baseline sample is t-20, so the
    # windowed observations are the last 20 seconds' worth
    windowed = np.concatenate(per_second[-20:])
    edges = [0.0] + list(bounds) + [np.inf]
    oracle_counts, _ = np.histogram(windowed, bins=edges)
    assert win["count"] == pytest.approx(len(windowed))
    assert win["sum"] == pytest.approx(windowed.sum(), rel=1e-6)
    assert np.allclose(win["counts"], oracle_counts)
    # interpolated percentile lands within the oracle quantile's bucket
    for q in (50, 95, 99):
        est = ring.percentile("lat_seconds", 20.0, q)
        oracle = float(np.percentile(windowed, q))
        edge_idx = int(np.searchsorted(bounds, oracle))
        lo = 0.0 if edge_idx == 0 else bounds[edge_idx - 1]
        hi = bounds[edge_idx] if edge_idx < len(bounds) else float("inf")
        assert lo <= est <= hi, (q, est, oracle)
    # fraction_le against the oracle share, within one bucket's mass
    thr = 0.1
    est = ring.fraction_le("lat_seconds", 20.0, thr)
    oracle_frac = float((windowed <= thr).mean())
    bucket_mass = oracle_counts[list(bounds).index(thr) + 1] / len(windowed)
    assert abs(est - oracle_frac) <= bucket_mass + 1e-9


def test_hist_reset_uses_post_reset_counts():
    bounds = (0.1, 1.0)
    reg, clock, ring = _ring()
    h = reg.histogram("lat_seconds", "l", buckets=bounds)
    for _ in range(10):
        h.observe(0.05)
    clock.advance(1.0)
    ring.sample()
    reg.reset()                                  # replica restart
    h = reg.histogram("lat_seconds", "l", buckets=bounds)
    for _ in range(4):
        h.observe(0.5)
    clock.advance(1.0)
    ring.sample()
    win = ring.hist_window("lat_seconds", 60.0)
    assert win["count"] == pytest.approx(4)      # post-reset only
    assert ring.percentile("lat_seconds", 60.0, 50) == pytest.approx(
        0.1 + 0.9 / 2)


def test_gauge_stats_over_window():
    reg, clock, ring = _ring()
    g = reg.gauge("depth", "d")
    for v in (1.0, 5.0, 3.0):
        g.set(v)
        clock.advance(1.0)
        ring.sample()
    stats = ring.gauge_stats("depth", 60.0)
    assert stats == {"last": 3.0, "min": 1.0, "max": 5.0,
                     "avg": 3.0, "samples": 3}


# ------------------------------------------------------ alert state machine
def _alert(for_s=0.0, keep_firing_s=60.0, burn_threshold=2.0):
    obj = Objective("o", "availability", "reqs_total", 0.9)
    rule = BurnRule("page", 3600.0, 300.0, burn_threshold, for_s=for_s,
                    keep_firing_s=keep_firing_s)
    return _Alert(obj, rule)


def test_alert_fires_immediately_without_for_hold():
    a = _alert(for_s=0.0)
    assert a.update(0.0, 5.0, 5.0) == "fired"
    assert a.describe()["state"] == "firing"


def test_alert_pending_waits_out_for_s_then_fires():
    a = _alert(for_s=30.0)
    assert a.update(0.0, 5.0, 5.0) is None
    assert a.describe()["state"] == "pending"
    assert a.update(10.0, 5.0, 5.0) is None
    # a dip back under threshold cancels the pending alert entirely
    assert a.update(20.0, 1.0, 1.0) is None
    assert a.describe()["state"] == "inactive"
    # the hold restarts from scratch
    assert a.update(30.0, 5.0, 5.0) is None
    assert a.update(59.0, 5.0, 5.0) is None
    assert a.update(60.0, 5.0, 5.0) == "fired"


def test_alert_multi_window_and_gating():
    a = _alert()
    # long window burning but the short window already clean: the
    # incident is OVER — must not fire (and vice versa)
    assert a.update(0.0, 5.0, 1.0) is None
    assert a.update(1.0, 1.0, 5.0) is None
    assert a.describe()["state"] == "inactive"
    # a window with no evidence (None) can never satisfy the gate
    assert a.update(2.0, None, 5.0) is None
    assert a.update(3.0, 5.0, None) is None
    assert a.describe()["state"] == "inactive"


def test_alert_flap_suppression_and_resolution():
    a = _alert(keep_firing_s=30.0)
    assert a.update(0.0, 5.0, 5.0) == "fired"
    # brief dips must not resolve: clear for 10 s, burn again, clear...
    assert a.update(10.0, 1.0, 1.0) is None
    assert a.update(20.0, 5.0, 5.0) is None      # clear timer reset
    assert a.update(30.0, 1.0, 1.0) is None
    assert a.update(59.0, 1.0, 1.0) is None      # 29 s clear: still held
    assert a.describe()["state"] == "firing"
    assert a.update(60.0, 1.0, 1.0) == "resolved"
    assert a.describe()["state"] == "inactive"
    # machine is reusable after resolution
    assert a.update(70.0, 5.0, 5.0) == "fired"


# ------------------------------------------------------------------ engine
def _engine(objectives, rules, clock, ring, trips):
    return SLOEngine(ring, objectives, rules=rules, time_fn=clock,
                     wall_fn=clock,
                     trip_fn=lambda reason, **meta: trips.append(
                         (reason, meta)))


def test_engine_availability_fire_and_resolve_lifecycle():
    reg, clock, ring = _ring()
    c = reg.counter("reqs_total", "r", labels=("code",))
    rules = (BurnRule("page", 20.0, 5.0, 2.0, keep_firing_s=4.0),)
    trips = []
    eng = _engine([Objective("avail", "availability", "reqs_total", 0.9)],
                  rules, clock, ring, trips)
    c.inc(0, code="500")              # pre-seed so errors count in full

    def tick(ok, bad):
        c.inc(ok, code="200")
        if bad:
            c.inc(bad, code="500")
        clock.advance(1.0)
        ring.sample()
        eng.evaluate()

    for _ in range(10):
        tick(10, 0)                       # clean traffic: no alert
    assert eng.alert_state("avail", "page") == "inactive" and not trips
    for _ in range(10):
        tick(5, 5)                        # 50% errors -> burn 5x short
    assert eng.alert_state("avail", "page") == "firing"
    assert trips and trips[0][0] == "slo_availability_burn"
    assert trips[0][1]["severity"] == "page"
    assert trips[0][1]["burn_long"] >= 2.0
    # recovery: clean traffic ages the errors out of both windows, then
    # keep_firing_s holds the alert a little longer before resolving
    for _ in range(40):
        tick(10, 0)
    assert eng.alert_state("avail", "page") == "inactive"
    events = [h["event"] for h in eng.history()]
    assert events == ["fired", "resolved"]
    assert len(trips) == 1                # resolution never re-trips
    # verdict reflects the quiet state and carries the history
    v = eng.verdict()
    assert v["state"] == "ok" and v["enabled"]
    assert v["objectives"][0]["ratio"] == pytest.approx(1.0)


def test_engine_no_traffic_means_no_verdict_and_no_alert():
    reg, clock, ring = _ring()
    reg.counter("reqs_total", "r", labels=("code",))
    trips = []
    eng = _engine([Objective("avail", "availability", "reqs_total", 0.9)],
                  (BurnRule("page", 20.0, 5.0, 1.0),), clock, ring, trips)
    for _ in range(10):                   # samples, but zero increments
        clock.advance(1.0)
        ring.sample()
        eng.evaluate()
    assert eng.alert_state("avail", "page") == "inactive" and not trips
    assert eng.verdict()["objectives"][0]["ratio"] is None


def test_engine_latency_objective_preserves_p99_breach_reason():
    reg, clock, ring = _ring()
    h = reg.histogram("serving_router_request_seconds", "lat",
                      buckets=(0.05, 0.1, 0.5, 1.0))
    trips = []
    objectives = slo_mod.router_objectives(slo_p99_ms=100.0)
    assert [o.name for o in objectives] == ["router_latency_p99"]
    eng = _engine(objectives, (BurnRule("page", 20.0, 5.0, 2.0),),
                  clock, ring, trips)
    for _ in range(10):
        # 90% fast, 10% slow: 10x the 1% budget on both windows
        for _ in range(9):
            h.observe(0.01)
        h.observe(0.4)
        clock.advance(1.0)
        ring.sample()
        eng.evaluate()
    assert eng.alert_state("router_latency_p99", "page") == "firing"
    assert trips[0][0] == "p99_breach"


def test_engine_exports_slo_metric_families():
    reg, clock, ring = _ring(reg=monitor.REGISTRY)
    c = monitor.counter("reqs_total", "r", labels=("code",))
    eng = _engine([Objective("avail", "availability", "reqs_total", 0.9)],
                  (BurnRule("page", 20.0, 5.0, 2.0),), clock, ring, [])
    c.inc(1, code="200")
    c.inc(1, code="500")
    ring.sample()
    c.inc(5, code="200")
    c.inc(5, code="500")
    clock.advance(1.0)
    ring.sample()
    eng.evaluate()
    text = monitor.prometheus_text()
    for family in ("timeseries_samples_total", "timeseries_series",
                   "timeseries_sample_seconds", "slo_burn_rate",
                   "slo_alert_state", "slo_objective_ratio",
                   "slo_alerts_total"):
        assert family in text, family
    assert monitor.gauge("slo_alert_state",
                         labels=("objective", "severity")).value(
        objective="avail", severity="page") == 2.0


def test_default_rules_are_the_sre_workbook_pair():
    (fast, slow) = DEFAULT_RULES
    assert (fast.long_window_s, fast.short_window_s,
            fast.burn_threshold) == (3600.0, 300.0, 14.4)
    assert (slow.long_window_s, slow.short_window_s,
            slow.burn_threshold) == (21600.0, 1800.0, 6.0)


# --------------------------------------------------------- zero-cost seam
def test_timeseries_disabled_by_default_and_lifecycle():
    assert not ts_mod.timeseries_enabled()
    assert ts_mod.default_ring() is None
    assert not any(t.name == "timeseries-sampler"
                   for t in threading.enumerate())
    ring = ts_mod.enable_timeseries(interval_s=60.0)
    assert ts_mod.timeseries_enabled()
    assert ts_mod.enable_timeseries() is ring          # idempotent
    assert any(t.name == "timeseries-sampler"
               for t in threading.enumerate())
    ts_mod.disable_timeseries()
    assert ts_mod.default_ring() is None
    assert not any(t.name == "timeseries-sampler"
                   for t in threading.enumerate())


def test_enable_slo_requires_a_ring():
    with pytest.raises(RuntimeError):
        slo_mod.enable_slo([Objective("a", "availability", "x_total", 0.9)])


# ---------------------------------------------------- OpenMetrics satellite
def test_openmetrics_exemplars_and_eof_default_stays_v004():
    reg = MetricsRegistry()
    reg.counter("hits_total", "hits", labels=("code",)).inc(3, code="200")
    h = reg.histogram("lat_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="00aa11bb")
    h.observe(0.5)
    before = reg.prometheus_text()
    om = reg.openmetrics_text()
    # the default exposition is untouched by OpenMetrics rendering
    assert reg.prometheus_text() == before
    assert "#" not in before.replace("# HELP", "").replace("# TYPE", "")
    # counter family name drops _total on HELP/TYPE, samples keep it
    assert "# TYPE hits counter" in om
    assert 'hits_total{code="200"} 3' in om
    # exemplar on the landing bucket, OpenMetrics syntax
    assert 'lat_seconds_bucket{le="0.1"} 1 # {trace_id="00aa11bb"} 0.05' \
        in om
    assert 'lat_seconds_bucket{le="1"} 2\n' in om      # no exemplar here
    assert om.endswith("# EOF\n")


# ---------------------------------------------------------- HTTP endpoints
def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def test_model_server_slo_and_timeseries_endpoints():
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import ModelServer

    clock = FakeClock()
    ring = TimeSeriesRing(registry=monitor.REGISTRY, time_fn=clock,
                          wall_fn=clock)
    c = monitor.counter("reqs_total", "r", labels=("code",))
    c.inc(0, code="200")
    ring.sample()
    c.inc(8, code="200")
    clock.advance(5.0)
    ring.sample()
    eng = SLOEngine(ring, [Objective("avail", "availability",
                                     "reqs_total", 0.9)],
                    rules=(BurnRule("page", 60.0, 10.0, 2.0),),
                    time_fn=clock, wall_fn=clock,
                    trip_fn=lambda *a, **k: None)
    server = ModelServer(ModelRegistry(), port=0, slo_engine=eng,
                         timeseries_ring=ring)
    try:
        doc = _get_json(server.url + "/v1/slo")
        assert doc["enabled"] and doc["state"] == "ok"
        assert doc["objectives"][0]["name"] == "avail"
        listing = _get_json(server.url + "/v1/timeseries")
        assert listing["enabled"] and "reqs_total" in listing["series"]
        q = _get_json(server.url
                      + "/v1/timeseries?series=reqs_total&window=60")
        assert q["kind"] == "counter" and q["increase"] == 8.0
        q2 = _get_json(server.url + "/v1/timeseries?series=nope&window=60")
        assert q2.get("error") == "unknown series"
    finally:
        server.drain(timeout=5.0)


def test_model_server_slo_disabled_answers_enabled_false():
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import ModelServer

    server = ModelServer(ModelRegistry(), port=0)
    try:
        assert _get_json(server.url + "/v1/slo") == {"enabled": False}
        assert _get_json(server.url + "/v1/timeseries") == \
            {"enabled": False}
    finally:
        server.drain(timeout=5.0)


def test_router_slo_fleet_aggregation_worst_state_wins():
    from deeplearning4j_tpu.serving.fleet import Replica
    from deeplearning4j_tpu.serving.router import (
        ResilientRouter, RouterServer,
    )

    verdicts = {
        "r0": {"enabled": True, "state": "firing", "objectives": [
            {"name": "avail", "alerts": [
                {"severity": "page", "state": "firing"}]}]},
        "r1": {"enabled": True, "state": "ok", "objectives": []},
    }

    def transport(replica, path, body, headers, timeout):
        assert path == "/v1/slo"
        return 200, {}, json.dumps(verdicts[replica.name]).encode()

    reps = []
    for i in range(2):
        r = Replica(f"r{i}")
        r.state = "ready"
        r.url = f"http://fake-{i}"
        reps.append(r)
    router = ResilientRouter(lambda: reps, transport=transport,
                             hedge=False)
    server = RouterServer(router, port=0)
    try:
        doc = _get_json(server.url + "/v1/slo")
        assert doc["router"] == {"enabled": False}     # no router engine
        assert doc["fleet"]["state"] == "firing"
        assert doc["fleet"]["reporting"] == 2
        assert doc["fleet"]["firing"] == ["r0:avail:page"]
        assert doc["fleet"]["unreachable"] == []
    finally:
        server.stop()


def test_router_slo_marks_unreachable_replicas():
    from deeplearning4j_tpu.serving.fleet import Replica
    from deeplearning4j_tpu.serving.router import (
        ReplicaTransportError, ResilientRouter, RouterServer,
    )

    def transport(replica, path, body, headers, timeout):
        raise ReplicaTransportError("connection refused")

    r = Replica("r0")
    r.state = "ready"
    r.url = "http://fake-0"
    router = ResilientRouter(lambda: [r], transport=transport, hedge=False)
    server = RouterServer(router, port=0)
    try:
        doc = _get_json(server.url + "/v1/slo")
        assert doc["fleet"]["state"] == "ok"
        assert doc["fleet"]["reporting"] == 0
        assert doc["fleet"]["unreachable"] == ["r0"]
    finally:
        server.stop()
