"""Device-side normalization (device_affine seam).

TPU-first data path: when an iterator's pre-processor is an affine map,
fit() ships RAW features over the host->HBM link (uint8 pixels stay
uint8 — 4x fewer bytes than float32) and normalizes on device inside a
jit, instead of the reference's host-side float preprocessing
(ND4J ImagePreProcessingScaler.preProcess / NormalizerStandardize).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
from deeplearning4j_tpu.data.normalization import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize,
    VGG16ImagePreProcessor, engage_device_affine,
)


def _affine_matches_transform(pp, x):
    shift, scale = pp.device_affine()
    np.testing.assert_allclose(pp.transform(x),
                               x.astype(np.float32) * scale + shift,
                               rtol=1e-5, atol=1e-5)


class TestDeviceAffine:
    def test_image_scaler(self):
        x = np.random.RandomState(0).randint(
            0, 256, (4, 8, 8, 3)).astype(np.uint8)
        _affine_matches_transform(ImagePreProcessingScaler(), x)
        _affine_matches_transform(ImagePreProcessingScaler(-1, 1), x)

    def test_vgg16(self):
        x = np.random.RandomState(1).randint(
            0, 256, (2, 8, 8, 3)).astype(np.uint8)
        _affine_matches_transform(VGG16ImagePreProcessor(), x)

    def test_minmax_fitted(self):
        rs = np.random.RandomState(2)
        x = rs.rand(32, 5).astype(np.float32) * 7 - 3
        pp = NormalizerMinMaxScaler(0, 1)
        assert pp.device_affine() is None     # unfitted
        pp.fit(DataSet(x, x[:, :1]))
        _affine_matches_transform(pp, x)

    def test_standardize_features_only(self):
        rs = np.random.RandomState(3)
        x = rs.randn(64, 4).astype(np.float32) * 3 + 1
        pp = NormalizerStandardize()
        pp.fit(DataSet(x, x[:, :1]))
        _affine_matches_transform(pp, x)

    def test_standardize_with_labels_has_no_affine(self):
        rs = np.random.RandomState(4)
        x = rs.randn(16, 4).astype(np.float32)
        pp = NormalizerStandardize(fit_labels=True)
        pp.fit(DataSet(x, x[:, :2]))
        assert pp.device_affine() is None

    def test_engage_detaches_and_walks_wrapper_chain(self):
        from deeplearning4j_tpu.data.async_iterator import (
            AsyncDataSetIterator)
        x = np.zeros((8, 4), np.uint8)
        y = np.eye(2, dtype=np.float32)[np.zeros(8, int)]
        it = ArrayDataSetIterator(x, y, batch_size=4)
        it.set_pre_processor(ImagePreProcessingScaler())
        wrapped = AsyncDataSetIterator(it, device_put=False)
        owner, pp, aff = engage_device_affine(wrapped)
        try:
            assert owner is it and isinstance(pp, ImagePreProcessingScaler)
            assert aff is not None
            # host application skipped: raw uint8 flows out
            ds = next(iter(it))
            assert ds.features.dtype == np.uint8
        finally:
            owner.pre_processor = pp
        ds = next(iter(it))
        assert ds.features.dtype == np.float32   # restored

    def test_engage_none_for_plain_iterator(self):
        it = ArrayDataSetIterator(np.zeros((4, 2), np.float32),
                                  np.zeros((4, 2), np.float32),
                                  batch_size=2)
        assert engage_device_affine(it) == (None, None, None)

    def test_context_skips_for_model_reading_listener(self):
        # an EvaluativeListener-style listener evaluates THROUGH the same
        # iterator mid-fit: with the pre-processor detached it would see
        # raw features, so engagement must be skipped entirely
        from deeplearning4j_tpu.data.normalization import (
            engaged_device_affine)
        it = ArrayDataSetIterator(np.zeros((4, 2), np.uint8),
                                  np.zeros((4, 2), np.float32),
                                  batch_size=2)
        pp = ImagePreProcessingScaler()
        it.set_pre_processor(pp)

        class Reader:
            reads_model = True

        with engaged_device_affine(it, [Reader()]) as aff:
            assert aff is None
            assert it.pre_processor is pp      # never detached

    def test_context_pauses_user_async_feature_cast(self):
        # a user-constructed AsyncDataSetIterator(cast_dtype=bf16) would
        # bf16-quantize RAW features before the device affine; the
        # engagement pauses its feature cast and restores it after
        import jax.numpy as jnp
        from deeplearning4j_tpu.data.async_iterator import (
            AsyncDataSetIterator)
        from deeplearning4j_tpu.data.normalization import (
            engaged_device_affine)
        inner = ArrayDataSetIterator(np.zeros((4, 2), np.float32),
                                     np.zeros((4, 2), np.float32),
                                     batch_size=2)
        inner.set_pre_processor(ImagePreProcessingScaler())
        wrapped = AsyncDataSetIterator(inner, device_put=False,
                                       cast_dtype=jnp.bfloat16)
        assert wrapped._cast_features
        with engaged_device_affine(wrapped) as aff:
            assert aff is not None
            assert wrapped._cast_features is False
            assert inner.pre_processor is None
        assert wrapped._cast_features is True
        assert inner.pre_processor is not None


def _make_net(seed=11):
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _uint8_data(n=48):
    rs = np.random.RandomState(7)
    x = rs.randint(0, 256, (n, 6)).astype(np.uint8)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


class TestFitWithDeviceNorm:
    @pytest.mark.parametrize("scan_steps", [1, 2])
    def test_matches_host_normalization(self, monkeypatch, scan_steps):
        x, y = _uint8_data()

        def run(device_norm):
            monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", device_norm)
            it = ArrayDataSetIterator(x, y, batch_size=12)
            it.set_pre_processor(ImagePreProcessingScaler())
            net = _make_net()
            net.fit(it, epochs=2, scan_steps=scan_steps)
            assert it.pre_processor is not None    # restored after fit
            return net

        a = run("1")
        b = run("0")
        import jax
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=2e-4, atol=2e-5)

    def test_graph_fit_device_norm_matches(self, monkeypatch):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.network import GraphBuilder
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam
        x, y = _uint8_data()

        def run(device_norm):
            monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", device_norm)
            g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(5)
                              .updater(Adam(1e-2)))
                 .add_inputs("in")
                 .set_input_types(InputType.feed_forward(6)))
            g.add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
            g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "d")
            g.set_outputs("out")
            net = ComputationGraph(g.build()).init()
            it = ArrayDataSetIterator(x, y, batch_size=12)
            it.set_pre_processor(ImagePreProcessingScaler())
            net.fit(it, epochs=2)
            assert it.pre_processor is not None
            return net

        import jax
        a, b = run("1"), run("0")
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("mode", ["averaging", "sync"])
    def test_parallel_wrapper_device_norm_matches(self, monkeypatch, mode):
        from deeplearning4j_tpu.parallel import (
            MeshConfig, ParallelWrapper, TrainingMode, build_mesh)
        x, y = _uint8_data()
        tm = (TrainingMode.AVERAGING if mode == "averaging"
              else TrainingMode.SYNC_GRADIENTS)

        def run(device_norm):
            monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", device_norm)
            it = ArrayDataSetIterator(x, y, batch_size=24)
            pp = ImagePreProcessingScaler()
            it.set_pre_processor(pp)
            net = _make_net()
            w = ParallelWrapper(net, mesh=build_mesh(MeshConfig()),
                                mode=tm, averaging_frequency=2)
            w.fit(it, epochs=2)
            assert it.pre_processor is pp       # restored after fit
            return net

        import jax
        a, b = run("1"), run("0")
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=2e-4, atol=2e-5)

    def test_bf16_compute_normalizes_before_cast(self, monkeypatch):
        # features ~ N(1000, 1): the standardized signal lives in the
        # f32 bits a premature bf16 cast (ulp ~4 at 1000) would destroy.
        # Guards the normalize-then-cast ordering: the async wrap must
        # not host-cast RAW features when the device affine is engaged.
        monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", "1")
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        rs = np.random.RandomState(9)
        x = (1000.0 + rs.randn(96, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 4000.0).astype(int)]
        pp = NormalizerStandardize()
        pp.fit(DataSet(x, y))
        conf = (NeuralNetConfiguration.Builder().seed(2)
                .updater(Adam(5e-2)).compute_dtype("bfloat16").list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        it = ArrayDataSetIterator(x, y, batch_size=32)
        it.set_pre_processor(pp)
        net.fit(it, epochs=40)
        acc = net.evaluate(it).accuracy()
        # with the cast-before-normalize bug the standardized features
        # collapse to a few quantized values and this stays near chance
        assert acc > 0.9, acc

    def test_pre_processor_restored_on_error(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DEVICE_NORM", "1")
        x, y = _uint8_data(12)
        it = ArrayDataSetIterator(x, y, batch_size=12)
        pp = ImagePreProcessingScaler()
        it.set_pre_processor(pp)
        net = _make_net()

        class Boom(Exception):
            pass

        class BoomListener:
            def on_epoch_start(self, *a):
                raise Boom()

            def __getattr__(self, name):
                return lambda *a, **k: None

        net.set_listeners(BoomListener())
        with pytest.raises(Boom):
            net.fit(it, epochs=1)
        assert it.pre_processor is pp
        assert net._input_affine is None
