"""Cross-process request tracing + flight recorder (PR 13).

Covers: W3C-traceparent context parse/mint/bind, automatic trace_id
attachment to spans, the zero-cost-when-disabled guard (the acceptance
contract: with tracing and the flight recorder off, the request path's
span sites allocate nothing), the flight recorder ring/trip lifecycle,
histogram trace_id exemplars, per-layer propagation (batcher, HTTP
server, decode scheduler, supervisor wedge postmortems), trace_report
merging, and — as the slow acceptance test — ONE trace_id spanning the
real CLI fleet (router + 2 subprocess replicas) merged into one valid
Perfetto document.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.monitor import trace as trace_mod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))
import trace_report  # noqa: E402  (tools/ is not a package)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with tracing + flight disabled and
    empty buffers — the library default other suites rely on."""
    monitor.disable_tracing()
    monitor.clear_trace()
    flight.disable_flight()
    flight.clear()
    yield
    monitor.disable_tracing()
    monitor.clear_trace()
    flight.disable_flight()
    flight.clear()


def _net(seed=0):
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------ the context
def test_traceparent_roundtrip():
    ctx = monitor.mint_context()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    parsed = monitor.parse_traceparent(ctx.header())
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    child = parsed.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-beef-01", "00-" + "g" * 32 + "-" +
    "a" * 16 + "-01", "00-" + "0" * 32 + "-" + "a" * 16 + "-01",
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",
    # int(x, 16) would accept these; strict hex must not
    "00-" + "a" * 29 + "_bb" + "-" + "b" * 16 + "-01",
    "00-" + "a" * 32 + "-" + " " + "b" * 15 + "-01",
])
def test_traceparent_rejects_malformed(bad):
    assert monitor.parse_traceparent(bad) is None


def test_span_attaches_bound_context():
    monitor.enable_tracing()
    ctx = monitor.mint_context()
    with monitor.bind_context(ctx):
        with monitor.span("a", k=1):
            pass
        assert monitor.current_context() is ctx
    assert monitor.current_context() is None
    with monitor.span("b"):          # outside any binding: no trace_id
        pass
    monitor.add_span("c", 0.0, 1.0, ctx=ctx)          # explicit override
    evs = {e["name"]: e for e in monitor.trace_events()}
    assert evs["a"]["args"]["trace_id"] == ctx.trace_id
    assert evs["a"]["args"]["k"] == 1
    assert "trace_id" not in evs["b"].get("args", {})
    assert evs["c"]["args"]["trace_id"] == ctx.trace_id


def test_disabled_request_path_allocates_nothing():
    """The acceptance guard: tracing + flight disabled means the span
    sites hand out the ONE shared null object, the ingress returns
    None, and nothing is recorded anywhere."""
    assert monitor.span("x", model="m") is monitor.span("y", n=3)
    assert flight.request_context("00-" + "a" * 32 + "-" + "b" * 16
                                  + "-01", "server") is None
    assert flight.begin(monitor.mint_context(), "predict") is None
    flight.note("deadbeef", "event")            # no-op, no error
    flight.finish(None, "ok")
    with monitor.bind_context(None):
        assert monitor.current_context() is None
    assert monitor.trace_events() == []
    assert flight.snapshot()["records"] == []


def test_request_context_minted_vs_adopted():
    flight.enable_flight()
    minted = flight.request_context(None, "router")
    assert minted is not None and minted.parent_id is None
    adopted = flight.request_context(minted.header(), "server")
    assert adopted.trace_id == minted.trace_id
    assert adopted.parent_id == minted.span_id
    # malformed header -> fresh mint, never a crash
    fresh = flight.request_context("not-a-header", "server")
    assert fresh is not None and fresh.parent_id is None


# ------------------------------------------------------- flight recorder
def test_flight_ring_and_multi_layer_notes(tmp_path):
    flight.enable_flight(capacity=4)
    ctx = monitor.mint_context()
    router_rec = flight.begin(ctx, "route", model="m", cls="batch")
    server_rec = flight.begin(ctx, "predict", model="m")
    # a note by context lands in EVERY open record of the request
    flight.note(ctx, "dispatch", wait_ms=1.5)
    flight.finish(server_rec, "ok", code=200)
    flight.finish(router_rec, "ok", code=200)
    snap = flight.snapshot()
    assert len(snap["records"]) == 2
    for rec in snap["records"]:
        assert rec["trace_id"] == ctx.trace_id
        assert rec["events"][0]["event"] == "dispatch"
        assert rec["outcome"] == "ok" and rec["duration_ms"] >= 0
    # the ring is bounded at capacity
    for _ in range(10):
        flight.finish(flight.begin(monitor.mint_context(), "predict"),
                      "ok")
    assert len(flight.snapshot()["records"]) == 4
    # open records are bounded too, evicting the OLDEST — never the
    # record just opened
    flight.clear()
    handles = [flight.begin(monitor.mint_context(), "predict")
               for _ in range(6)]
    live_ids = {rec["trace_id"] for rec in flight.snapshot()["live"]}
    assert live_ids == {h["trace_id"] for h in handles[-4:]}


def test_flight_trip_dumps_postmortem_with_cooldown(tmp_path):
    flight.enable_flight(capacity=8, dump_dir=str(tmp_path))
    rec = flight.begin(monitor.mint_context(), "route", model="m")
    flight.note(rec["trace_id"], "shed", cls="batch")
    flight.finish(rec, "shed_429", code=429)
    path = flight.trip("replica_wedged", replica="r-1", generation=3)
    assert path is not None and os.path.isfile(path)
    doc = json.load(open(path))
    assert doc["reason"] == "replica_wedged"
    assert doc["meta"] == {"replica": "r-1", "generation": 3}
    assert any(r["outcome"] == "shed_429" and
               r["events"][0]["event"] == "shed"
               for r in doc["records"])
    # cooldown: an immediate second trip for the SAME reason is absorbed
    assert flight.trip("replica_wedged", replica="r-1") is None
    # ... but a different reason dumps
    assert flight.trip("breaker_open", replica="r-0") is not None
    assert len(flight.postmortems()) == 2


def test_histogram_exemplars():
    h = monitor.histogram("test_exemplar_seconds", "x", labels=("m",))
    h.observe(0.007, m="a")                       # no exemplar: fine
    h.observe(0.3, exemplar="trace-slow", m="a")
    h.observe(0.004, exemplar="trace-fast", m="a")
    ex = h.exemplars(m="a")
    assert ex["0.5"] == {"value": 0.3, "trace_id": "trace-slow"}
    assert ex["0.005"] == {"value": 0.004, "trace_id": "trace-fast"}
    series = monitor.dump()["test_exemplar_seconds"]["series"][0]
    assert series["exemplars"]["0.5"]["trace_id"] == "trace-slow"
    # exemplars never leak into the classic text exposition
    assert "trace-slow" not in monitor.prometheus_text()


# ------------------------------------------------------- batcher + server
def test_batcher_propagates_request_context():
    from deeplearning4j_tpu.serving.batcher import ShapeBucketedBatcher
    monitor.enable_tracing()
    flight.enable_flight()
    ctx = monitor.mint_context()
    fr = flight.begin(ctx, "predict", model="bt")
    b = ShapeBucketedBatcher(lambda x: x * 2.0, input_shape=(4,),
                             buckets=(1, 8), name="bt")
    try:
        with monitor.bind_context(ctx):
            y = b.predict(np.ones((2, 4), "float32"))
        assert y.shape == (2, 4)
    finally:
        b.shutdown()
    flight.finish(fr, "ok", code=200)
    evs = [e for e in monitor.trace_events() if e.get("ph") == "X"
           and (e.get("args") or {}).get("trace_id") == ctx.trace_id]
    names = {e["name"] for e in evs}
    assert "serving/queue_wait" in names
    assert "serving/batch" in names
    rec = flight.snapshot()["records"][-1]
    ev_names = [e["event"] for e in rec["events"]]
    assert "dispatch" in ev_names
    # no warm(): the live request paid the bucket compile — the flight
    # timeline must say so
    assert "bucket_compile" in ev_names


def test_server_http_propagation_and_debug_endpoint():
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import ModelServer
    monitor.enable_tracing()
    flight.enable_flight()
    registry = ModelRegistry()
    registry.deploy("m", _net(), buckets=(1, 8))
    server = ModelServer(registry, port=0)
    try:
        client_tid = "ab" * 16
        body = json.dumps({"inputs": [[0.1] * 6]}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{client_tid}-{'cd' * 8}-01"}),
            timeout=30)
        r.read()
        assert r.status == 200
        # the response names the trace; the adopted id is the client's
        assert r.headers.get("X-Trace-Id") == client_tid
        # a request WITHOUT a header gets a server-minted id
        r2 = urllib.request.urlopen(urllib.request.Request(
            server.url + "/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json"}), timeout=30)
        r2.read()
        minted = r2.headers.get("X-Trace-Id")
        assert minted and minted != client_tid
        # replica-side spans carry the client's trace id
        evs = [e for e in monitor.trace_events() if e.get("ph") == "X"
               and (e.get("args") or {}).get("trace_id") == client_tid]
        assert {"serving/request", "serving/batch",
                "serving/queue_wait"} <= {e["name"] for e in evs}
        # the debug endpoint exposes the finished record + exemplars
        doc = json.loads(urllib.request.urlopen(
            server.url + "/v1/debug/flight", timeout=10).read())
        recs = {rec["trace_id"]: rec for rec in doc["records"]}
        assert client_tid in recs and minted in recs
        assert recs[client_tid]["outcome"] == "ok"
        assert recs[client_tid]["code"] == 200
        assert "serving_request_seconds" in doc["exemplars"]
    finally:
        server.drain(timeout=5)


# --------------------------------------------------------- decode stream
class _FakeCache:
    def __init__(self, slots):
        self.slots = slots
        self.seq_lens = np.zeros((slots,), np.int32)
        self._active = set()

    def admit(self, n):
        for s in range(self.slots):
            if s not in self._active:
                self._active.add(s)
                self.seq_lens[s] = n
                return s
        return None

    def active_slots(self):
        return sorted(self._active)

    def ensure_page(self, s):
        return True

    def release(self, s):
        self._active.discard(s)

    def register_prefix(self, slot, tokens):
        pass


class _FakeEngine:
    max_context = 128
    prefill_chunk_tokens = 0          # chunking off: one-shot prefill
    spec_enabled = False              # no speculative draft engine

    def __init__(self, slots=2):
        self.cache = _FakeCache(slots)
        self.closed = False

    def draft_prefill_origin(self, slot):
        return None

    def draft_prefill_done(self, slot, prompt):
        pass

    def release_slot(self, slot):
        self.cache.release(slot)

    def admit_prompt(self, prompt):
        from deeplearning4j_tpu.serving.kvcache import AdmitInfo
        slot = self.cache.admit(len(prompt))
        return None if slot is None else AdmitInfo(slot, 0)

    def prefill(self, slot, prompt, temperature, top_k):
        with monitor.span("serving/prefill", model="fake", bucket=8):
            return 1, None

    def step(self, exclude=()):
        act = np.zeros((self.cache.slots,), bool)
        for s in self.cache.active_slots():
            if s in set(exclude):
                continue
            act[s] = True
            self.cache.seq_lens[s] += 1
        return np.full((self.cache.slots,), 2, np.int32), act, None

    def close(self):
        self.closed = True


def test_decode_scheduler_stream_spans_and_flight_timeline():
    from deeplearning4j_tpu.serving.decode import (
        DecodeScheduler, GenerateRequest,
    )
    monitor.enable_tracing()
    flight.enable_flight()
    ctx = monitor.mint_context()
    sched = DecodeScheduler("fake", queue_limit=4)
    sched.install(_FakeEngine(), version=1)
    fr = flight.begin(ctx, "stream", model="fake")
    with monitor.bind_context(ctx):
        req = GenerateRequest([1, 2, 3], max_new_tokens=3)
    assert req.ctx is ctx
    sched.submit(req)
    assert req.done.wait(5.0), "stream did not finish"
    sched.drain(timeout=2.0)
    flight.finish(fr, "ok", code=200)
    evs = [e for e in monitor.trace_events() if e.get("ph") == "X"
           and (e.get("args") or {}).get("trace_id") == ctx.trace_id]
    names = {e["name"] for e in evs}
    assert "serving/prefill" in names            # bound around prefill
    assert "serving/stream" in names             # whole-stream span
    assert "decode/itl_gap" in names             # per-token-gap spans
    stream = next(e for e in evs if e["name"] == "serving/stream")
    assert stream["args"]["reason"] == "length"
    assert stream["args"]["tokens"] == 3
    rec = flight.snapshot()["records"][-1]
    ev_names = [e["event"] for e in rec["events"]]
    assert ev_names[0] == "queued"
    assert "admitted" in ev_names and "finish" in ev_names
    admitted = next(e for e in rec["events"] if e["event"] == "admitted")
    assert admitted["engine_version"] == 1


def test_router_passes_traceparent_through_when_recorder_off():
    """With the router's tracing AND flight recorder off (the autouse
    fixture's state), a client's traceparent must still reach the
    replica untouched — recorder-enabled replicas downstream keep the
    trace intact."""
    from deeplearning4j_tpu.serving.fleet import Replica
    from deeplearning4j_tpu.serving.router import ResilientRouter
    seen = {}

    def transport(replica, path, body, headers, timeout):
        seen.update(headers)
        return 200, {"Content-Type": "application/json"}, b"{}"

    rep = Replica("r0")
    rep.url = "http://fake"
    router = ResilientRouter(lambda: [rep], transport=transport,
                             hedge=False)
    hdr = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    code, _, _ = router.route_predict("m", b"{}", {"Traceparent": hdr})
    assert code == 200
    assert seen.get("traceparent") == hdr


def test_subprocess_replica_argv_threads_flight_knobs():
    """--no-flight / --flight-records / --trace-out / --postmortem-dir
    must reach every subprocess replica, not just the router."""
    from deeplearning4j_tpu.serving.fleet import (
        ReplicaSpec, SubprocessReplica,
    )
    spec = ReplicaSpec([("m", "zoo:LeNet")], flight=False,
                       trace_out="/t/fleet.json", postmortem_dir="/t/pm")
    argv = SubprocessReplica("replica-0", spec)._argv()
    assert "--no-flight" in argv
    assert "/t/fleet.replica-0.json" in argv
    assert "--postmortem-dir" in argv and "/t/pm" in argv
    spec2 = ReplicaSpec([("m", "zoo:LeNet")], flight_records=64)
    argv2 = SubprocessReplica("replica-1", spec2)._argv()
    assert argv2[argv2.index("--flight-records") + 1] == "64"
    assert "--no-flight" not in argv2


# ------------------------------------------------- supervisor wedge trip
def test_supervisor_wedge_trips_postmortem(tmp_path):
    import random
    from deeplearning4j_tpu.serving.fleet import Replica, ReplicaSupervisor

    class FakeReplica(Replica):
        def __init__(self, name, spec=None):
            super().__init__(name, spec)
            self.alive_flag = False
            self.probe_ok = True

        def launch(self):
            self.alive_flag = True
            self.url = "http://fake"

        def alive(self):
            return self.alive_flag

        def kill(self):
            self.alive_flag = False

    flight.enable_flight(capacity=8, dump_dir=str(tmp_path))
    clock = [0.0]
    sup = ReplicaSupervisor(
        lambda i: FakeReplica(f"f{i}"), 1, unhealthy_after=2,
        time_fn=lambda: clock[0], sleep_fn=lambda s: None,
        rng=random.Random(0),
        probe_fn=lambda r, timeout: r.probe_ok and r.alive(),
        spawn_fn=lambda fn, name: (fn(), None)[1])
    (r,) = sup.replicas
    r.launch()
    sup.tick()                                   # ready (probe ok)
    assert r.state == "ready"
    r.probe_ok = False                           # wedged: alive, no probes
    for _ in range(2):
        clock[0] += 1.0
        sup.tick()
    dumps = [f for f in os.listdir(tmp_path)
             if "replica_wedged" in f and f.endswith(".json")]
    assert dumps, "wedge detection did not dump a postmortem"
    doc = json.load(open(tmp_path / dumps[0]))
    assert doc["meta"]["replica"] == "f0"
    assert doc["meta"]["generation"] == 0
    assert doc["meta"]["probe_failures"] == 2


# ----------------------------------------------------------- trace merge
def _seg(pid, name, trace_id=None, label=None):
    args = {"trace_id": trace_id} if trace_id else {}
    return {"traceEvents": [
        {"name": name, "ph": "X", "ts": 1.0, "dur": 2.0, "pid": pid,
         "tid": 7, "args": args},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 7,
         "args": {"name": "worker"}},
    ]}


def test_trace_report_merges_and_remaps_pid_collisions(tmp_path):
    a, b = tmp_path / "router.json", tmp_path / "replica.json"
    tid = "ee" * 16
    json.dump(_seg(42, "serving/route", tid), open(a, "w"))
    json.dump(_seg(42, "serving/request", tid), open(b, "w"))  # SAME pid
    doc = trace_report.merge_trace_files([("router", str(a)),
                                          ("replica-0", str(b))])
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len({e["pid"] for e in spans}) == 2, \
        "colliding pids were not remapped onto separate tracks"
    pnames = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert sorted(pnames.values()) == ["replica-0", "router"]
    # both spans still carry the trace id; the filter keeps them + meta
    sub = trace_report.filter_to_trace(doc, tid)
    kept = [e for e in sub["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in kept} == {"serving/route",
                                         "serving/request"}
    json.loads(json.dumps(sub))                   # still valid JSON


def test_trace_report_cli_errors_on_missing_input(tmp_path, capsys):
    rc = trace_report.main([str(tmp_path / "nope.json")])
    assert rc == 2
    rc = trace_report.main(["--trace-id", "ff" * 16,
                            str(tmp_path / "nope.json")])
    assert rc == 2


# --------------------------------------- the CLI-fleet acceptance (slow)
@pytest.mark.slow
def test_cli_fleet_one_request_one_trace_merged(tmp_path):
    """Acceptance: a single client request through the CLI fleet (router
    + 2 subprocess replicas) yields ONE trace_id present in router,
    replica-server, and batcher spans, and trace_report merges the
    per-process segments into one valid Perfetto trace."""
    from bench import cache_dir
    from deeplearning4j_tpu.util.serialization import save_model
    model_zip = str(tmp_path / "model.zip")
    save_model(_net(), model_zip)
    trace_out = str(tmp_path / "fleet.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir())
    proc = subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.serving",
         "--model", f"m={model_zip}", "--replicas", "2",
         "--replica-mode", "subprocess", "--port", "0",
         "--buckets", "1,8", "--trace-out", trace_out,
         "--postmortem-dir", str(tmp_path / "pm"),
         "--drain-timeout-s", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=_REPO, env=env)
    try:
        line = proc.stdout.readline()
        ann = json.loads(line)
        assert ann.get("role") == "router", ann
        url = ann["serving"]
        body = json.dumps({"inputs": [[0.1] * 6]}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            url + "/v1/models/m/predict", data=body,
            headers={"Content-Type": "application/json"}), timeout=60)
        r.read()
        assert r.status == 200
        tid = r.headers.get("X-Trace-Id")
        assert tid, "router response carries no X-Trace-Id"
        served_by = r.headers.get("X-Served-By")
        assert served_by in ("replica-0", "replica-1")
    finally:
        proc.send_signal(2)                       # SIGINT -> fleet drain
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0
    segments = [("router", trace_out)]
    for i in range(2):
        seg = str(tmp_path / f"fleet.replica-{i}.json")
        assert os.path.isfile(seg), f"replica {i} saved no trace segment"
        segments.append((f"replica-{i}", seg))
    merged = trace_report.merge_trace_files(segments)
    json.loads(json.dumps(merged))                # valid Perfetto JSON
    spans = trace_report.events_for_trace(merged, tid)
    names = {e["name"] for e in spans}
    pids = {e["pid"] for e in spans}
    assert "serving/route" in names, names        # router hop
    assert "serving/request" in names, names      # replica server hop
    assert names & {"serving/batch", "serving/queue_wait"}, names
    assert len(pids) >= 2, \
        f"trace {tid} did not cross a process boundary: {sorted(names)}"
    # the filtered single-request view stays loadable
    sub = trace_report.filter_to_trace(merged, tid)
    assert trace_report.events_for_trace(sub, tid)
