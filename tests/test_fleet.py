"""Fleet-resilience tests: ReplicaSupervisor, circuit breakers, priority
shedding, power-of-two-choices, hedging (serving/fleet.py + router.py).

Everything policy-level is pinned DETERMINISTICALLY: fake clocks drive the
breaker lifecycle and the supervisor's backoff/budget arithmetic, fake
replicas/transports make routing outcomes exact, and util/faults.py
toggles wedge live servers — no sleeps-and-hope timing anywhere. The
end-to-end chaos run (real subprocess replicas, SIGKILL, wedged probes)
lives in tools/serve_chaos.py and rides as a slow-marked test here.
"""
import json
import os
import random
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.serving import retry_after_seconds
from deeplearning4j_tpu.serving.fleet import (
    Replica, ReplicaSpec, ReplicaSupervisor,
)
from deeplearning4j_tpu.serving.router import (
    BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN, CircuitBreaker,
    ReplicaTransportError, ResilientRouter,
)
from deeplearning4j_tpu.util.faults import serving_faults


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------- circuit breaker
def test_breaker_full_lifecycle_closed_open_half_open_closed():
    clock = FakeClock()
    br = CircuitBreaker(window=10, min_samples=4, failure_rate=0.5,
                        open_for_s=10.0, time_fn=clock)
    assert br.state == BREAKER_CLOSED
    # below min_samples: failures alone cannot open it
    br.record_failure()
    br.record_failure()
    br.record_failure()
    assert br.state == BREAKER_CLOSED and br.allow()
    # 4th sample crosses min_samples at 100% failure rate -> OPEN
    br.record_failure()
    assert br.state == BREAKER_OPEN
    assert not br.allow() and not br.would_allow()
    # time heals nothing until open_for_s elapses
    clock.advance(9.9)
    assert not br.allow()
    clock.advance(0.2)
    # first allow() after the cooldown is the half-open probe
    assert br.would_allow()
    assert br.allow()
    assert br.state == BREAKER_HALF_OPEN
    # only one probe may be in flight
    assert not br.allow()
    # probe success -> CLOSED, window reset (old failures forgotten)
    br.record_success()
    assert br.state == BREAKER_CLOSED
    br.record_failure()
    br.record_failure()
    br.record_failure()
    assert br.state == BREAKER_CLOSED      # fresh window: 3 < min_samples


def test_breaker_half_open_failure_reopens_for_full_cooldown():
    clock = FakeClock()
    br = CircuitBreaker(window=4, min_samples=2, failure_rate=0.5,
                        open_for_s=5.0, time_fn=clock)
    br.record_failure()
    br.record_failure()
    assert br.state == BREAKER_OPEN
    clock.advance(5.0)
    assert br.allow()                      # the half-open probe
    br.record_failure()                    # probe failed
    assert br.state == BREAKER_OPEN
    clock.advance(4.9)
    assert not br.allow()                  # a FULL new cooldown
    clock.advance(0.2)
    assert br.allow()


def test_breaker_mixed_rate_below_threshold_stays_closed():
    clock = FakeClock()
    br = CircuitBreaker(window=10, min_samples=4, failure_rate=0.5,
                        open_for_s=5.0, time_fn=clock)
    for _ in range(8):
        br.record_success()
        br.record_failure()                # 50% in a window of 10...
    # exactly at threshold -> opens (>= semantics)
    assert br.state == BREAKER_OPEN


# ------------------------------------------------------------ the router
def _ready_replicas(n, inflight=()):
    reps = []
    for i in range(n):
        r = Replica(f"r{i}")
        r.state = "ready"
        r.url = f"http://fake-{i}"
        if i < len(inflight):
            r.inflight_add(inflight[i])
        reps.append(r)
    return reps


def _ok_transport(replica, path, body, headers, timeout):
    return 200, {"Content-Type": "application/json"}, json.dumps(
        {"who": replica.name}).encode()


def _router(reps, **kw):
    kw.setdefault("transport", _ok_transport)
    kw.setdefault("hedge", False)
    kw.setdefault("rng", random.Random(0))
    return ResilientRouter(lambda: [r for r in reps
                                    if r.state == "ready"], **kw)


def test_priority_shedding_order_is_deterministic():
    """The pinned shed order: utilization sheds strictly lowest-class
    first — batch at the floor, standard next, interactive only when the
    fleet is hard-full."""
    reps = _ready_replicas(2)
    router = _router(reps, classes=("interactive", "standard", "batch"),
                     default_class="standard", shed_floor=0.5,
                     per_replica_inflight=4)    # capacity 8
    # thresholds: interactive 1.0, standard 0.75, batch 0.5
    assert router.shed_at == {"interactive": 1.0, "standard": 0.75,
                              "batch": 0.5}

    def code_for(cls, used):
        for r in reps:
            r._inflight = 0
        reps[0]._inflight = used
        code, _, _ = router.route_predict(
            "m", b"{}", {"X-Priority": cls} if cls else {})
        return code

    # util 0.5: batch shed, standard + interactive flow
    assert code_for("batch", 4) == 429
    assert code_for("standard", 4) == 200
    assert code_for("interactive", 4) == 200
    # util 0.75: batch + standard shed, interactive flows
    assert code_for("batch", 6) == 429
    assert code_for("standard", 6) == 429
    assert code_for("interactive", 6) == 200
    # hard full: everyone sheds
    assert code_for("interactive", 8) == 429
    # no header -> default class (standard here)
    assert code_for(None, 6) == 429
    assert code_for(None, 0) == 200
    # unknown class name -> default class, not a crash
    assert code_for("no-such-class", 6) == 429
    # shed responses carry a jittered integer Retry-After
    for r in reps:
        r._inflight = 4
    code, headers, body = router.route_predict(
        "m", b"{}", {"X-Priority": "batch"})
    assert code == 429
    ra = dict(headers).get("Retry-After")
    assert ra is not None and 1 <= int(ra) <= 5
    shed = monitor.REGISTRY.collect("serving_router_shed_total")
    assert shed is not None and shed.value(cls="batch") >= 3
    assert shed.value(cls="standard") >= 2     # the defaulted sheds


def test_priority_classes_match_case_insensitively():
    """--priority-classes Interactive,Batch must still match the
    lowercased X-Priority header: classes normalize to lowercase."""
    reps = _ready_replicas(1)
    router = _router(reps, classes=("Interactive", "Batch"),
                     per_replica_inflight=4)
    assert router.classes == ("interactive", "batch")
    assert router.default_class == "batch"
    reps[0]._inflight = 3                  # past batch's shed floor
    code, _, _ = router.route_predict("m", b"{}",
                                      {"X-Priority": "INTERACTIVE"})
    assert code == 200                     # top class, matched, not shed
    code, _, _ = router.route_predict("m", b"{}",
                                      {"X-Priority": "batch"})
    assert code == 429                     # low class sheds


def test_power_of_two_choices_prefers_lower_inflight():
    reps = _ready_replicas(2, inflight=(5, 0))
    router = _router(reps, per_replica_inflight=100)
    served_by = set()
    for _ in range(10):
        code, headers, _ = router.route_predict("m", b"{}", {})
        assert code == 200
        served_by.add(dict(headers)["X-Served-By"])
    assert served_by == {"r1"}             # always the shallower queue


def test_router_fails_over_past_a_dead_replica():
    reps = _ready_replicas(2)
    calls = []

    def transport(replica, path, body, headers, timeout):
        calls.append(replica.name)
        if replica.name == "r0":
            raise ReplicaTransportError("r0: connection refused")
        return _ok_transport(replica, path, body, headers, timeout)

    router = _router(reps, transport=transport, max_attempts=2,
                     breaker_min_samples=3)
    # every request lands 200 on r1 whether or not r0 was tried first
    for _ in range(8):
        code, headers, _ = router.route_predict("m", b"{}", {})
        assert code == 200
        assert dict(headers)["X-Served-By"] == "r1"
    assert "r0" in calls                   # r0 was really attempted
    # r0's transport failures opened its breaker -> stops being attempted
    assert not router.breaker(reps[0], "m").would_allow()
    n0 = calls.count("r0")
    for _ in range(5):
        assert router.route_predict("m", b"{}", {})[0] == 200
    assert calls.count("r0") == n0         # no further traffic to r0


def test_breaker_resets_on_replica_generation_bump():
    reps = _ready_replicas(1)
    router = _router(reps)
    br = router.breaker(reps[0], "m")
    for _ in range(10):
        br.record_failure()
    assert not br.would_allow()
    reps[0].generation += 1                # supervisor replaced it
    fresh = router.breaker(reps[0], "m")
    assert fresh is not br and fresh.would_allow()


def test_router_503_when_no_replica_routable():
    reps = _ready_replicas(1)
    router = _router(reps)
    for _ in range(10):
        router.breaker(reps[0], "m").record_failure()
    code, headers, body = router.route_predict("m", b"{}", {})
    assert code == 503
    assert 1 <= int(dict(headers)["Retry-After"]) <= 5
    assert "error" in json.loads(body)
    # and with an empty fleet
    code, _, _ = _router([]).route_predict("m", b"{}", {})
    assert code == 503


def test_hedged_request_wins_on_straggling_primary():
    """Deterministic straggler: the primary's transport blocks on an
    Event; the hedge must fire (tracked-p99 delay) and its fast response
    must be returned while the primary is still stuck."""
    monitor.REGISTRY.reset()
    reps = _ready_replicas(2, inflight=(0, 3))   # p2c primary pick = r0
    release = threading.Event()
    calls = []

    def transport(replica, path, body, headers, timeout):
        calls.append(replica.name)
        if replica.name == "r0":
            release.wait(10)               # straggler until released
        return _ok_transport(replica, path, body, headers, timeout)

    router = _router(reps, transport=transport, hedge=True,
                     hedge_min_s=0.02, hedge_min_samples=1)
    router._note_latency("m", 0.01)        # p99 tracker armed
    try:
        code, headers, _ = router.route_predict("m", b"{}", {})
        assert code == 200
        assert dict(headers)["X-Served-By"] == "r1"
        assert calls == ["r0", "r1"]       # hedge really was a second send
        hedges = monitor.REGISTRY.collect("serving_router_hedges_total")
        assert hedges.value(model="m") == 1
    finally:
        release.set()


# --------------------------------------------------------- the supervisor
class FakeReplica(Replica):
    """Scripted replica: tests flip `alive_flag`/`probe_ok`."""

    def __init__(self, name, spec=None):
        super().__init__(name, spec)
        self.alive_flag = False
        self.probe_ok = True
        self.launches = 0
        self.kills = 0

    def launch(self):
        self.launches += 1
        self.alive_flag = True
        self.url = f"http://fake/{self.name}/{self.launches}"

    def alive(self):
        return self.alive_flag

    def kill(self):
        self.kills += 1
        self.alive_flag = False


def _supervisor(n=1, clock=None, **kw):
    clock = clock or FakeClock()
    reps = []

    def factory(i):
        r = FakeReplica(f"f{i}")
        reps.append(r)
        return r

    kw.setdefault("probe_interval_s", 1.0)
    kw.setdefault("unhealthy_after", 3)
    kw.setdefault("restart_backoff_s", 1.0)
    kw.setdefault("restart_budget", 3)
    kw.setdefault("restart_budget_window_s", 100.0)
    # synchronous relaunches keep these policy tests single-threaded;
    # the threaded default is pinned by
    # test_hung_relaunch_does_not_stall_supervision
    kw.setdefault("spawn_fn", lambda fn, name: (fn(), None)[1])
    sup = ReplicaSupervisor(
        factory, n, time_fn=clock, sleep_fn=lambda s: None,
        rng=random.Random(0),
        probe_fn=lambda r, timeout: r.probe_ok and r.alive(), **kw)
    # tests drive tick() directly — launch without the loop thread
    for r in sup.replicas:
        r.launch()
    return sup, reps, clock


def test_supervisor_restarts_crashed_replica_with_backoff():
    sup, (r,), clock = _supervisor()
    sup.tick()
    assert r.state == "ready"
    r.alive_flag = False                   # SIGKILL analog
    sup.tick()
    assert r.state == "backoff" and r.restart_at is not None
    # jittered exponential backoff: within (0.5, 1.0] * base
    delay = r.restart_at - clock()
    assert 0.5 < delay <= 1.0
    assert monitor.REGISTRY.collect("serving_fleet_restarts_total").value(
        replica="f0", reason="crash") >= 1
    # before the backoff deadline: no relaunch
    sup.tick()
    assert r.launches == 1
    clock.advance(1.1)
    sup.tick()                             # relaunch fires
    assert r.launches == 2 and r.generation == 1 and r.state == "starting"
    sup.tick()                             # first good probe -> ready
    assert r.state == "ready"
    assert r.consecutive_probe_failures == 0


def test_supervisor_replaces_wedged_replica_after_k_probes():
    sup, (r,), clock = _supervisor(unhealthy_after=3)
    sup.tick()
    assert r.state == "ready"
    r.probe_ok = False                     # alive but wedged
    sup.tick()
    sup.tick()
    assert r.state == "ready"              # 2 failures: still tolerated
    assert r.consecutive_probe_failures == 2
    sup.tick()                             # 3rd consecutive: replaced
    assert r.kills == 1                    # a wedged process gets killed
    assert r.state == "backoff"
    assert monitor.REGISTRY.collect("serving_fleet_restarts_total").value(
        replica="f0", reason="probe") >= 1
    r.probe_ok = True
    clock.advance(5.0)
    sup.tick()                             # relaunch
    sup.tick()                             # probe ok
    assert r.state == "ready" and r.generation == 1


def test_supervisor_one_good_probe_resets_failure_count():
    sup, (r,), clock = _supervisor(unhealthy_after=3)
    sup.tick()
    r.probe_ok = False
    sup.tick()
    sup.tick()
    r.probe_ok = True
    sup.tick()                             # heals
    assert r.consecutive_probe_failures == 0
    r.probe_ok = False
    sup.tick()
    sup.tick()
    assert r.state == "ready"              # the count really restarted


def test_supervisor_restart_budget_marks_crash_loop_dead():
    sup, (r,), clock = _supervisor(restart_budget=2,
                                   restart_budget_window_s=100.0,
                                   restart_backoff_s=0.1)
    sup.tick()
    for _ in range(2):                     # two budgeted restarts
        r.alive_flag = False
        sup.tick()
        clock.advance(10.0)
        sup.tick()                         # relaunch
        sup.tick()                         # ready again
        assert r.state == "ready"
    r.alive_flag = False                   # third crash inside the window
    sup.tick()
    assert r.state == "dead"
    assert monitor.REGISTRY.collect("serving_fleet_gave_up_total").value(
        replica="f0") == 1
    # dead replicas are left alone...
    clock.advance(50.0)
    sup.tick()
    assert r.state == "dead" and r.launches == 3
    # ...but the budget is a WINDOW: crashes spread beyond it still heal
    sup2, (r2,), clock2 = _supervisor(restart_budget=2,
                                      restart_budget_window_s=100.0,
                                      restart_backoff_s=0.1)
    sup2.tick()
    for _ in range(4):                     # 4 crashes, 150s apart
        r2.alive_flag = False
        sup2.tick()
        clock2.advance(150.0)
        sup2.tick()
        sup2.tick()
        assert r2.state == "ready"


def test_supervisor_backoff_grows_exponentially_until_stable():
    sup, (r,), clock = _supervisor(restart_backoff_s=1.0, restart_budget=10)
    sup.tick()
    delays = []
    for _ in range(3):
        r.alive_flag = False
        r.probe_ok = False                 # relaunched incarnation stays bad
        sup.tick()
        delays.append(r.restart_at - clock())
        clock.advance(delays[-1] + 0.01)
        sup.tick()                         # relaunch (comes up not-ready)
        r.alive_flag = False               # crashes again immediately
        sup.tick()
    # attempt exponent grew: each full-jitter window doubles
    assert delays[0] <= 1.0 < delays[1] <= 2.0 < delays[2] <= 4.0
    # a stable ready period resets the exponent
    r.probe_ok = True
    clock.advance(10.0)
    sup.tick()                             # relaunch
    sup.tick()                             # ready
    assert r.state == "ready" and r.restart_attempt == 0


def test_hung_relaunch_does_not_stall_supervision():
    """One replica's relaunch hanging (silent child, slow model load)
    must not block probing/restarting the rest of the fleet: relaunches
    run on spawn_fn threads, outside the tick lock."""
    clock = FakeClock()
    gate = threading.Event()
    reps = []

    class Hanging(FakeReplica):
        def launch(self):
            if self.name == "h0" and self.launches >= 1:
                gate.wait(10)          # hung relaunch analog
            super().launch()

    def factory(i):
        r = Hanging(f"h{i}")
        reps.append(r)
        return r

    sup = ReplicaSupervisor(
        factory, 2, time_fn=clock, sleep_fn=lambda s: None,
        rng=random.Random(0), restart_backoff_s=1.0,
        probe_fn=lambda r, timeout: r.probe_ok and r.alive())
    try:
        for r in sup.replicas:
            r.launch()
        sup.tick()
        assert all(r.state == "ready" for r in reps)
        reps[0].alive_flag = False
        sup.tick()                     # h0 -> backoff
        clock.advance(2.0)
        sup.tick()                     # h0 relaunch spawns and HANGS
        assert reps[0].state == "starting"
        # while h0's relaunch hangs, h1 is still supervised:
        reps[1].alive_flag = False
        sup.tick()
        assert reps[1].state == "backoff"
        clock.advance(2.0)
        sup.tick()                     # h1 relaunches (its own thread)
        reps[1]._launch_thread.join(10)
        sup.tick()
        assert reps[1].state == "ready" and reps[1].generation == 1
        assert reps[0].state == "starting"   # h0 still stuck, contained
    finally:
        gate.set()
    reps[0]._launch_thread.join(10)
    sup.tick()
    assert reps[0].state == "ready" and reps[0].generation == 1


def test_router_relays_replica_504_without_poisoning_breaker():
    """A replica 504 (the request's own deadline expired) is client
    backpressure: it must be relayed, and must NOT count toward the
    breaker the way a 500 does — a tight-deadline client cannot open
    breakers on healthy replicas."""
    reps = _ready_replicas(1)

    def transport_504(replica, path, body, headers, timeout):
        return 504, {"Content-Type": "application/json"}, json.dumps(
            {"error": "deadline"}).encode()

    router = _router(reps, transport=transport_504)
    for _ in range(10):
        code, _, body = router.route_predict("m", b"{}", {})
        assert code == 504, code
        assert "deadline" in json.loads(body)["error"]
    from deeplearning4j_tpu.serving.router import BREAKER_CLOSED
    assert router.breaker(reps[0], "m").state == BREAKER_CLOSED


def test_failover_skips_denied_breaker_and_reaches_third_replica():
    """Failover after a primary failure must loop past a candidate whose
    breaker denies allow() (half-open slot consumed mid-request) and
    reach the next closed-breaker replica instead of giving up."""
    clock = FakeClock()
    reps = _ready_replicas(3)          # r0 primary (lowest inflight)
    reps[1].inflight_add(1)
    reps[2].inflight_add(2)
    calls = []
    router_box = []

    def transport(replica, path, body, headers, timeout):
        calls.append(replica.name)
        if replica.name == "r0":
            # while r0 is in flight, r1's half-open probe slot is taken
            # by "another request", then r0 fails at the wire
            router_box[0].breaker(reps[1], "m").allow()
            raise ReplicaTransportError("r0 died")
        return _ok_transport(replica, path, body, headers, timeout)

    # seed 1: the first p2c sample is (r0, r2) -> r0 (lowest inflight)
    # is deterministically the primary
    router = _router(reps, transport=transport, time_fn=clock,
                     breaker_open_for_s=5.0, rng=random.Random(1))
    router_box.append(router)
    # put r1's breaker into half-open: open it, then lapse the cooldown
    br1 = router.breaker(reps[1], "m")
    for _ in range(5):
        br1.record_failure()
    assert br1.state == BREAKER_OPEN
    clock.advance(6.0)
    assert br1.would_allow()           # half-open, one probe slot free
    code, headers, _ = router.route_predict("m", b"{}", {})
    assert code == 200, code
    assert dict(headers)["X-Served-By"] == "r2"
    assert calls == ["r0", "r2"]       # r1 denied, skipped — not dropped
    retries = monitor.REGISTRY.collect("serving_router_retries_total")
    assert retries.value(model="m") >= 1


def test_half_open_probe_slot_released_on_backpressure():
    """A half-open probe answered with 429/503/504 is INCONCLUSIVE: the
    probe slot must be given back (not leaked), or the breaker wedges in
    half-open and a healthy replica never gets traffic again."""
    clock = FakeClock()
    codes = [429, 200]

    def transport(replica, path, body, headers, timeout):
        return codes.pop(0), {"Content-Type": "application/json"}, \
            json.dumps({"who": replica.name}).encode()

    reps = _ready_replicas(1)
    router = _router(reps, transport=transport, time_fn=clock,
                     breaker_open_for_s=5.0)
    br = router.breaker(reps[0], "m")
    for _ in range(5):
        br.record_failure()
    assert br.state == BREAKER_OPEN
    clock.advance(6.0)
    # half-open probe hits momentary backpressure: relayed, slot freed
    code, _, _ = router.route_predict("m", b"{}", {})
    assert code == 429
    assert br.state == BREAKER_HALF_OPEN
    assert br.would_allow()            # the slot came back
    # next probe succeeds and closes the breaker
    code, _, _ = router.route_predict("m", b"{}", {})
    assert code == 200
    assert br.state == BREAKER_CLOSED


def test_hedge_loops_past_denied_spare_breaker():
    """Hedging must try the next candidate when the first spare's
    breaker denies allow() — symmetric with the failover loop."""
    monitor.REGISTRY.reset()
    clock = FakeClock()
    reps = _ready_replicas(3)
    reps[1].inflight_add(1)            # hedge pool pick order: r1 first
    reps[2].inflight_add(2)
    release = threading.Event()
    calls = []
    router_box = []

    def transport(replica, path, body, headers, timeout):
        calls.append(replica.name)
        if replica.name == "r0":
            release.wait(10)           # straggler primary
        return _ok_transport(replica, path, body, headers, timeout)

    # seed 1: first p2c sample is (r0, r2) -> r0 primary
    router = _router(reps, transport=transport, hedge=True,
                     hedge_min_s=0.02, hedge_min_samples=1,
                     time_fn=clock, breaker_open_for_s=5.0,
                     rng=random.Random(1))
    router_box.append(router)
    router._note_latency("m", 0.01)    # p99 tracker armed
    # r1 half-open with its only probe slot consumed -> allow() denies
    br1 = router.breaker(reps[1], "m")
    for _ in range(5):
        br1.record_failure()
    clock.advance(6.0)
    assert br1.allow()                 # consume the half-open slot
    try:
        code, headers, _ = router.route_predict("m", b"{}", {})
        assert code == 200
        assert dict(headers)["X-Served-By"] == "r2"
        assert calls == ["r0", "r2"]   # r1 denied, r2 hedged instead
        hedges = monitor.REGISTRY.collect("serving_router_hedges_total")
        assert hedges.value(model="m") == 1
    finally:
        release.set()


def test_fleet_swap_updates_spec_and_reports_skipped():
    """A fleet swap must leave future incarnations on the NEW source
    (spec updated) and name the replicas the fan-out could not reach."""
    from deeplearning4j_tpu.serving.router import RouterServer

    spec = ReplicaSpec([("m", "/old/src")])
    reps = _ready_replicas(2)
    for r in reps:
        r.spec = spec
    down = Replica("r-down", spec)     # crashed: not in the routing set
    down.state = "backoff"

    def transport(replica, path, body, headers, timeout):
        return 200, {"Content-Type": "application/json"}, json.dumps(
            {"model": "m", "active": {"version": 2}}).encode()

    class Sup:                         # duck-typed supervisor view
        replicas = reps + [down]

        def healthy(self):
            return [r for r in self.replicas if r.state == "ready"]

    router = _router(reps, transport=transport)
    server = RouterServer(router, supervisor=Sup())
    try:
        req = urllib.request.Request(
            f"{server.url}/v1/models/m/swap",
            data=json.dumps({"source": "/new/src"}).encode(),
            headers={"Content-Type": "application/json"})
        r = urllib.request.urlopen(req, timeout=10)
        doc = json.loads(r.read())
        assert r.status == 200 and doc["ok"], doc
        assert doc["skipped_unhealthy"] == ["r-down"]
        # the shared spec now carries the swapped source: a supervisor
        # relaunch of r-down will load /new/src, not /old/src
        assert spec.models == [("m", "/new/src")]
    finally:
        server.stop()


def test_supervisor_healthy_excludes_non_ready():
    sup, reps, clock = _supervisor(3)
    sup.tick()
    assert [r.name for r in sup.healthy()] == ["f0", "f1", "f2"]
    reps[1].alive_flag = False
    sup.tick()
    assert [r.name for r in sup.healthy()] == ["f0", "f2"]
    gauge = monitor.REGISTRY.collect("serving_fleet_replicas")
    assert gauge.value(state="ready") == 2
    assert gauge.value(state="backoff") == 1


def test_supervisor_rejects_bad_config():
    with pytest.raises(ValueError, match="n_replicas"):
        ReplicaSupervisor(lambda i: FakeReplica("x"), 0)
    with pytest.raises(ValueError, match="unique"):
        ReplicaSupervisor(lambda i: FakeReplica("same"), 2)


# -------------------------------------------------- retry-after / faults
def test_router_server_drain_flips_readyz():
    """The fleet CLI's SIGTERM path flips RouterServer.draining before
    tearing replicas down: /readyz must go 503 (balancer drains us) with
    a jittered Retry-After while predicts still route."""
    from deeplearning4j_tpu.serving.router import RouterServer

    reps = _ready_replicas(1)
    server = RouterServer(_router(reps))
    try:
        r = urllib.request.urlopen(f"{server.url}/readyz", timeout=10)
        assert r.status == 200
        r.read()
        server.draining = True
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{server.url}/readyz", timeout=10)
        assert e.value.code == 503
        assert 1 <= int(e.value.headers["Retry-After"]) <= 5
        assert json.loads(e.value.read())["status"] == "draining"
        # in-flight/late traffic still routes during the drain window
        req = urllib.request.Request(
            f"{server.url}/v1/models/m/predict", data=b"{}",
            headers={"Content-Type": "application/json"})
        r = urllib.request.urlopen(req, timeout=10)
        assert r.status == 200
        r.read()
    finally:
        server.stop()


def test_retry_after_seconds_scales_and_jitters():
    rng = random.Random(0)
    # empty queue: always the 1s floor
    assert {retry_after_seconds(0, 64, rng=rng) for _ in range(20)} == {1}
    # full queue: jittered across [1, 5]
    vals = {retry_after_seconds(64, 64, rng=rng) for _ in range(50)}
    assert vals == {1, 2, 3, 4, 5}
    # draining: flat [1, 5] horizon regardless of queue
    vals = {retry_after_seconds(0, 64, draining=True, rng=rng)
            for _ in range(50)}
    assert vals == {1, 2, 3, 4, 5}
    # half-full: ceiling 3
    vals = {retry_after_seconds(32, 64, rng=rng) for _ in range(50)}
    assert vals == {1, 2, 3}


def test_serving_faults_toggles_and_env(monkeypatch):
    sf = serving_faults()
    sf.clear()
    assert not sf.active()
    sf.set(predict_delay_s=0.25, probe_error=True)
    assert sf.active()
    assert sf.describe()["predict_delay_s"] == 0.25
    with pytest.raises(ValueError, match="unknown serving fault"):
        sf.set(nonsense=1)
    sf.clear()
    monkeypatch.setenv("DL4J_TPU_SERVING_FAULTS",
                       "probe_delay_s=2;predict_error=1")
    sf.apply_env()
    assert sf.probe_delay_s == 2.0 and sf.predict_error
    # falsy env strings mean OFF — bool("0") must not arm the fault
    monkeypatch.setenv("DL4J_TPU_SERVING_FAULTS",
                       "predict_error=0;probe_error=false")
    sf.clear()
    sf.apply_env()
    assert not sf.predict_error and not sf.probe_error
    assert not sf.active()
    sf.clear()


# ------------------------------------------------- chaos SLO gate (slow)
@pytest.mark.slow
def test_serve_chaos_slo_gate(tmp_path):
    """The acceptance run: 3 subprocess replicas, SIGKILL + wedge under
    traffic, zero 5xx, restart-and-rejoin, p99 recovery — all asserted
    by tools/serve_chaos.py itself (exit 0 == SLO held)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "serve_chaos.py")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, timeout=580)
    assert proc.returncode == 0, \
        f"chaos SLO gate failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    report = json.loads(proc.stdout)
    assert report["ok"] and not report["failures"]
    assert report["fleet_restarts_total"] >= 2
    assert report["shed"]["batch"] > 0
