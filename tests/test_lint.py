"""graftlint: fixture goldens per rule + the tier-1 live-tree gate.

Fixture convention (tests/fixtures/graftlint/): every rule has a
`*_pos.py` with `# EXPECT` markers on each line that must be flagged,
and a `*_neg.py` of near-misses that must stay clean. The live-tree
test IS the CI gate: `deeplearning4j_tpu/ + tools/ + bench.py` must
have zero unsuppressed findings, so every future PR (including the
GSPMD-mesh refactor) walks through the analyzer.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")
sys.path.insert(0, REPO)

from deeplearning4j_tpu import analysis
from deeplearning4j_tpu.analysis import core as lint_core
from deeplearning4j_tpu.analysis.rules.telemetry import (
    MetricFamilyRegistrationRule,
)

RULES_BY_NAME = {r.name: r for r in analysis.ALL_RULES}


def expect_lines(path):
    with open(path, encoding="utf-8") as fh:
        return {i for i, line in enumerate(fh.read().splitlines(), 1)
                if "# EXPECT" in line}


def run_rule(rule_name, fixture, rule=None):
    rule = rule or RULES_BY_NAME[rule_name]
    mod = lint_core.load_module(os.path.join(FIXTURES, fixture))
    assert mod is not None, f"fixture {fixture} failed to parse"
    if isinstance(rule, analysis.ProjectRule):
        findings = rule.check_project(lint_core.Project([mod]))
    else:
        findings = rule.check(mod)
    return sorted(f.line for f in findings)


FIXTURE_MATRIX = [
    ("donated-aliasing", "donated_aliasing_pos.py"),
    ("donated-aliasing", "donated_aliasing_pr3_pos.py"),
    ("donated-aliasing", "donated_aliasing_neg.py"),
    ("unlaundered-restore-placement", "restore_placement_pos.py"),
    ("unlaundered-restore-placement", "restore_placement_neg.py"),
    ("host-sync-in-hot-path", "host_sync_pos.py"),
    ("host-sync-in-hot-path", "host_sync_neg.py"),
    ("recompile-hazard", "recompile_hazard_pos.py"),
    ("recompile-hazard", "recompile_hazard_neg.py"),
    ("env-knob-contract", "env_knob_pos.py"),
    ("env-knob-contract", "env_knob_neg.py"),
    ("blocking-under-lock", "blocking_under_lock_pos.py"),
    ("blocking-under-lock", "blocking_under_lock_neg.py"),
    ("telemetry-zero-cost", "telemetry_zero_cost_pos.py"),
    ("telemetry-zero-cost", "telemetry_zero_cost_neg.py"),
    ("bare-except-swallow", os.path.join("parallel", "bare_except_pos.py")),
    ("bare-except-swallow", os.path.join("parallel", "bare_except_neg.py")),
    ("lock-order-inversion", "lock_order_pos.py"),
    ("lock-order-inversion", "lock_order_neg.py"),
    ("transitive-blocking-under-lock", "transitive_blocking_pos.py"),
    ("transitive-blocking-under-lock", "transitive_blocking_neg.py"),
    ("thread-lifecycle", "thread_lifecycle_pos.py"),
    ("thread-lifecycle", "thread_lifecycle_neg.py"),
    ("resource-pairing", "resource_pairing_pos.py"),
    ("resource-pairing", "resource_pairing_neg.py"),
]


def test_pr8_and_pr11_shapes_invisible_to_lexical_rules():
    """THE acceptance pin: the literal PR-8 transitive-blocking and
    PR-11 silent-thread-death regression shapes are caught ONLY by the
    new interprocedural rules — every pre-PR lexical rule reports
    nothing on those fixtures."""
    lexical = [r for r in analysis.ALL_RULES
               if not isinstance(r, analysis.ProjectRule)
               and r.name != "resource-pairing"]
    for fixture in ("transitive_blocking_pos.py",
                    "thread_lifecycle_pos.py"):
        mod = lint_core.load_module(os.path.join(FIXTURES, fixture))
        for rule in lexical:
            hits = list(rule.check(mod))
            assert hits == [], (
                f"{rule.name} unexpectedly fires on {fixture}: {hits}")
    # ...and the new rules DO catch them (the fixture goldens pin the
    # exact lines; this is the cross-check that both halves exist)
    assert run_rule("transitive-blocking-under-lock",
                    "transitive_blocking_pos.py")
    assert run_rule("thread-lifecycle", "thread_lifecycle_pos.py")


@pytest.mark.parametrize("rule_name,fixture", FIXTURE_MATRIX,
                         ids=[f"{r}:{os.path.basename(f)}"
                              for r, f in FIXTURE_MATRIX])
def test_fixture_golden(rule_name, fixture):
    """Each `# EXPECT` line is flagged; nothing else is. Positives prove
    the rule catches the shipped bug shape (incl. the PR-3 donated-
    aliasing resume and the PR-8 launch-under-tick-lock); negatives
    prove the near-misses stay clean."""
    path = os.path.join(FIXTURES, fixture)
    assert run_rule(rule_name, fixture) == sorted(expect_lines(path))


def test_metric_family_rule_against_fixture_catalog():
    rule = MetricFamilyRegistrationRule(
        catalog_path=os.path.join(FIXTURES, "fixture_catalog.md"))
    pos = os.path.join(FIXTURES, "metric_family_pos.py")
    assert run_rule(None, "metric_family_pos.py", rule=rule) == \
        sorted(expect_lines(pos))
    assert run_rule(None, "metric_family_neg.py", rule=rule) == []


def test_metric_family_extraction_is_shared_source_of_truth():
    """telemetry_smoke.py consumes this exact extraction — the static
    catalog check and the live-scrape check must agree on what the tree
    emits."""
    fams = analysis.extract_metric_families(
        [os.path.join(REPO, "deeplearning4j_tpu")])
    for expected in ("train_iterations_total", "etl_fetch_wait_seconds",
                     "serving_requests_total",
                     "serving_fleet_restarts_total",
                     "xla_analysis_unavailable_total"):
        assert expected in fams, f"extraction lost {expected}"
    # every extraction hit carries (path, line) provenance
    path, line = fams["train_iterations_total"][0]
    assert path.endswith(".py") and line > 0


# ------------------------------------------------------------- framework
def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body), encoding="utf-8")
    return str(p)


def test_pragma_suppresses_with_justification(tmp_path):
    p = _write(tmp_path, "m.py", """\
        import os
        # graftlint: disable=env-knob-contract -- fixture: recorded decision
        v = os.environ.get("DL4J_TPU_X")
        """)
    res = analysis.run([p])
    assert res.findings == [] and res.pragma_findings == []
    assert len(res.suppressed) == 1


def test_pragma_without_justification_is_a_finding(tmp_path):
    p = _write(tmp_path, "m.py", """\
        import os
        v = os.environ.get("DL4J_TPU_X")  # graftlint: disable=env-knob-contract
        """)
    res = analysis.run([p])
    assert any(f.rule == analysis.PRAGMA_RULE and "justification"
               in f.message for f in res.pragma_findings)
    # an unjustified pragma does NOT suppress
    assert any(f.rule == "env-knob-contract" for f in res.findings)


def test_stale_and_unknown_pragmas_are_findings(tmp_path):
    p = _write(tmp_path, "m.py", """\
        x = 1  # graftlint: disable=env-knob-contract -- suppresses nothing
        y = 2  # graftlint: disable=not-a-rule -- bogus rule name
        """)
    res = analysis.run([p])
    msgs = [f.message for f in res.pragma_findings]
    assert any("suppresses nothing" in m for m in msgs)
    assert any("unknown rule" in m for m in msgs)


# ------------------------------------------------------------------- CLI
def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def test_cli_exit_2_and_json_on_findings(tmp_path):
    p = _write(tmp_path, "dirty.py", """\
        import os
        v = os.environ.get("DL4J_TPU_X")
        """)
    r = _cli("--json", p)
    assert r.returncode == 2, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["files"] == 1
    assert [f["rule"] for f in payload["findings"]] == ["env-knob-contract"]


def test_unparseable_file_is_a_finding_not_clean(tmp_path):
    p = _write(tmp_path, "broken.py", "def oops(:\n")
    res = analysis.run([p])
    assert [f.rule for f in res.findings] == ["parse-error"]
    r = _cli(p)
    assert r.returncode == 2 and "parse-error" in r.stdout


def test_cli_refuses_empty_path_glob(tmp_path):
    """A typo'd path must not read as a permanently-green gate."""
    r = _cli(str(tmp_path / "no_such_dir"))
    assert r.returncode == 1
    assert "nothing was linted" in r.stderr


def test_cli_exit_0_on_clean(tmp_path):
    p = _write(tmp_path, "clean.py", "x = 1\n")
    r = _cli(p)
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_baseline_burn_down_workflow(tmp_path):
    """A new rule lands with --write-baseline; the gate then passes on
    old debt, fails on NEW findings, and reports stale entries when debt
    is paid down."""
    p = _write(tmp_path, "legacy.py", """\
        import os
        v = os.environ.get("DL4J_TPU_OLD")
        """)
    base = str(tmp_path / "baseline.json")
    assert _cli("--write-baseline", base, p).returncode == 0
    assert _cli("--baseline", base, p).returncode == 0     # old debt passes
    _write(tmp_path, "legacy.py", """\
        import os
        v = os.environ.get("DL4J_TPU_OLD")
        w = os.environ.get("DL4J_TPU_NEW")
        """)
    r = _cli("--json", "--baseline", base, p)
    assert r.returncode == 2                               # new finding gates
    payload = json.loads(r.stdout)
    assert len(payload["findings"]) == 1
    assert "DL4J_TPU_NEW" in payload["findings"][0]["message"]
    _write(tmp_path, "legacy.py", "x = 1\n")
    r = _cli("--json", "--baseline", base, p)
    assert r.returncode == 0                               # debt paid
    assert json.loads(r.stdout)["stale_baseline_entries"]  # ...and visible


def test_cli_list_rules_names_all_thirteen():
    r = _cli("--list-rules")
    assert r.returncode == 0
    for name in RULES_BY_NAME:
        assert name in r.stdout
    assert len(RULES_BY_NAME) == 13
    for new in ("lock-order-inversion", "transitive-blocking-under-lock",
                "thread-lifecycle", "resource-pairing"):
        assert new in RULES_BY_NAME


# --------------------------------------------------------- the tier-1 gate
def test_live_tree_is_clean():
    """THE gate: zero unsuppressed findings over the shipped tree. If
    this fails, either fix the finding or suppress it with a justified
    `# graftlint: disable=<rule> -- <why>` pragma."""
    # the gate runs the FULL registry — including the PR-15
    # interprocedural concurrency rules (a select= or trimmed registry
    # would silently narrow the invariant)
    active = {r.name for r in analysis.ALL_RULES}
    for required in ("lock-order-inversion",
                     "transitive-blocking-under-lock",
                     "thread-lifecycle", "resource-pairing"):
        assert required in active
    res = analysis.run([os.path.join(REPO, "deeplearning4j_tpu"),
                        os.path.join(REPO, "tools"),
                        os.path.join(REPO, "bench.py")])
    rendered = "\n".join(f.render(REPO) for f in res.all_unsuppressed)
    assert not res.all_unsuppressed, f"graftlint findings:\n{rendered}"
    # the suite actually ran over the tree (not an empty glob) and the
    # suppression machinery engaged (a count pin would punish future
    # PRs for legitimately deleting suppressed code)
    assert res.files > 100
    assert len(res.suppressed) >= 1
