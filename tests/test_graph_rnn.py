"""ComputationGraph recurrent parity: tBPTT, rnn_time_step, per-input mask
routing (DL4J ComputationGraph.java:2894 doTruncatedBPTT, :2720 rnnTimeStep,
setLayerMaskArrays per-input semantics)."""
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import MultiDataSet
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import (
    GraphBuilder, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener


def _seq_data(n=64, t=8, f=3, nc=2, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, t, f)).astype(np.float32)
    labels = (X.sum((1, 2)) > 0).astype(int)
    Y = np.tile(np.eye(nc, dtype=np.float32)[labels][:, None, :], (1, t, 1))
    return X, Y


def _lstm_graph(tbptt=None, seed=3, t=8):
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(seed)
                      .updater(Adam(1e-2)))
         .add_inputs("in")
         .set_input_types(InputType.recurrent(3, t)))
    g.add_layer("lstm", LSTM(n_out=8), "in")
    g.add_layer("out", RnnOutputLayer(n_out=2), "lstm")
    g.set_outputs("out")
    if tbptt:
        g.backprop_type("tbptt", tbptt, tbptt)
    return ComputationGraph(g.build()).init()


def test_graph_tbptt_trains_and_chunks():
    """char-RNN-as-graph under tBPTT: state carried across chunks, one
    iteration per chunk (ComputationGraph.java:2894)."""
    X, Y = _seq_data(t=8)
    net = _lstm_graph(tbptt=4)
    s = CollectScoresIterationListener()
    net.set_listeners(s)
    net.fit(MultiDataSet((X,), (Y,)), epochs=5)
    # 1 batch * 2 chunks * 5 epochs = 10 iterations
    assert net.iteration_count == 10
    assert s.scores[-1][1] < s.scores[0][1]


def test_graph_tbptt_matches_standard_when_chunk_covers_sequence():
    """fwd_length >= T: tBPTT degenerates to standard BPTT — identical
    parameters after one batch (the carry starts empty and stop_gradient
    never cuts anything)."""
    X, Y = _seq_data(n=16, t=4)
    net_a = _lstm_graph(tbptt=4, t=4)
    net_b = _lstm_graph(tbptt=None, t=4)
    net_a.fit(MultiDataSet((X,), (Y,)), epochs=1)
    net_b.fit(MultiDataSet((X,), (Y,)), epochs=1)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()),
                               rtol=2e-4, atol=2e-5)


def test_graph_rnn_time_step_matches_full_output():
    X, _ = _seq_data(n=4, t=6)
    net = _lstm_graph(t=6)
    full = np.asarray(net.output(X))
    net.rnn_clear_previous_state()
    outs = [np.asarray(net.rnn_time_step(X[:, t, :])) for t in range(6)]
    stepped = np.stack(outs, axis=1)
    np.testing.assert_allclose(stepped, full, rtol=1e-4, atol=1e-5)
    # clearing state restarts the stream
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(X[:, 0, :]))
    np.testing.assert_allclose(again, outs[0], rtol=1e-5, atol=1e-6)


def _two_input_graph(t=5, seed=0):
    """Two differently-masked sequence inputs, each through its own LSTM to
    its own RnnOutputLayer — per-input mask routing is load-bearing both in
    the forward (masked LSTM steps) and in the per-output loss masking."""
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(seed)
                      .updater(Sgd(1e-2)))
         .add_inputs("a", "b")
         .set_input_types(InputType.recurrent(3, t),
                          InputType.recurrent(4, t)))
    g.add_layer("lstm_a", LSTM(n_out=6), "a")
    g.add_layer("lstm_b", LSTM(n_out=6), "b")
    g.add_layer("out_a", RnnOutputLayer(n_out=2), "lstm_a")
    g.add_layer("out_b", RnnOutputLayer(n_out=2), "lstm_b")
    g.set_outputs("out_a", "out_b")
    return ComputationGraph(g.build()).init()


def _two_input_data(t=5, seed=1):
    rs = np.random.RandomState(seed)
    Xa = rs.randn(4, t, 3).astype("float32")
    Xb = rs.randn(4, t, 4).astype("float32")
    Ya = np.eye(2, dtype="float32")[rs.randint(0, 2, (4, t))]
    Yb = np.eye(2, dtype="float32")[rs.randint(0, 2, (4, t))]
    mask_a = np.ones((4, t), np.float32)
    mask_a[:, 3:] = 0                      # input a: only 3 valid steps
    mask_b = np.ones((4, t), np.float32)   # input b: all valid
    return Xa, Xb, Ya, Yb, mask_a, mask_b


def test_graph_per_input_mask_routing_gradcheck():
    """Two sequence inputs with DIFFERENT masks: each RNN vertex must see
    the mask propagated along ITS input path (round-2 VERDICT weak #3: the
    first non-None mask was applied to every RNN vertex)."""
    t = 5
    Xa, Xb, Ya, Yb, mask_a, mask_b = _two_input_data(t)
    net = _two_input_graph(t=t)
    res = check_gradients(net, (Xa, Xb), (Ya, Yb),
                          features_mask=(mask_a, mask_b),
                          max_per_param=8)
    assert res.passed, res.failures[:3]


def test_graph_per_input_mask_is_actually_applied_per_input():
    """Behavioral check: b's LSTM output at steps 3-4 must be alive (its
    mask is all-ones) while a's is zeroed — under the old first-non-None
    routing, mask_a silenced BOTH paths. And the per-output loss must use
    the mask from ITS path: perturbing labels of `a` in a's masked-out
    region leaves the score unchanged, perturbing `b`'s there changes it."""
    t = 5
    Xa, Xb, Ya, Yb, mask_a, mask_b = _two_input_data(t)
    net = _two_input_graph(t=t)

    acts, _, _, _ = net._forward(net.params, net.state, (Xa, Xb), False,
                                 None, fmasks=(mask_a, mask_b))
    assert np.abs(np.asarray(acts["lstm_a"])[:, 3:]).max() == 0.0
    assert np.abs(np.asarray(acts["lstm_b"])[:, 3:]).max() > 1e-4

    def score(ya, yb):
        loss, _ = net._score_fn(net.params, net.state, (Xa, Xb), (ya, yb),
                                (mask_a, mask_b), None, False, None)
        return float(loss)

    base = score(Ya, Yb)
    Ya_pert = Ya.copy()
    Ya_pert[:, 3:] = 1.0 - Ya_pert[:, 3:]   # flip labels in a's dead zone
    assert score(Ya_pert, Yb) == pytest.approx(base, abs=1e-6)
    Yb_pert = Yb.copy()
    Yb_pert[:, 3:] = 1.0 - Yb_pert[:, 3:]   # same steps are LIVE for b
    assert abs(score(Ya, Yb_pert) - base) > 1e-4


def test_graph_multi_step_rnn_time_step():
    """(B, T, F) input to rnn_time_step consumes T steps at once and leaves
    the stream positioned after them."""
    X, _ = _seq_data(n=4, t=6)
    net = _lstm_graph(t=6)
    full = np.asarray(net.output(X))
    net.rnn_clear_previous_state()
    first = np.asarray(net.rnn_time_step(X[:, :4]))   # (B, 4, C)
    rest = np.asarray(net.rnn_time_step(X[:, 4:]))    # (B, 2, C)
    np.testing.assert_allclose(np.concatenate([first, rest], axis=1), full,
                               rtol=1e-4, atol=1e-5)


def test_graph_scan_fit_matches_per_call_bitwise():
    """Input-pipelined (scan_steps>1) ComputationGraph.fit must be
    bit-identical to the per-call path, masks and multi-IO included."""
    import jax
    from deeplearning4j_tpu.data.dataset import MultiDataSet

    Xa, Xb, Ya, Yb, mask_a, mask_b = _two_input_data()
    batches = [MultiDataSet((Xa, Xb), (Ya, Yb), (mask_a, mask_b), None)
               for _ in range(5)]
    a2, b2 = _two_input_graph(), _two_input_graph()
    a2.fit(_Replay(batches), epochs=2)
    b2.fit(_Replay(batches), epochs=2, scan_steps=3)
    flat_a = jax.tree_util.tree_leaves(a2.params)
    flat_b = jax.tree_util.tree_leaves(b2.params)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert a2.iteration_count == b2.iteration_count


class _Replay:
    """Minimal resettable multi-dataset iterator."""
    def __init__(self, batches):
        self.batches = batches
        self._i = 0
    def __iter__(self):
        self._i = 0
        return self
    def __next__(self):
        if self._i >= len(self.batches):
            raise StopIteration
        self._i += 1
        return self.batches[self._i - 1]
    def reset(self):
        self._i = 0
