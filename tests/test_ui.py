"""Observability stack tests — StatsListener -> StatsStorage -> UIServer
(the analog of DL4J's TestStatsListener / TestStatsStorage / ui tests)."""
import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.ui import (
    FileStatsStorage, InMemoryStatsStorage, StatsListener, StatsRecord,
    UIServer,
)


def _train_net(listener, epochs=2):
    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(listener)
    rs = np.random.RandomState(0)
    X = rs.randn(48, 5).astype("float32")
    Y = np.eye(3, dtype="float32")[rs.randint(0, 3, 48)]
    net.fit((X, Y), epochs=epochs, batch_size=16)
    return net


# ------------------------------------------------------------------ storage
def test_stats_storage_round_trip_and_events():
    st = InMemoryStatsStorage()
    events = []
    st.register_stats_storage_listener(lambda ev, r: events.append(ev))
    rec = StatsRecord("sess1", "StatsListener", "w0", 1.0, {"score": 0.5})
    st.put_update(rec)
    st.put_static_info(StatsRecord("sess1", "StatsListener", "w0", 0.5,
                                   {"model_class": "X"}))
    assert st.list_session_ids() == ["sess1"]
    assert st.list_type_ids("sess1") == ["StatsListener"]
    assert st.list_worker_ids("sess1") == ["w0"]
    assert st.get_latest_update("sess1", "StatsListener", "w0").data["score"] == 0.5
    assert st.get_all_updates_after("sess1", "StatsListener", "w0", 0.9)
    assert not st.get_all_updates_after("sess1", "StatsListener", "w0", 1.5)
    assert "new_session" in events and "post_update" in events \
        and "post_static" in events


def test_file_stats_storage_persists(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    st = FileStatsStorage(p)
    for i in range(5):
        st.put_update(StatsRecord("s", "T", "w", float(i), {"i": i}))
    st.put_static_info(StatsRecord("s", "T", "w", 0.0, {"static": True}))
    st.close()
    re = FileStatsStorage(p)       # reload from disk
    assert re.num_updates("s", "T", "w") == 5
    assert re.get_static_info("s", "T", "w").data["static"] is True
    assert re.get_latest_update("s", "T", "w").data["i"] == 4
    re.close()


def test_stats_record_json_round_trip():
    rec = StatsRecord("s", "T", "w", 3.25, {"a": [1, 2], "b": "x"})
    assert StatsRecord.from_json(rec.to_json()) == rec


# ----------------------------------------------------------------- listener
def test_stats_listener_captures_full_stats():
    st = InMemoryStatsStorage()
    lst = StatsListener(st, frequency=1, session_id="t1")
    _train_net(lst)
    static = st.get_static_info("t1", "StatsListener", "worker-0")
    assert static is not None
    assert static.data["model_class"] == "MultiLayerNetwork"
    assert static.data["num_params"] > 0
    n = st.num_updates("t1", "StatsListener", "worker-0")
    assert n == 6            # 48/16 * 2 epochs
    last = st.get_latest_update("t1", "StatsListener", "worker-0").data
    assert np.isfinite(last["score"])
    # per-leaf param/grad/update summaries with histograms
    for group in ("params", "gradients", "updates"):
        assert "0/W" in last[group] and "1/b" in last[group], last[group].keys()
        e = last[group]["0/W"]
        assert e["norm"] > 0 or group == "updates"
        assert len(e["hist"]) == 20
        assert sum(e["hist"]) == 5 * 8     # W is (5, 8)


def test_stats_listener_frequency_thins_records():
    st = InMemoryStatsStorage()
    lst = StatsListener(st, frequency=3, session_id="t2", histograms=False)
    _train_net(lst)                # 6 iterations -> captures at 0 and 3
    assert st.num_updates("t2", "StatsListener", "worker-0") == 2
    last = st.get_latest_update("t2", "StatsListener", "worker-0").data
    assert "hist" not in last["params"]["0/W"]


# ------------------------------------------------------------------- server
def test_ui_server_serves_dashboard_and_data():
    st = InMemoryStatsStorage()
    lst = StatsListener(st, frequency=1, session_id="ui-sess")
    _train_net(lst, epochs=1)
    server = UIServer(port=0)
    try:
        server.attach(st)
        page = urllib.request.urlopen(server.url, timeout=10).read().decode()
        assert "Training Dashboard" in page
        sessions = json.loads(urllib.request.urlopen(
            server.url + "train/sessions", timeout=10).read())
        assert "ui-sess" in sessions["sessions"]
        data = json.loads(urllib.request.urlopen(
            server.url + "train/data?sid=ui-sess&after=0", timeout=10).read())
        assert data["static"]["data"]["num_layers"] == 2
        assert len(data["updates"]) == 3
        assert data["updates"][0]["data"]["iteration"] == 0
        # incremental polling: after=last timestamp -> nothing new
        after = data["updates"][-1]["timestamp"]
        data2 = json.loads(urllib.request.urlopen(
            server.url + f"train/data?sid=ui-sess&after={after}",
            timeout=10).read())
        assert data2["updates"] == []
    finally:
        server.stop()


def test_ui_server_model_tab_and_chart_components():
    """/train/model endpoint + per-layer static detail + the shared
    /assets/charts.js module (TrainModule model-tab parity)."""
    storage = InMemoryStatsStorage()
    listener = StatsListener(storage, frequency=1)
    _train_net(listener)
    server = UIServer(port=0)
    try:
        server.attach(storage)
        base = server.url.rstrip("/")
        model_html = urllib.request.urlopen(
            base + "/train/model", timeout=5).read().decode()
        assert "ltable" in model_html and "charts.js" in model_html
        js = urllib.request.urlopen(
            base + "/assets/charts.js", timeout=5).read().decode()
        for component in ("line", "bars", "kvTable", "grid", "palette"):
            assert component in js
        # overview page uses the SAME shared module (no inline chart code)
        over = urllib.request.urlopen(
            base + "/train", timeout=5).read().decode()
        assert "charts.js" in over and "dl4j.line" in over
        sid = json.loads(urllib.request.urlopen(
            base + "/train/sessions", timeout=5).read())["sessions"][0]
        data = json.loads(urllib.request.urlopen(
            f"{base}/train/data?sid={sid}&after=0", timeout=5).read())
        layers = data["static"]["data"]["layers"]
        assert [l["type"] for l in layers] == ["DenseLayer", "OutputLayer"]
        assert layers[0]["n_params"] == 5 * 8 + 8
        assert layers[0]["shapes"]["W"] == [5, 8]
        # per-layer histograms flow for params, gradients AND updates
        last = data["updates"][-1]["data"]
        for group in ("params", "gradients", "updates"):
            keys = [k for k in last[group] if k.startswith("0/")]
            assert keys, group
            assert "hist" in last[group][keys[0]]
    finally:
        server.stop()


def test_tsne_word2vec_views_and_i18n():
    """Legacy-visualizer parity: /tsne (TsneModule routes), /word2vec
    (NearestNeighborsQuery) and the /i18n catalog."""
    import urllib.error
    from deeplearning4j_tpu.embeddings.vocab import VocabCache
    from deeplearning4j_tpu.embeddings.wordvectors import WordVectors

    server = UIServer(port=0)
    try:
        base = server.url.rstrip("/")
        # --- t-SNE: POST coords (module route) then render data
        pts = [[0.0, 1.0, "a"], [2.0, 3.0, "b"], [4.0, 5.0, "a"]]
        req = urllib.request.Request(
            base + "/tsne/post/run1",
            data=json.dumps({"points": pts}).encode(),
            headers={"Content-Type": "application/json"})
        r = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert r == {"ok": True, "n": 3}
        sessions = json.loads(urllib.request.urlopen(
            base + "/tsne/sessions", timeout=5).read())["sessions"]
        assert sessions == ["run1"]
        coords = json.loads(urllib.request.urlopen(
            base + "/tsne/coords/run1", timeout=5).read())["points"]
        assert coords == pts
        page = urllib.request.urlopen(base + "/tsne", timeout=5).read()
        assert b"dl4j.scatter" in page
        sysp = urllib.request.urlopen(base + "/train/system",
                                      timeout=5).read()
        assert b"Iteration time" in sysp and b"charts.js" in sysp
        # python-side publisher too
        server.post_tsne("run2", np.array([[1.0, 2.0], [3.0, 4.0]]),
                         labels=["x", "y"])
        coords2 = json.loads(urllib.request.urlopen(
            base + "/tsne/coords/run2", timeout=5).read())["points"]
        assert coords2[0] == [1.0, 2.0, "x"]

        # --- word2vec nearest view
        vocab = VocabCache()
        for w, c in (("king", 3), ("queen", 2), ("apple", 1)):
            vocab.add_token(w, count=c)
        vocab.build()
        vecs = np.array([[1.0, 0.0], [0.9, 0.1], [0.0, 1.0]], np.float32)
        server.attach_word_vectors(WordVectors(vocab, vecs))
        res = json.loads(urllib.request.urlopen(
            base + "/word2vec/nearest?word=king&n=2", timeout=5).read())
        assert res["nearest"][0]["word"] == "queen"
        assert res["nearest"][0]["similarity"] > 0.9
        oov = json.loads(urllib.request.urlopen(
            base + "/word2vec/nearest?word=zzz&n=2", timeout=5).read())
        assert "not in vocabulary" in oov["error"]

        # --- i18n catalog in all six reference languages
        for lang, expect in [("en", "overview"), ("de", "Übersicht"),
                             ("ja", "概要"), ("ko", "개요"),
                             ("ru", "обзор"), ("zh", "概览")]:
            cat = json.loads(urllib.request.urlopen(
                base + f"/i18n?lang={lang}", timeout=5).read())
            assert cat["train.nav.overview"] == expect
        # unknown language falls back to english
        cat = json.loads(urllib.request.urlopen(
            base + "/i18n?lang=xx", timeout=5).read())
        assert cat["train.nav.overview"] == "overview"
    finally:
        server.stop()


def test_tsne_routes_handle_encoded_ids_and_bad_bodies():
    import urllib.error
    server = UIServer(port=0)
    try:
        base = server.url.rstrip("/")
        server.post_tsne("run 1", [[0.0, 1.0]])
        got = json.loads(urllib.request.urlopen(
            base + "/tsne/coords/run%201", timeout=5).read())
        assert got["points"] == [[0.0, 1.0]]
        req = urllib.request.Request(
            base + "/tsne/post/x", data=b'{"points": [[1]]}',
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # non-numeric n falls back instead of crashing the handler
        r = json.loads(urllib.request.urlopen(
            base + "/word2vec/nearest?word=x&n=", timeout=5).read())
        assert "error" in r
    finally:
        server.stop()


# ---------------------------------------------------- remote stats router

def test_remote_router_two_process():
    """VERDICT r4 #4: worker stats stream over HTTP into the driver's one
    dashboard. Driver = this process (UIServer + enable_remote_listener);
    worker = a separate OS process posting via RemoteUIStatsStorageRouter
    (reference RemoteUIStatsStorageRouter.java -> RemoteReceiverModule)."""
    import os
    import subprocess
    import sys

    server = UIServer(port=0)
    try:
        storage = server.enable_remote_listener()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        worker = os.path.join(repo, "tests", "_remote_stats_worker.py")
        r = subprocess.run([sys.executable, worker,
                            server.url.rstrip("/"), repo],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "FLUSHED" in r.stdout
        # records landed in the DRIVER's storage...
        assert "remote-sess-1" in storage.list_session_ids()
        ups = storage.get_all_updates_after(
            "remote-sess-1", "StatsListener", "worker-7", 0.0)
        assert len(ups) == 5
        assert ups[0].data["score"] == 1.0
        st = storage.get_static_info("remote-sess-1", "StatsListener",
                                     "worker-7")
        assert st.data["n_params"] == 42
        # ...and render through the normal dashboard data endpoint
        data = json.loads(urllib.request.urlopen(
            server.url + "train/data?sid=remote-sess-1&after=0",
            timeout=10).read())
        assert len(data["updates"]) == 5
    finally:
        server.stop()


def test_remote_router_full_fit_pipeline():
    """The full producer path: a training run whose StatsListener writes
    through the remote router (HTTP) instead of a local storage."""
    from deeplearning4j_tpu.ui.storage import RemoteUIStatsStorageRouter

    server = UIServer(port=0)
    try:
        storage = server.enable_remote_listener()
        router = RemoteUIStatsStorageRouter(server.url.rstrip("/"))
        lst = StatsListener(router, frequency=1, session_id="fit-remote")
        _train_net(lst, epochs=1)
        assert router.flush(timeout=20)
        assert "fit-remote" in storage.list_session_ids()
        ups = storage.get_all_updates_after(
            "fit-remote", "StatsListener",
            storage.list_worker_ids("fit-remote")[0], 0.0)
        assert len(ups) == 3
        assert "score" in ups[0].data
        router.close()
    finally:
        server.stop()


def test_remote_receive_without_listener_enabled_409():
    server = UIServer(port=0)
    try:
        import urllib.error
        req = urllib.request.Request(
            server.url + "remoteReceive",
            data=json.dumps({"records": []}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected HTTP 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
    finally:
        server.stop()
