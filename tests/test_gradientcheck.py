"""Systematic gradient-check matrix — the correctness contract.

Port of the reference's gradcheck strategy
(`deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/`,
16 suites driven by `GradientCheckUtil.java:109-121`): finite-difference
verification of every layer family x {masked, unmasked} x {bias, no-bias},
prioritizing the hand-rolled-math paths where autodiff-through-clever-code
goes wrong: ring/blockwise attention (incl. dropout rng), MoE routing,
YOLO loss, VAE, GravesLSTM peepholes, and every registered loss function.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    LSTM, AutoEncoder, BatchNormalization, Bidirectional, CnnLossLayer,
    ConvolutionLayer, Deconvolution2D, DenseLayer, DepthwiseConvolution2D,
    EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer, GravesLSTM,
    GravesBidirectionalLSTM, LastTimeStep, LocalResponseNormalization,
    LossLayer, MoEFeedForward, MultiHeadAttention, OutputLayer,
    RnnLossLayer, RnnOutputLayer, SeparableConvolution2D, SimpleRnn,
    SubsamplingLayer, TransformerBlock, VariationalAutoencoder,
    Yolo2OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd

RS = np.random.RandomState(12345)


def _net(layers, input_type, l1=0.0, l2=0.0, seed=0):
    b = NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(1e-2))
    if l1:
        b = b.l1(l1)
    if l2:
        b = b.l2(l2)
    lb = b.list()
    for layer in layers:
        lb = lb.layer(layer)
    conf = lb.set_input_type(input_type).build()
    return MultiLayerNetwork(conf).init()


def _check(net, X, Y, fmask=None, lmask=None, n=8, tol=None):
    kwargs = {}
    if tol is not None:
        kwargs["max_rel_error"] = tol
    res = check_gradients(net, X, Y, features_mask=fmask, labels_mask=lmask,
                          max_per_param=n, **kwargs)
    assert res.passed, (res.worst_param, res.max_rel_error, res.failures[:3])
    return res


def _ff_data(n=6, f=5, c=3):
    X = RS.randn(n, f).astype("float32")
    Y = np.eye(c, dtype="float32")[RS.randint(0, c, n)]
    return X, Y


def _rnn_data(n=3, t=5, f=4, c=2):
    X = RS.rand(n, t, f).astype("float32")
    Y = np.eye(c, dtype="float32")[RS.randint(0, c, (n, t))]
    mask = np.ones((n, t), "float32")
    mask[1, 3:] = 0
    mask[2, 2:] = 0
    return X, Y, mask


def _cnn_data(n=3, h=6, w=6, ch=2, c=3):
    X = RS.rand(n, h, w, ch).astype("float32")
    Y = np.eye(c, dtype="float32")[RS.randint(0, c, n)]
    return X, Y


# --------------------------------------------------------------- dense / ff
@pytest.mark.parametrize("has_bias", [True, False],
                         ids=["bias", "nobias"])
def test_gc_dense(has_bias):
    X, Y = _ff_data()
    net = _net([DenseLayer(n_out=7, activation="tanh", has_bias=has_bias),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent",
                            has_bias=has_bias)],
               InputType.feed_forward(5), l1=1e-3, l2=1e-3)
    _check(net, X, Y)


def test_gc_embedding():
    # integer token features -> EmbeddingLayer (gather path)
    X = RS.randint(0, 10, (6, 1)).astype("float32")
    Y = np.eye(3, dtype="float32")[RS.randint(0, 3, 6)]
    net = _net([EmbeddingLayer(n_in=10, n_out=6, activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.feed_forward(1))
    _check(net, X, Y)


def test_gc_autoencoder_supervised():
    X, Y = _ff_data()
    net = _net([AutoEncoder(n_out=4, activation="sigmoid"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.feed_forward(5))
    _check(net, X, Y)


# ---------------------------------------------------------------- conv zoo
def test_gc_conv_same_dilated():
    X, Y = _cnn_data()
    net = _net([ConvolutionLayer(n_out=3, kernel=(3, 3), dilation=(2, 2),
                                 convolution_mode="same", activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_conv_nobias_strided():
    X, Y = _cnn_data()
    net = _net([ConvolutionLayer(n_out=3, kernel=(2, 2), stride=(2, 2),
                                 activation="tanh", has_bias=False),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_separable_conv():
    X, Y = _cnn_data()
    net = _net([SeparableConvolution2D(n_out=4, kernel=(3, 3),
                                       depth_multiplier=2,
                                       convolution_mode="same",
                                       activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_depthwise_conv():
    X, Y = _cnn_data()
    net = _net([DepthwiseConvolution2D(depth_multiplier=2, kernel=(3, 3),
                                       convolution_mode="same",
                                       activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_deconvolution():
    X, Y = _cnn_data()
    net = _net([Deconvolution2D(n_out=3, kernel=(2, 2), stride=(2, 2),
                                activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_subsampling_avg_and_max():
    X, Y = _cnn_data()
    net = _net([ConvolutionLayer(n_out=3, kernel=(3, 3),
                                 convolution_mode="same", activation="tanh"),
                SubsamplingLayer(kernel=(2, 2), stride=(2, 2),
                                 pooling_type="avg"),
                SubsamplingLayer(kernel=(3, 3), stride=(1, 1),
                                 pooling_type="max",
                                 convolution_mode="same"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_cnn_loss_layer():
    # per-pixel softmax head (dense prediction)
    X = RS.rand(2, 4, 4, 2).astype("float32")
    Y = np.eye(3, dtype="float32")[RS.randint(0, 3, (2, 4, 4))]
    net = _net([ConvolutionLayer(n_out=3, kernel=(3, 3),
                                 convolution_mode="same", activation="tanh"),
                CnnLossLayer(activation="softmax", loss="mcxent")],
               InputType.convolutional(4, 4, 2))
    _check(net, X, Y)


# ----------------------------------------------------------- normalization
def test_gc_batchnorm():
    X, Y = _cnn_data()
    net = _net([ConvolutionLayer(n_out=3, kernel=(3, 3),
                                 convolution_mode="same",
                                 activation="identity"),
                BatchNormalization(),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 2))
    _check(net, X, Y)


def test_gc_lrn():
    X, Y = _cnn_data(ch=4)
    net = _net([ConvolutionLayer(n_out=4, kernel=(3, 3),
                                 convolution_mode="same", activation="tanh"),
                LocalResponseNormalization(),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.convolutional(6, 6, 4))
    _check(net, X, Y)


# ------------------------------------------------------------ recurrent zoo
@pytest.mark.parametrize("masked", [False, True], ids=["unmasked", "masked"])
def test_gc_graves_lstm(masked):
    # peephole connections are the hand-written-math hotspot
    X, Y, mask = _rnn_data()
    net = _net([GravesLSTM(n_out=5, activation="tanh"),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, fmask=mask if masked else None)


@pytest.mark.parametrize("masked", [False, True], ids=["unmasked", "masked"])
def test_gc_graves_bidirectional_lstm(masked):
    X, Y, mask = _rnn_data()
    net = _net([GravesBidirectionalLSTM(n_out=4),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, fmask=mask if masked else None)


def test_gc_simple_rnn_bidirectional():
    X, Y, mask = _rnn_data()
    net = _net([Bidirectional(layer=SimpleRnn(n_out=4, activation="tanh"),
                              mode="concat"),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, fmask=mask)


def test_gc_lstm_last_time_step_global_pool():
    # LastTimeStep + masked global pooling both reduce (B,T,F) -> (B,F)
    X, _, mask = _rnn_data()
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, 3)]
    net = _net([LastTimeStep(layer=LSTM(n_out=5)),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, fmask=mask)
    net2 = _net([LSTM(n_out=5),
                 GlobalPoolingLayer(pooling_type="avg"),
                 OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                InputType.recurrent(4, 5))
    _check(net2, X, Y, fmask=mask)


def test_gc_rnn_loss_layer_label_masked():
    X, _, _ = _rnn_data()
    Y = RS.rand(3, 5, 4).astype("float32")
    lmask = np.ones((3, 5), "float32")
    lmask[:, -2:] = 0
    net = _net([LSTM(n_out=4, activation="tanh"),
                RnnLossLayer(activation="identity", loss="mse")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, lmask=lmask)


# ------------------------------------------------- attention / transformer
def test_gc_multi_head_attention():
    X = RS.rand(2, 6, 8).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, (2, 6))]
    net = _net([MultiHeadAttention(n_out=8, n_heads=2, causal=True),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(8, 6))
    _check(net, X, Y)


def test_gc_transformer_block_blockwise():
    # blockwise (online-softmax scan) attention inside a full block
    X = RS.rand(2, 8, 8).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, (2, 8))]
    net = _net([TransformerBlock(n_out=8, n_heads=2,
                                 attention_impl="blockwise", block_size=4),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(8, 8))
    _check(net, X, Y)


def test_gc_moe():
    # top-k routing: gradients flow through selected experts + gate
    X = RS.rand(2, 4, 8).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, (2, 4))]
    net = _net([MoEFeedForward(n_out=8, n_experts=4, top_k=2),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(8, 4))
    _check(net, X, Y)


def test_gc_embedding_sequence_transformer():
    X = RS.randint(0, 12, (2, 6)).astype("float32")
    Y = np.eye(12, dtype="float32")[RS.randint(0, 12, (2, 6))]
    net = _net([EmbeddingSequenceLayer(n_in=12, n_out=8),
                TransformerBlock(n_out=8, n_heads=2),
                RnnOutputLayer(n_out=12, activation="softmax",
                               loss="mcxent")],
               InputType.recurrent(1, 6))
    _check(net, X, Y)


# ----------------------------------------------------------- VAE and YOLO
def test_gc_vae_supervised():
    X, Y = _ff_data()
    net = _net([VariationalAutoencoder(n_out=3, encoder_layer_sizes=(6,),
                                       decoder_layer_sizes=(6,)),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.feed_forward(5))
    _check(net, X, Y)


def test_gc_vae_pretrain_elbo():
    # the reparameterized ELBO itself (VaeGradientCheckTests analog):
    # fixed rng makes the loss deterministic, so FD is valid
    layer = VariationalAutoencoder(n_out=3, encoder_layer_sizes=(6,),
                                   decoder_layer_sizes=(6,))
    from jax import config as jc
    jc.update("jax_enable_x64", True)
    try:
        params, _ = layer.init(jax.random.PRNGKey(0),
                               InputType.feed_forward(5), jnp.float64)
        x = jnp.asarray(RS.rand(4, 5), jnp.float64)
        rng = jax.random.PRNGKey(7)

        @jax.jit
        def loss(p):
            return layer.pretrain_score(p, x, rng)

        analytic = jax.jit(jax.grad(loss))(params)
        _fd_sweep(loss, params, analytic, per_leaf=4)
    finally:
        jc.update("jax_enable_x64", False)


def _fd_sweep(loss, params, analytic, per_leaf=3, eps=1e-6, tol=1e-3):
    """FD-check `per_leaf` random entries of every leaf of `params`."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [leaf for _, leaf in flat]
    aleaves = jax.tree_util.tree_leaves(analytic)
    checked = 0
    for leaf_idx, ((path, leaf), g) in enumerate(zip(flat, aleaves)):
        leaf_np = np.asarray(leaf)
        for flat_i in RS.choice(leaf_np.size,
                                min(per_leaf, leaf_np.size), replace=False):
            i = np.unravel_index(flat_i, leaf_np.shape)

            def at(v):
                pl = leaf_np.copy()
                pl[i] = v
                new_leaves = list(leaves)
                new_leaves[leaf_idx] = jnp.asarray(pl)
                return jax.tree_util.tree_unflatten(treedef, new_leaves)

            num = (float(loss(at(leaf_np[i] + eps))) -
                   float(loss(at(leaf_np[i] - eps)))) / (2 * eps)
            ana = float(np.asarray(g)[i])
            denom = abs(num) + abs(ana)
            assert denom < 1e-8 or abs(num - ana) / denom < tol, \
                (path, i, ana, num)
            checked += 1
    return checked


def test_gc_yolo_loss():
    # YoloGradientCheckTests analog: conv backbone + YOLOv2 loss head
    B, C = 2, 2                   # 2 anchors, 2 classes
    X = RS.rand(2, 4, 4, 3).astype("float32")
    Y = np.zeros((2, 2, 2, 4 + C), "float32")
    Y[0, 0, 0] = [0.1, 0.1, 0.9, 0.9, 1, 0]
    Y[1, 1, 1] = [1.2, 1.2, 1.9, 1.8, 0, 1]
    net = _net([ConvolutionLayer(n_out=B * (5 + C), kernel=(3, 3),
                                 stride=(2, 2), convolution_mode="same",
                                 activation="identity"),
                Yolo2OutputLayer(anchors=((1.0, 1.0), (0.5, 0.5)),
                                 n_classes=C)],
               InputType.convolutional(4, 4, 3))
    _check(net, X, Y, tol=2e-3)


# ------------------------------------------------------------- loss sweep
_LOSS_CASES = [
    ("mse", "identity"), ("mae", "identity"), ("l1", "identity"),
    ("l2", "identity"), ("xent", "sigmoid"), ("mcxent", "softmax"),
    ("negativeloglikelihood", "softmax"), ("kl_divergence", "softmax"),
    ("poisson", "softplus"), ("cosine_proximity", "identity"),
    ("hinge", "identity"), ("squared_hinge", "identity"),
]


@pytest.mark.parametrize("loss,act", _LOSS_CASES,
                         ids=[c[0] for c in _LOSS_CASES])
def test_gc_loss_functions(loss, act):
    # LossFunctionGradientCheck analog: every registered loss through a
    # small MLP head
    X = RS.randn(5, 4).astype("float32")
    if loss in ("xent",):
        Y = (RS.rand(5, 3) > 0.5).astype("float32")
    elif loss in ("mcxent", "negativeloglikelihood", "kl_divergence"):
        Y = np.eye(3, dtype="float32")[RS.randint(0, 3, 5)]
    elif loss in ("hinge", "squared_hinge"):
        Y = (2 * (RS.rand(5, 3) > 0.5) - 1).astype("float32")
    elif loss == "poisson":
        Y = RS.poisson(2.0, (5, 3)).astype("float32")
    else:
        Y = RS.randn(5, 3).astype("float32")
    net = _net([DenseLayer(n_out=6, activation="tanh"),
                OutputLayer(n_out=3, activation=act, loss=loss)],
               InputType.feed_forward(4))
    _check(net, X, Y)


# ------------------------------------------- ring / blockwise (functional)
def test_gc_ring_attention_fd():
    """FD-check the ring-attention primitive itself on an 8-device seq mesh
    (the shard_map + ppermute + online-softmax path has no autodiff-free
    reference; the numeric gradient IS the oracle)."""
    from deeplearning4j_tpu.parallel import MeshConfig, build_mesh
    from deeplearning4j_tpu.parallel.ring import make_ring_attention
    from jax import config as jc
    jc.update("jax_enable_x64", True)
    try:
        mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
        attend = make_ring_attention(mesh, causal=True)
        q = jnp.asarray(RS.rand(1, 16, 2, 4), jnp.float64)
        k = jnp.asarray(RS.rand(1, 16, 2, 4), jnp.float64)
        v = jnp.asarray(RS.rand(1, 16, 2, 4), jnp.float64)
        w = jnp.asarray(RS.rand(1, 16, 2, 4), jnp.float64)  # fixed probe

        @jax.jit
        def loss(q_):
            return jnp.sum(attend(q_, k, v) * w)

        analytic = np.asarray(jax.jit(jax.grad(loss))(q))
        eps = 1e-6
        qn = np.asarray(q)
        for flat_i in RS.choice(qn.size, 10, replace=False):
            i = np.unravel_index(flat_i, qn.shape)
            qp, qm = qn.copy(), qn.copy()
            qp[i] += eps
            qm[i] -= eps
            num = (float(loss(jnp.asarray(qp))) -
                   float(loss(jnp.asarray(qm)))) / (2 * eps)
            ana = analytic[i]
            denom = abs(num) + abs(ana)
            assert denom < 1e-8 or abs(num - ana) / denom < 1e-3, \
                (i, ana, num)
    finally:
        jc.update("jax_enable_x64", False)


def test_gc_attention_dropout_fixed_rng():
    """Attention dropout path: with a FIXED rng the loss is deterministic,
    so FD still applies — this is the dropout-rng-through-autodiff check
    the round-1 verdict called out."""
    from jax import config as jc
    jc.update("jax_enable_x64", True)
    try:
        layer = TransformerBlock(n_out=8, n_heads=2, attention_dropout=0.25,
                                 residual_dropout=0.25)
        params, state = layer.init(jax.random.PRNGKey(0),
                                   InputType.recurrent(8, 6), jnp.float64)
        x = jnp.asarray(RS.rand(2, 6, 8), jnp.float64)
        rng = jax.random.PRNGKey(11)

        @jax.jit
        def loss(p):
            y, _ = layer.apply(p, state, x, train=True, rng=rng)
            return jnp.sum(y ** 2)

        analytic = jax.jit(jax.grad(loss))(params)
        assert _fd_sweep(loss, params, analytic, per_leaf=3) >= 20
    finally:
        jc.update("jax_enable_x64", False)


@pytest.mark.parametrize("reset_after", [True, False],
                         ids=["reset_after", "classic"])
def test_gc_gru(reset_after):
    from deeplearning4j_tpu.nn.layers import GRU
    X, Y, mask = _rnn_data()
    net = _net([GRU(n_out=5, reset_after=reset_after),
                RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(4, 5))
    _check(net, X, Y, fmask=mask)


def test_gc_locally_connected_1d():
    from deeplearning4j_tpu.nn.layers import (
        GlobalPoolingLayer, LocallyConnected1D,
    )
    X = RS.randn(4, 6, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, 4)]
    net = _net([LocallyConnected1D(n_out=4, kernel=3, activation="tanh"),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(3, 6))
    _check(net, X, Y)


def test_gc_locally_connected_2d():
    from deeplearning4j_tpu.nn.layers import (
        GlobalPoolingLayer, LocallyConnected2D,
    )
    X = RS.randn(3, 5, 5, 2).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, 3)]
    net = _net([LocallyConnected2D(n_out=3, kernel=(2, 2),
                                   activation="tanh"),
                GlobalPoolingLayer(pooling_type="max"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.convolutional(5, 5, 2))
    _check(net, X, Y)


def test_gc_repeat_permute_reshape_chain():
    from deeplearning4j_tpu.nn.layers import (
        GlobalPoolingLayer, PermuteLayer, RepeatVector, ReshapeLayer,
    )
    X = RS.randn(4, 6).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, 4)]
    net = _net([DenseLayer(n_out=6, activation="tanh"),
                RepeatVector(n=4),          # (B, 4, 6)
                PermuteLayer(dims=(2, 1)),  # (B, 6, 4)
                ReshapeLayer(target=(8, 3)),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.feed_forward(6))
    _check(net, X, Y)


def test_gc_cropping_padding_upsampling_1d():
    from deeplearning4j_tpu.nn.layers import (
        Cropping1D, GlobalPoolingLayer, Upsampling1D, ZeroPadding1DLayer,
    )
    X = RS.randn(3, 8, 3).astype("float32")
    Y = np.eye(2, dtype="float32")[RS.randint(0, 2, 3)]
    net = _net([Cropping1D(cropping=(1, 2)),
                Upsampling1D(size=2),
                ZeroPadding1DLayer(padding=(1, 1)),
                LSTM(n_out=5),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent")],
               InputType.recurrent(3, 8))
    _check(net, X, Y)


# ------------------------------------------------- round-5 parity closers
def test_gc_elementwise_multiplication():
    """ElementWiseMultiplicationLayer: out = act(x * w + b)
    (reference nn/conf/layers/misc/ElementWiseMultiplicationLayer.java)."""
    from deeplearning4j_tpu.nn.layers import ElementWiseMultiplicationLayer
    X, Y = _ff_data()
    net = _net([DenseLayer(n_out=6, activation="tanh"),
                ElementWiseMultiplicationLayer(n_out=6, activation="sigmoid"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
               InputType.feed_forward(5), l1=1e-3, l2=1e-3)
    _check(net, X, Y)


def test_gc_poolhelper_vertex():
    """PoolHelperVertex strips the first spatial row/col inside a graph
    (reference nn/conf/graph/PoolHelperVertex.java)."""
    from deeplearning4j_tpu.nn.conf.graph_vertices import PoolHelperVertex
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import ConvolutionLayer

    X, Y = _cnn_data(n=3, h=6, w=6, ch=2, c=3)
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(0)
                      .updater(Sgd(1e-2)))
         .add_inputs("in")
         .set_input_types(InputType.convolutional(6, 6, 2)))
    g.add_layer("c", ConvolutionLayer(n_out=3, kernel=(3, 3),
                                      convolution_mode="same",
                                      activation="tanh"), "in")
    g.add_vertex("ph", PoolHelperVertex(), "c")
    g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"), "ph")
    g.set_outputs("out")
    gn = ComputationGraph(g.build()).init()
    # shape: 6x6 conv-same -> 6x6, poolhelper -> 5x5
    res = check_gradients(gn, X, Y, max_per_param=24)
    assert res.passed, (res.worst_param, res.max_rel_error, res.failures[:3])
