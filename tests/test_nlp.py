"""NLP tests (DL4J deeplearning4j-nlp test strategy: small corpora, check
vocab/similarity structure rather than absolute numbers)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.embeddings import (
    Glove, ParagraphVectors, VocabCache, Word2Vec, WordVectors,
)
from deeplearning4j_tpu.text import (
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, NGramTokenizerFactory, STOP_WORDS,
)


def _toy_corpus(n_sent=300, seed=0):
    """Two topic clusters: {cat, dog, pet} and {car, bus, road} co-occur
    within topics, never across — embeddings must separate them."""
    rs = np.random.RandomState(seed)
    animals = ["cat", "dog", "pet", "fur", "tail"]
    vehicles = ["car", "bus", "road", "wheel", "engine"]
    sents = []
    for _ in range(n_sent):
        pool = animals if rs.rand() < 0.5 else vehicles
        sents.append(" ".join(rs.choice(pool, 6)))
    return sents


# ------------------------------------------------------------------- text
def test_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    assert tf.tokenize("Hello, World! 123") == ["hello", "world"]
    ng = NGramTokenizerFactory(min_n=1, max_n=2)
    toks = ng.tokenize("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_sentence_iterators(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("first sentence\n\nsecond sentence\n")
    it = BasicLineIterator(str(p))
    assert list(it) == ["first sentence", "second sentence"]
    ci = CollectionSentenceIterator(["a", "b"])
    assert list(ci) == ["a", "b"]
    assert "the" in STOP_WORDS


# ------------------------------------------------------------------ vocab
def test_vocab_build_and_huffman():
    v = VocabCache()
    for w, c in (("the", 100), ("cat", 10), ("dog", 8), ("rare", 1)):
        v.add_token(w, c)
    v.build(min_count=2)
    assert len(v) == 3
    assert v.index_of("the") == 0          # most frequent first
    assert v.index_of("rare") == -1
    v.build_huffman()
    vws = v.vocab_words()
    # frequent word gets a shorter code
    assert len(vws[0].codes) <= len(vws[-1].codes)
    # codes are prefix-free: no code is a prefix of another
    codes = ["".join(map(str, w.codes)) for w in vws]
    for i, a in enumerate(codes):
        for j, b in enumerate(codes):
            if i != j:
                assert not b.startswith(a)


def test_unigram_table_powers():
    v = VocabCache()
    v.add_token("a", 100)
    v.add_token("b", 1)
    v.build()
    t = v.unigram_table()
    assert t[0] > t[1] and abs(t.sum() - 1) < 1e-6


# --------------------------------------------------------------- word2vec
def test_word2vec_separates_topics():
    w2v = Word2Vec(layer_size=32, window=3, min_count=2, negative=5,
                   epochs=40, seed=1)
    w2v.fit(CollectionSentenceIterator(_toy_corpus()))
    assert len(w2v.vocab) == 10
    same = w2v.similarity("cat", "dog")
    cross = w2v.similarity("cat", "car")
    assert same > cross, (same, cross)
    near = w2v.words_nearest("cat", 4)
    assert set(near).issubset({"dog", "pet", "fur", "tail"}), near


def test_word2vec_cbow_and_hs():
    corpus = CollectionSentenceIterator(_toy_corpus(200, seed=2))
    cbow = Word2Vec(layer_size=24, window=3, min_count=2, negative=5,
                    elements_learning_algorithm="cbow", epochs=40, seed=2)
    cbow.fit(corpus)
    assert cbow.similarity("bus", "road") > cbow.similarity("bus", "dog")
    hs = Word2Vec(layer_size=24, window=3, min_count=2, negative=0,
                  use_hierarchic_softmax=True, epochs=40, seed=3)
    hs.fit(corpus)
    assert hs.similarity("cat", "pet") > hs.similarity("cat", "engine")


def test_word2vec_cbow_hs_learns():
    # CBOW + hierarchical softmax: context-window mean predicts the center
    # word's Huffman path (was degenerate self-prediction pre-round-2)
    corpus = CollectionSentenceIterator(_toy_corpus(200, seed=4))
    m = Word2Vec(layer_size=24, window=3, min_count=2, negative=0,
                 use_hierarchic_softmax=True,
                 elements_learning_algorithm="cbow", epochs=40, seed=4)
    m.fit(corpus)
    assert m.similarity("cat", "pet") > m.similarity("cat", "engine")
    assert m.similarity("bus", "road") > m.similarity("bus", "fur")


def test_word_vectors_serde(tmp_path):
    w2v = Word2Vec(layer_size=16, min_count=1, epochs=1, seed=0)
    w2v.fit(CollectionSentenceIterator(_toy_corpus(50)))
    p = str(tmp_path / "vecs.txt")
    w2v.save_text(p)
    loaded = WordVectors.load_text(p)
    assert len(loaded.vocab) == len(w2v.vocab)
    np.testing.assert_allclose(loaded.get_word_vector("cat"),
                               w2v.get_word_vector("cat"), atol=1e-5)


# ------------------------------------------------------- paragraph vectors
def test_paragraph_vectors_labels():
    docs = []
    rs = np.random.RandomState(0)
    animals = ["cat", "dog", "pet", "fur"]
    vehicles = ["car", "bus", "road", "wheel"]
    for i in range(40):
        docs.append((f"animal_{i}", " ".join(rs.choice(animals, 8))))
        docs.append((f"vehicle_{i}", " ".join(rs.choice(vehicles, 8))))
    pv = ParagraphVectors(layer_size=24, min_count=1, negative=5, epochs=20,
                          learning_rate=0.5, seed=4)
    pv.fit(docs)
    assert len(pv.labels) == 80
    near = pv.nearest_labels("cat dog fur pet cat dog", top_n=10)
    animal_hits = sum(1 for lbl in near if lbl.startswith("animal"))
    assert animal_hits >= 7, near


# ------------------------------------------------------------------ glove
def test_glove_separates_topics():
    g = Glove(layer_size=24, window=4, min_count=2, epochs=20,
              batch_size=256, seed=5)
    g.fit(CollectionSentenceIterator(_toy_corpus(300, seed=5)))
    assert np.isfinite(g.last_loss)
    assert g.similarity("cat", "dog") > g.similarity("cat", "car")
