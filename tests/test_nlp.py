"""NLP tests (DL4J deeplearning4j-nlp test strategy: small corpora, check
vocab/similarity structure rather than absolute numbers)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.embeddings import (
    Glove, ParagraphVectors, VocabCache, Word2Vec, WordVectors,
)
from deeplearning4j_tpu.text import (
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, NGramTokenizerFactory, STOP_WORDS,
)


def _toy_corpus(n_sent=300, seed=0):
    """Two topic clusters: {cat, dog, pet} and {car, bus, road} co-occur
    within topics, never across — embeddings must separate them."""
    rs = np.random.RandomState(seed)
    animals = ["cat", "dog", "pet", "fur", "tail"]
    vehicles = ["car", "bus", "road", "wheel", "engine"]
    sents = []
    for _ in range(n_sent):
        pool = animals if rs.rand() < 0.5 else vehicles
        sents.append(" ".join(rs.choice(pool, 6)))
    return sents


# ------------------------------------------------------------------- text
def test_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    assert tf.tokenize("Hello, World! 123") == ["hello", "world"]
    ng = NGramTokenizerFactory(min_n=1, max_n=2)
    toks = ng.tokenize("a b c")
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_sentence_iterators(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("first sentence\n\nsecond sentence\n")
    it = BasicLineIterator(str(p))
    assert list(it) == ["first sentence", "second sentence"]
    ci = CollectionSentenceIterator(["a", "b"])
    assert list(ci) == ["a", "b"]
    assert "the" in STOP_WORDS


# ------------------------------------------------------------------ vocab
def test_vocab_build_and_huffman():
    v = VocabCache()
    for w, c in (("the", 100), ("cat", 10), ("dog", 8), ("rare", 1)):
        v.add_token(w, c)
    v.build(min_count=2)
    assert len(v) == 3
    assert v.index_of("the") == 0          # most frequent first
    assert v.index_of("rare") == -1
    v.build_huffman()
    vws = v.vocab_words()
    # frequent word gets a shorter code
    assert len(vws[0].codes) <= len(vws[-1].codes)
    # codes are prefix-free: no code is a prefix of another
    codes = ["".join(map(str, w.codes)) for w in vws]
    for i, a in enumerate(codes):
        for j, b in enumerate(codes):
            if i != j:
                assert not b.startswith(a)


def test_unigram_table_powers():
    v = VocabCache()
    v.add_token("a", 100)
    v.add_token("b", 1)
    v.build()
    t = v.unigram_table()
    assert t[0] > t[1] and abs(t.sum() - 1) < 1e-6


# --------------------------------------------------------------- word2vec
def test_word2vec_separates_topics():
    w2v = Word2Vec(layer_size=32, window=3, min_count=2, negative=5,
                   epochs=40, seed=1)
    w2v.fit(CollectionSentenceIterator(_toy_corpus()))
    assert len(w2v.vocab) == 10
    same = w2v.similarity("cat", "dog")
    cross = w2v.similarity("cat", "car")
    assert same > cross, (same, cross)
    near = w2v.words_nearest("cat", 4)
    assert set(near).issubset({"dog", "pet", "fur", "tail"}), near


def test_word2vec_cbow_and_hs():
    corpus = CollectionSentenceIterator(_toy_corpus(200, seed=2))
    cbow = Word2Vec(layer_size=24, window=3, min_count=2, negative=5,
                    elements_learning_algorithm="cbow", epochs=40, seed=2)
    cbow.fit(corpus)
    assert cbow.similarity("bus", "road") > cbow.similarity("bus", "dog")
    hs = Word2Vec(layer_size=24, window=3, min_count=2, negative=0,
                  use_hierarchic_softmax=True, epochs=40, seed=3)
    hs.fit(corpus)
    assert hs.similarity("cat", "pet") > hs.similarity("cat", "engine")


def test_word2vec_cbow_hs_learns():
    # CBOW + hierarchical softmax: context-window mean predicts the center
    # word's Huffman path (was degenerate self-prediction pre-round-2)
    corpus = CollectionSentenceIterator(_toy_corpus(200, seed=4))
    m = Word2Vec(layer_size=24, window=3, min_count=2, negative=0,
                 use_hierarchic_softmax=True,
                 elements_learning_algorithm="cbow", epochs=40, seed=4)
    m.fit(corpus)
    assert m.similarity("cat", "pet") > m.similarity("cat", "engine")
    assert m.similarity("bus", "road") > m.similarity("bus", "fur")


def test_word_vectors_serde(tmp_path):
    w2v = Word2Vec(layer_size=16, min_count=1, epochs=1, seed=0)
    w2v.fit(CollectionSentenceIterator(_toy_corpus(50)))
    p = str(tmp_path / "vecs.txt")
    w2v.save_text(p)
    loaded = WordVectors.load_text(p)
    assert len(loaded.vocab) == len(w2v.vocab)
    np.testing.assert_allclose(loaded.get_word_vector("cat"),
                               w2v.get_word_vector("cat"), atol=1e-5)


# ------------------------------------------------------- paragraph vectors
def test_paragraph_vectors_labels():
    docs = []
    rs = np.random.RandomState(0)
    animals = ["cat", "dog", "pet", "fur"]
    vehicles = ["car", "bus", "road", "wheel"]
    for i in range(40):
        docs.append((f"animal_{i}", " ".join(rs.choice(animals, 8))))
        docs.append((f"vehicle_{i}", " ".join(rs.choice(vehicles, 8))))
    pv = ParagraphVectors(layer_size=24, min_count=1, negative=5, epochs=20,
                          learning_rate=0.5, seed=4)
    pv.fit(docs)
    assert len(pv.labels) == 80
    near = pv.nearest_labels("cat dog fur pet cat dog", top_n=10)
    animal_hits = sum(1 for lbl in near if lbl.startswith("animal"))
    assert animal_hits >= 7, near


# ------------------------------------------------------------------ glove
def test_glove_separates_topics():
    g = Glove(layer_size=24, window=4, min_count=2, epochs=20,
              batch_size=256, seed=5)
    g.fit(CollectionSentenceIterator(_toy_corpus(300, seed=5)))
    assert np.isfinite(g.last_loss)
    assert g.similarity("cat", "dog") > g.similarity("cat", "car")


# ------------------------------------------------- document iterators / BoW
def _labelled_corpus(n_per=30, seed=11):
    """Synthetic 3-topic labelled corpus with overlapping filler words."""
    rs = np.random.RandomState(seed)
    topics = {
        "sports": ["ball", "goal", "team", "match", "score", "coach"],
        "finance": ["stock", "market", "bond", "yield", "bank", "trade"],
        "cooking": ["oven", "spice", "recipe", "flour", "butter", "salt"],
    }
    filler = ["the", "a", "of", "and", "to", "in"]
    docs = []
    for label, words in topics.items():
        for _ in range(n_per):
            body = list(rs.choice(words, 10)) + list(rs.choice(filler, 5))
            rs.shuffle(body)
            docs.append((" ".join(body), label))
    rs.shuffle(docs)
    return docs


def test_document_iterators(tmp_path):
    from deeplearning4j_tpu.text import (
        BasicLabelAwareIterator, FileLabelAwareIterator,
        SimpleLabelAwareIterator,
    )
    it = SimpleLabelAwareIterator([("hello world", "a"), ("bye", "b")])
    docs = list(it)
    assert [d.label for d in docs] == ["a", "b"]
    assert it.labels_source.index_of("b") == 1

    it2 = BasicLabelAwareIterator(["s one", "s two", "s three"])
    assert [d.label for d in it2] == ["DOC_0", "DOC_1", "DOC_2"]

    (tmp_path / "pos").mkdir()
    (tmp_path / "neg").mkdir()
    (tmp_path / "pos" / "0.txt").write_text("good great fine")
    (tmp_path / "neg" / "0.txt").write_text("bad awful poor")
    it3 = FileLabelAwareIterator(str(tmp_path))
    docs3 = {d.label: d.content for d in it3}
    assert "good" in docs3["pos"] and "awful" in docs3["neg"]
    assert it3.labels_source.get_labels() == ["neg", "pos"]


def test_inverted_index():
    from deeplearning4j_tpu.text import InMemoryInvertedIndex
    idx = InMemoryInvertedIndex()
    idx.add_doc(0, ["cat", "dog", "cat"])
    idx.add_doc(1, ["dog", "bird"])
    assert idx.num_documents() == 2
    assert idx.doc_appeared_in("cat") == 1
    assert idx.doc_appeared_in("dog") == 2
    assert idx.term_frequency("cat", 0) == 2
    assert idx.total_term_frequency("cat") == 2
    assert idx.search("dog") == [0, 1]
    assert idx.search("dog", "cat") == [0]
    assert idx.search("fish") == []


def test_bag_of_words_counts():
    from deeplearning4j_tpu.text import BagOfWordsVectorizer
    bow = BagOfWordsVectorizer([("cat cat dog", "x"), ("dog bird", "y")])
    bow.fit()
    assert bow.vocab == ["bird", "cat", "dog"]
    row = bow.transform("cat cat cat bird")[0]
    np.testing.assert_allclose(row, [1.0, 3.0, 0.0])


def test_tfidf_reference_formula():
    """tf = count/len, idf = log10(N/df), weight = tf*idf — the exact
    MathUtils.java:258-286 arithmetic."""
    import math
    from deeplearning4j_tpu.text import TfidfVectorizer
    tv = TfidfVectorizer([("cat dog", "x"), ("dog bird", "y"),
                          ("dog dog dog", "z")])
    tv.fit()
    assert tv.idf("dog") == 0.0                      # in all 3 docs
    assert tv.idf("cat") == pytest.approx(math.log10(3.0))
    row = tv.transform(["cat", "cat", "dog", "bird"])[0]
    v = {w: row[tv.index_of(w)] for w in ("cat", "dog", "bird")}
    assert v["cat"] == pytest.approx(0.5 * math.log10(3.0), rel=1e-6)
    assert v["dog"] == 0.0
    assert v["bird"] == pytest.approx(0.25 * math.log10(3.0), rel=1e-6)


def test_tfidf_min_word_frequency_and_stopwords():
    from deeplearning4j_tpu.text import TfidfVectorizer
    tv = TfidfVectorizer([("the cat cat", "x"), ("the dog", "y")],
                         min_word_frequency=2, stop_words=["the"])
    tv.fit()
    assert tv.vocab == ["cat"]        # "the" stopped, "dog" below min freq


def test_tfidf_classifier_end_to_end():
    """The reference's text-classification on-ramp: labelled corpus ->
    TfidfVectorizer -> OutputLayer softmax classifier trains to high
    accuracy (TfidfVectorizer feeding MultiLayerNetwork)."""
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.text import TfidfVectorizer

    tv = TfidfVectorizer(_labelled_corpus(), min_word_frequency=2)
    tv.fit()
    ds = tv.vectorize()
    assert ds.features.shape[0] == 90
    assert ds.labels.shape == (90, 3)
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(5e-2)).list()
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(ds.features.shape[1]))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ArrayDataSetIterator(ds.features, ds.labels, batch_size=32),
            epochs=20)
    acc = net.evaluate((ds.features, ds.labels)).accuracy()
    assert acc > 0.95, acc
    # single-document vectorize round-trip
    one = tv.vectorize("goal match team ball", "sports")
    assert one.features.shape == (1, ds.features.shape[1])
    assert one.labels[0, tv.labels_source.index_of("sports")] == 1.0


def test_tfidf_transform_consistent_with_corpus_path():
    """transform() must filter stop words like fit() did, and fit() must be
    re-runnable (rebuilds index + labels from scratch)."""
    from deeplearning4j_tpu.text import TfidfVectorizer
    tv = TfidfVectorizer([("the cat", "x"), ("the dog", "y")],
                         stop_words=["the"])
    tv.fit()
    corpus = tv.vectorize()
    row = tv.transform("the cat")[0]
    np.testing.assert_allclose(row, corpus.features[0], atol=1e-7)
    tv.fit()                                   # refit does not corrupt
    assert tv.index.num_documents() == 2
    np.testing.assert_allclose(tv.transform("the cat")[0], row, atol=1e-7)


def test_spark_word2vec_partition_parallel():
    """Partition-parallel word2vec with per-epoch table averaging (the
    dl4j-spark-nlp Word2Vec flow: broadcast vocab, per-partition training,
    fold results)."""
    from deeplearning4j_tpu.embeddings import SparkWord2Vec
    w2v = SparkWord2Vec(n_workers=4, layer_size=32, window=3, min_count=2,
                        negative=5, epochs=30, seed=7)
    w2v.fit(CollectionSentenceIterator(_toy_corpus(500, seed=7)))
    assert len(w2v.vocab) == 10
    # averaged tables must carry the topic structure: same-topic pairs
    # beat cross-topic pairs across the board
    vehicles = {"bus", "road", "wheel", "engine"}
    for a, b, c in (("cat", "dog", "car"), ("bus", "road", "pet"),
                    ("car", "wheel", "fur")):
        assert w2v.similarity(a, b) > w2v.similarity(a, c), (a, b, c)
    near = w2v.words_nearest("car", 3)
    assert len(vehicles.intersection(near)) >= 2, near
