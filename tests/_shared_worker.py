"""Worker process for the 2-process encoded-gradient convergence test.

Each OS process is one logical pod: it computes gradients on its own batch
shard, exchanges threshold-encoded messages with its peer over the TCP
SocketTransport, and applies the identical decoded sum — the in-tree analog
of one Spark executor in the reference's SharedTrainingMaster topology
(SharedTrainingWrapper.java:206-244).

Usage: python tests/_shared_worker.py RANK N_WORKERS BASE_PORT OUT.npz
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf.base import InputType  # noqa: E402
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.updaters import Sgd  # noqa: E402
from deeplearning4j_tpu.parallel import (  # noqa: E402
    SharedGradientsTrainer, SocketTransport,
)
from deeplearning4j_tpu.train.listeners import (  # noqa: E402
    CollectScoresIterationListener,
)


def blob_data(n=256, d=8, k=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    X = np.concatenate([centers[i] + rs.randn(n // k, d)
                        for i in range(k)]).astype("float32")
    Y = np.eye(k, dtype="float32")[np.repeat(np.arange(k), n // k)]
    perm = rs.permutation(n)
    return X[perm], Y[perm]


def main():
    rank, n_workers, base_port = (int(a) for a in sys.argv[1:4])
    out_path = sys.argv[4]
    X, Y = blob_data()
    conf = (NeuralNetConfiguration.Builder()
            .seed(9).updater(Sgd(5e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    scores = CollectScoresIterationListener()
    net.set_listeners(scores)
    with SocketTransport(rank=rank, n_workers=n_workers,
                         base_port=base_port) as transport:
        trainer = SharedGradientsTrainer(net, n_workers=n_workers,
                                         threshold=5e-4, rank=rank,
                                         transport=transport)
        trainer.fit(ArrayDataSetIterator(X, Y, batch_size=64), epochs=6)
        acc = net.evaluate((X, Y)).accuracy()
        np.savez(out_path,
                 params=np.asarray(net.params_flat()),
                 scores=np.array([s for _, s in scores.scores]),
                 accuracy=acc,
                 bytes_sent=transport.bytes_sent,
                 messages_sent=transport.messages_sent)


if __name__ == "__main__":
    main()
