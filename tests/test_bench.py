"""Unit tests for the bench orchestration (driver contract pieces that
need no device): config ordering, mode-label canonicalization, cache
path, and the headline-aggregation rule.

bench.py's module level imports no jax, so these are instant.
"""
import os
import subprocess
import sys

import pytest

import bench

_KNOBS = ("DL4J_TPU_BENCH_BATCHES", "DL4J_TPU_BENCH_ATTENTION",
          "DL4J_TPU_BENCH_LSTM", "DL4J_TPU_BENCH_W2V",
          "DL4J_TPU_BENCH_LENET", "DL4J_TPU_BENCH_FIT_E2E")


@pytest.fixture
def clean_knobs(monkeypatch):
    """_configs() reads DL4J_TPU_BENCH_* — isolate from the caller's
    shell so an exported knob can't flip these assertions."""
    for k in _KNOBS:
        monkeypatch.delenv(k, raising=False)


class TestConfigs:
    def test_tpu_order_banks_decisive_trio_first(self, clean_knobs):
        cfgs = bench._configs(True)
        kinds = [(c.get("kind"), c.get("mode", "")) for c in cfgs]
        # the per-call/scan/fit trio at batch 128 must precede the Pallas
        # attention micro (first-contact wedge risk) and batch 256
        assert kinds[:3] == [("resnet", "per-call"), ("resnet", "scan"),
                             ("resnet", "fit")]
        # the cheap h2d bandwidth micro (attributes the fit number) rides
        # right behind the trio, before the wedge-risky attention micro
        assert kinds[3] == ("h2d", "")
        assert kinds[4] == ("attention", "")
        assert {c["batch"] for c in cfgs[:3]} == {128}
        # full sweep carries all 4 BASELINE configs
        assert {"char-lstm", "word2vec", "lenet"} <= {k for k, _ in kinds}
        # plus the fit()-end-to-end (product path incl. ETL) rows
        assert [c.get("model") for c in cfgs if c["kind"] == "fit_e2e"] \
            == ["lenet", "char-lstm", "word2vec"]

    def test_cpu_order_single_batch(self, clean_knobs):
        cfgs = bench._configs(False)
        batches = {c.get("batch") for c in cfgs if "batch" in c
                   and c["kind"] == "resnet"}
        assert batches == {8}

    def test_env_knobs_disable_entries(self, clean_knobs, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_BENCH_LSTM", "0")
        monkeypatch.setenv("DL4J_TPU_BENCH_W2V", "0")
        monkeypatch.setenv("DL4J_TPU_BENCH_LENET", "0")
        monkeypatch.setenv("DL4J_TPU_BENCH_ATTENTION", "0")
        monkeypatch.setenv("DL4J_TPU_BENCH_H2D", "0")
        monkeypatch.setenv("DL4J_TPU_BENCH_FIT_E2E", "0")
        kinds = {c["kind"] for c in bench._configs(True)}
        assert kinds == {"resnet"}


class TestCanonMode:
    def test_scan_and_fit_get_k_suffix(self):
        assert bench._canon_mode(
            {"kind": "resnet", "mode": "scan"}, 10)["mode"] == "scan10"
        assert bench._canon_mode(
            {"kind": "resnet", "mode": "fit"}, 2)["mode"] == "fit-pipelined2"

    def test_other_configs_untouched(self):
        for cfg in ({"kind": "resnet", "mode": "per-call"},
                    {"kind": "attention"}, {"kind": "char-lstm"}):
            assert bench._canon_mode(dict(cfg), 10) == cfg


class TestCacheDir:
    def test_repo_local_path(self):
        # repo-local so the cached TPU programs survive /tmp wipes
        # between builder sessions (PERF.md round-5 hardware status)
        d = bench.cache_dir()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        assert d == os.path.join(repo, ".jaxcache")
        assert os.path.isdir(d)

    def test_shared_with_graft_entry_and_conftest(self):
        # conftest imports the same symbol; __graft_entry__ falls back to
        # it too — one definition, so just assert it is importable from
        # the repo root the way both callers do it
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, "-c",
             "from bench import cache_dir; print(cache_dir())"],
            capture_output=True, text=True, cwd=repo, timeout=60)
        assert r.returncode == 0
        assert r.stdout.strip() == bench.cache_dir()


class TestHeadlineAggregation:
    def test_best_is_max_imgs_sec_and_micro_entries_cannot_win(self):
        results = [
            {"batch": 128, "mode": "per-call", "imgs_sec": 2400.0},
            {"batch": 128, "mode": "scan10", "imgs_sec": 3300.0},
            {"mode": "lenet-mnist", "lenet_imgs_sec": 99999.0},
            {"mode": "char-lstm", "chars_sec": 1e9},
            {"batch": 256, "mode": "per-call",
             "error": "watchdog: config exceeded 1800s"},
        ]
        best = bench._headline(results)
        assert best["mode"] == "scan10"   # micro benches ride along only
        assert bench._headline([{"mode": "x", "error": "e"}]) is None

    @pytest.mark.slow
    @pytest.mark.distributed
    def test_sigterm_kills_inflight_child(self, tmp_path):
        # orchestration-level contract: the --one child dies with the
        # orchestrator (no orphan contending for the chip)
        import signal
        import time as _t
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DL4J_TPU_BENCH_PARTIAL=str(tmp_path / "partial.jsonl"))
        p = subprocess.Popen([sys.executable, "bench.py"], cwd=repo,
                             env=env, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        try:
            child_pid = None
            for _ in range(120):     # wait for the first --one child
                _t.sleep(1)
                r = subprocess.run(
                    ["pgrep", "-f", "bench.py --one"],
                    capture_output=True, text=True)
                pids = [int(x) for x in r.stdout.split()
                        if x.strip().isdigit() and int(x) != p.pid]
                live = []
                for pid in pids:
                    try:
                        with open(f"/proc/{pid}/stat") as f:
                            ppid = int(f.read().split()[3])
                        if ppid == p.pid:
                            live.append(pid)
                    except OSError:
                        pass
                if live:
                    child_pid = live[0]
                    break
            assert child_pid is not None, "no --one child appeared"
            p.send_signal(signal.SIGTERM)
            p.wait(timeout=30)
            for _ in range(20):
                if not os.path.exists(f"/proc/{child_pid}"):
                    break
                _t.sleep(0.5)
            # a zombie (not yet reaped) also counts as dead
            alive = os.path.exists(f"/proc/{child_pid}")
            if alive:
                with open(f"/proc/{child_pid}/stat") as f:
                    alive = f.read().split()[2] != "Z"
            assert not alive, "config child survived orchestrator SIGTERM"
        finally:
            try:
                p.kill()
            except OSError:
                pass
