"""Dataset fetcher + record reader tests (DL4J deeplearning4j-data tests)."""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.data.fetchers import (
    Cifar10DataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
    MnistDataSetIterator, UciSequenceDataSetIterator, iris_dataset, read_idx,
)
from deeplearning4j_tpu.data.records import (
    CollectionRecordReader, CollectionSequenceRecordReader, CSVRecordReader,
    RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


def test_iris_real_data():
    ds = iris_dataset()
    assert ds.features.shape == (150, 4)
    assert ds.labels.shape == (150, 3)
    assert ds.labels.sum() == 150
    # canonical first row of Fisher's data
    np.testing.assert_allclose(ds.features[0], [5.1, 3.5, 1.4, 0.2])


def test_iris_trains_to_high_accuracy():
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    it = IrisDataSetIterator(batch_size=50)
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(5e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=40)
    acc = net.evaluate(it).accuracy()
    assert acc > 0.95, acc


def test_mnist_synthetic_shapes():
    it = MnistDataSetIterator(batch_size=64, n_synthetic=256)
    batches = list(it)
    assert batches[0].features.shape == (64, 28, 28, 1)
    assert batches[0].labels.shape == (64, 10)
    assert 0.0 <= batches[0].features.min() and batches[0].features.max() <= 1.3


def test_mnist_missing_cache_raises_when_synthetic_disabled(tmp_path):
    old = os.environ.get("DL4J_TPU_DATA_DIR")
    os.environ["DL4J_TPU_DATA_DIR"] = str(tmp_path)
    try:
        with pytest.raises(FileNotFoundError):
            MnistDataSetIterator(batch_size=8, synthetic=False)
    finally:
        if old is None:
            del os.environ["DL4J_TPU_DATA_DIR"]
        else:
            os.environ["DL4J_TPU_DATA_DIR"] = old


def test_idx_roundtrip(tmp_path):
    import struct
    arr = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
    p = str(tmp_path / "test-idx3-ubyte")
    with open(p, "wb") as f:
        f.write(bytes([0, 0, 0x08, 3]))
        f.write(struct.pack(">3I", 2, 3, 4))
        f.write(arr.tobytes())
    np.testing.assert_array_equal(read_idx(p), arr)


def test_emnist_and_cifar_synthetic():
    e = EmnistDataSetIterator("letters", batch_size=32, n_synthetic=64)
    b = next(iter(e))
    assert b.labels.shape == (32, 26)
    c = Cifar10DataSetIterator(batch_size=16, n_synthetic=64)
    b = next(iter(c))
    assert b.features.shape == (16, 32, 32, 3)


def test_uci_sequence_shapes():
    it = UciSequenceDataSetIterator(batch_size=50)
    b = next(iter(it))
    assert b.features.shape == (50, 60, 1)
    assert b.labels.shape == (50, 6)


def test_uci_sequence_split_sees_all_classes():
    # the raw file is class-ordered; the fixed-seed shuffle before the
    # 450/150 split must leave every class in both splits
    # (UciSequenceDataFetcher.java:143)
    for train in (True, False):
        it = UciSequenceDataSetIterator(batch_size=600, train=train)
        b = next(iter(it))
        classes_present = (b.labels.sum(axis=0) > 0)
        assert classes_present.all(), b.labels.sum(axis=0)


# ---------------------------------------------------------------- record IO
def test_csv_record_reader_classification(tmp_path):
    p = tmp_path / "data.csv"
    rows = [[0.1, 0.2, 0], [0.3, 0.4, 1], [0.5, 0.6, 2], [0.7, 0.8, 1]]
    p.write_text("\n".join(",".join(str(v) for v in r) for r in rows))
    rr = CSVRecordReader(str(p))
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     num_classes=3)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].features.shape == (2, 2)
    assert batches[0].labels.shape == (2, 3)
    np.testing.assert_allclose(batches[0].labels[1], [0, 1, 0])


def test_record_reader_regression_multi_column():
    rows = [[1, 2, 10, 20], [3, 4, 30, 40]]
    it = RecordReaderDataSetIterator(CollectionRecordReader(rows),
                                     batch_size=2, label_index=2,
                                     label_index_to=3, regression=True)
    ds = next(iter(it))
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_allclose(ds.labels, [[10, 20], [30, 40]])


def test_sequence_reader_align_end_masks():
    seqs = [
        [[0.1, 0], [0.2, 1], [0.3, 2]],
        [[0.4, 1]],
    ]
    it = SequenceRecordReaderDataSetIterator(
        CollectionSequenceRecordReader(seqs), batch_size=2, num_classes=3,
        label_index=1)
    ds = next(iter(it))
    assert ds.features.shape == (2, 3, 1)
    assert ds.labels.shape == (2, 3, 3)
    # ALIGN_END: short sequence padded at the front
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [0, 0, 1]])
    np.testing.assert_allclose(ds.features[1, 2], [0.4])
    np.testing.assert_allclose(ds.labels[1, 2], [0, 1, 0])


def test_sequence_reader_dual_readers():
    feats = [[[0.1], [0.2]], [[0.3], [0.4]]]
    labs = [[[0], [1]], [[1], [0]]]
    it = SequenceRecordReaderDataSetIterator(
        CollectionSequenceRecordReader(feats), batch_size=2, num_classes=2,
        labels_reader=CollectionSequenceRecordReader(labs))
    ds = next(iter(it))
    assert ds.features.shape == (2, 2, 1)
    assert ds.labels.shape == (2, 2, 2)
    assert ds.features_mask is None


def test_multi_dataset_iterator():
    r1 = CollectionRecordReader([[1, 2, 0], [3, 4, 1], [5, 6, 2],
                                 [7, 8, 0]])
    it = (RecordReaderMultiDataSetIterator(batch_size=2)
          .add_reader("r", r1)
          .add_input("r", 0, 1)
          .add_output_one_hot("r", 2, 3))
    batches = list(it)
    assert len(batches) == 2
    mds = batches[0]
    assert mds.features[0].shape == (2, 2)
    assert mds.labels[0].shape == (2, 3)
    np.testing.assert_allclose(mds.labels[0][0], [1, 0, 0])


def test_multi_dataset_iterator_partial_final_batch():
    # 5 rows, batch 2 -> batches of 2, 2, 1 (final partial batch emitted,
    # DL4J RecordReaderMultiDataSetIterator behavior); and a dataset
    # SMALLER than batch_size still yields one batch
    rows = [[1, 2, 0], [3, 4, 1], [5, 6, 2], [7, 8, 0], [9, 10, 1]]
    it = (RecordReaderMultiDataSetIterator(batch_size=2)
          .add_reader("r", CollectionRecordReader(rows))
          .add_input("r", 0, 1)
          .add_output_one_hot("r", 2, 3))
    sizes = [b.features[0].shape[0] for b in it]
    assert sizes == [2, 2, 1]
    small = (RecordReaderMultiDataSetIterator(batch_size=8)
             .add_reader("r", CollectionRecordReader(rows[:3]))
             .add_input("r", 0, 1)
             .add_output_one_hot("r", 2, 3))
    sizes = [b.features[0].shape[0] for b in small]
    assert sizes == [3]


def test_svhn_tinyimagenet_lfw_synthetic_shapes():
    from deeplearning4j_tpu.data.fetchers import (
        LfwDataSetIterator, SvhnDataSetIterator, TinyImageNetDataSetIterator,
    )
    ds = next(iter(SvhnDataSetIterator(batch_size=16, n_synthetic=64)))
    assert ds.features.shape == (16, 32, 32, 3)
    assert ds.labels.shape == (16, 10)
    ds = next(iter(TinyImageNetDataSetIterator(batch_size=8, n_synthetic=32)))
    assert ds.features.shape == (8, 64, 64, 3)
    assert ds.labels.shape == (8, 200)
    it = LfwDataSetIterator(batch_size=8, n_synthetic=32, image_size=48)
    ds = next(iter(it))
    assert ds.features.shape == (8, 48, 48, 3)
    assert ds.labels.shape == (8, 8)
    assert len(it.label_names) == 8


def test_svhn_real_mat_parsing(tmp_path, monkeypatch):
    """SVHN .mat layout: X (32,32,3,N) HWCN + y 1..10 with 10 == digit 0
    (SvhnDataFetcher.java parity)."""
    from scipy.io import savemat
    from deeplearning4j_tpu.data.fetchers import SvhnDataSetIterator
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    d = tmp_path / "svhn"
    d.mkdir()
    rs = np.random.RandomState(0)
    X = (rs.rand(32, 32, 3, 12) * 255).astype("uint8")
    y = np.array([[1], [2], [10], [4], [5], [6], [7], [8], [9], [10],
                  [1], [3]], dtype="uint8")
    savemat(str(d / "train_32x32.mat"), {"X": X, "y": y})
    ds = next(iter(SvhnDataSetIterator(batch_size=12)))
    assert ds.features.shape == (12, 32, 32, 3)
    assert float(ds.features.max()) <= 1.0
    labels = np.argmax(np.asarray(ds.labels), 1)
    assert labels[2] == 0 and labels[9] == 0      # '10' -> class 0
    assert labels[0] == 1 and labels[1] == 2
    np.testing.assert_allclose(np.asarray(ds.features)[3],
                               X[:, :, :, 3] / 255.0, atol=1e-6)


def test_lfw_real_directory_parsing(tmp_path, monkeypatch):
    from PIL import Image
    from deeplearning4j_tpu.data.fetchers import LfwDataSetIterator
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    root = tmp_path / "lfw"
    rs = np.random.RandomState(1)
    for person, n in (("Ada_Lovelace", 3), ("Alan_Turing", 2),
                      ("One_Shot", 1)):
        pdir = root / person
        pdir.mkdir(parents=True)
        for i in range(n):
            arr = (rs.rand(250, 250, 3) * 255).astype("uint8")
            Image.fromarray(arr).save(str(pdir / f"{person}_{i:04d}.jpg"))
    it = LfwDataSetIterator(batch_size=4, image_size=32,
                            min_faces_per_person=2)
    ds = next(iter(it))
    assert it.label_names == ["Ada_Lovelace", "Alan_Turing"]   # One_Shot filtered
    assert ds.features.shape == (4, 32, 32, 3)
    assert ds.labels.shape == (4, 2)


def test_tiny_imagenet_real_directory_parsing(tmp_path, monkeypatch):
    from PIL import Image
    from deeplearning4j_tpu.data.fetchers import TinyImageNetDataSetIterator
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    root = tmp_path / "tiny-imagenet-200"
    rs = np.random.RandomState(2)
    wnids = ["n001", "n002"]
    for w in wnids:
        img_dir = root / "train" / w / "images"
        img_dir.mkdir(parents=True)
        for i in range(3):
            arr = (rs.rand(64, 64, 3) * 255).astype("uint8")
            Image.fromarray(arr).save(str(img_dir / f"{w}_{i}.JPEG"))
    it = TinyImageNetDataSetIterator(batch_size=6)
    ds = next(iter(it))
    assert ds.features.shape == (6, 64, 64, 3)
    # labels one-hot over the discovered wnids (2 classes present)
    assert set(np.argmax(np.asarray(ds.labels), 1)) == {0, 1}


class TestRound4UtilityIterators:
    """The remaining load-bearing utility-iterator surface (DL4J
    deeplearning4j-utility-iterators round-4 additions)."""

    def _mds_batches(self, n=6):
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        return [MultiDataSet((np.full((2, 3), i, np.float32),),
                             (np.full((2, 1), i, np.float32),), None, None)
                for i in range(n)]

    def test_reconstruction_iterator_mirrors_features(self):
        from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
        from deeplearning4j_tpu.data.utility_iterators import (
            ReconstructionDataSetIterator,
        )
        X = np.arange(12, dtype=np.float32).reshape(4, 3)
        it = ReconstructionDataSetIterator(
            ArrayDataSetIterator(X, np.zeros((4, 1), np.float32),
                                 batch_size=2))
        for ds in it:
            np.testing.assert_array_equal(ds.features, ds.labels)

    def test_async_shield_passes_through_unwrapped(self):
        from deeplearning4j_tpu.data.async_iterator import AsyncDataSetIterator
        from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
        from deeplearning4j_tpu.data.utility_iterators import (
            AsyncShieldDataSetIterator,
        )
        X = np.zeros((4, 3), np.float32)
        Y = np.zeros((4, 2), np.float32)
        shielded = AsyncShieldDataSetIterator(
            ArrayDataSetIterator(X, Y, batch_size=2))
        wrapped = AsyncDataSetIterator(shielded)
        assert wrapped._passthrough is shielded
        assert len(list(wrapped)) == 2

    def test_benchmark_iterator_reuses_one_batch(self):
        from deeplearning4j_tpu.data.utility_iterators import (
            BenchmarkDataSetIterator,
        )
        it = BenchmarkDataSetIterator((8, 4), n_labels=3, n_batches=5)
        batches = list(it)
        assert len(batches) == 5
        assert all(b is batches[0] for b in batches)
        assert batches[0].labels.shape == (8, 3)

    def test_mds_wrapper_splitter_and_early_termination(self):
        from deeplearning4j_tpu.data.utility_iterators import (
            EarlyTerminationMultiDataSetIterator,
            IteratorMultiDataSetIterator, MultiDataSetIteratorSplitter,
            MultiDataSetWrapperIterator, SingletonMultiDataSetIterator,
        )
        batches = self._mds_batches(6)
        src = IteratorMultiDataSetIterator(batches)
        assert len(list(src)) == 6
        early = EarlyTerminationMultiDataSetIterator(src, 2)
        assert len(list(early)) == 2
        split = MultiDataSetIteratorSplitter(src, total_batches=6,
                                             ratio=0.5)
        assert [float(m.features[0][0, 0])
                for m in split.train_iterator] == [0.0, 1.0, 2.0]
        assert [float(m.features[0][0, 0])
                for m in split.test_iterator] == [3.0, 4.0, 5.0]
        ds = list(MultiDataSetWrapperIterator(src))
        assert ds[0].features.shape == (2, 3)
        single = SingletonMultiDataSetIterator(batches[0])
        assert len(list(single)) == 1


class TestNormalizers:
    """ND4J normalizer suite parity (NormalizerStandardize & co.)."""

    def _iter(self, X, Y, bs=32):
        from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
        # drop_last=False so the fitted statistics cover every sample
        return ArrayDataSetIterator(X, Y, batch_size=bs, drop_last=False)

    def test_standardize_fit_transform_revert(self):
        from deeplearning4j_tpu.data.normalization import (
            NormalizerStandardize,
        )
        rs = np.random.RandomState(0)
        X = (rs.randn(256, 5) * [1, 10, 0.1, 5, 2] + [3, -7, 0, 1, 9]) \
            .astype("float32")
        Y = rs.randn(256, 2).astype("float32") * 4 + 2
        norm = NormalizerStandardize(fit_labels=True)
        norm.fit(self._iter(X, Y))
        Z = norm.transform(X)
        np.testing.assert_allclose(Z.mean(0), 0.0, atol=1e-3)
        np.testing.assert_allclose(Z.std(0), 1.0, atol=1e-2)
        np.testing.assert_allclose(norm.revert_features(Z), X, atol=1e-3)
        from deeplearning4j_tpu.data.dataset import DataSet
        ds = norm.preprocess(DataSet(X, Y))
        assert abs(np.asarray(ds.labels).mean()) < 0.1

    def test_set_pre_processor_flows_through_iterator_and_training(self):
        from deeplearning4j_tpu.data.normalization import (
            NormalizerStandardize,
        )
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        rs = np.random.RandomState(1)
        centers = rs.randn(3, 4) * 2
        # wildly different feature scales: training fails without norm
        scales = np.array([1e-3, 1.0, 1e3, 10.0], np.float32)
        X = (np.concatenate([centers[i] + rs.randn(60, 4)
                             for i in range(3)]) * scales).astype("float32")
        Y = np.eye(3, dtype="float32")[np.repeat(np.arange(3), 60)]
        it = self._iter(X, Y, bs=60)
        norm = NormalizerStandardize().fit(it)
        it.set_pre_processor(norm)
        batch = next(iter(it))     # one cluster, but unit-scale features
        assert abs(np.asarray(batch.features)).max() < 8.0
        assert abs(norm.transform(X).mean(0)).max() < 1e-3
        conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, epochs=30)
        assert net.evaluate(it).accuracy() > 0.9

    def test_minmax_and_image_scalers(self):
        from deeplearning4j_tpu.data.normalization import (
            ImagePreProcessingScaler, NormalizerMinMaxScaler,
            VGG16ImagePreProcessor,
        )
        rs = np.random.RandomState(2)
        X = (rs.rand(100, 6) * 50 - 25).astype("float32")
        mm = NormalizerMinMaxScaler(-1.0, 1.0)
        mm.fit(self._iter(X, np.zeros((100, 1), np.float32)))
        Z = mm.transform(X)
        assert Z.min() >= -1.0001 and Z.max() <= 1.0001
        np.testing.assert_allclose(mm.revert_features(Z), X, atol=1e-3)
        img = rs.randint(0, 256, (2, 4, 4, 3)).astype("float32")
        np.testing.assert_allclose(
            ImagePreProcessingScaler().transform(img), img / 255.0)
        v = VGG16ImagePreProcessor().transform(img)
        np.testing.assert_allclose(v, img - VGG16ImagePreProcessor.MEANS,
                                   atol=1e-5)

    def test_normalizer_serde_round_trip(self, tmp_path):
        from deeplearning4j_tpu.data.normalization import (
            NormalizerStandardize,
        )
        rs = np.random.RandomState(3)
        X = rs.randn(64, 3).astype("float32") * 7 + 2
        norm = NormalizerStandardize().fit(
            self._iter(X, np.zeros((64, 1), np.float32)))
        p = str(tmp_path / "norm.json")
        norm.save(p)
        back = NormalizerStandardize.restore(p)
        np.testing.assert_allclose(back.transform(X), norm.transform(X))

    def test_multi_normalizer(self):
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.data.normalization import (
            MultiNormalizerStandardize,
        )
        rs = np.random.RandomState(4)
        batches = [MultiDataSet(
            (rs.randn(16, 3).astype("float32") * 5 + 1,
             rs.randn(16, 2).astype("float32") * 0.1 - 3),
            (np.zeros((16, 1), np.float32),), None, None)
            for _ in range(6)]
        norm = MultiNormalizerStandardize().fit(list(batches))
        out = norm.preprocess(batches[0])
        assert abs(np.asarray(out.features[0]).mean()) < 0.5
        assert abs(np.asarray(out.features[1]).mean()) < 0.5

    def test_pre_processor_respected_by_wrappers_and_async(self):
        from deeplearning4j_tpu.data import (
            AsyncDataSetIterator, EarlyTerminationDataSetIterator,
            MultipleEpochsIterator, NormalizerStandardize,
            SamplingDataSetIterator,
        )
        from deeplearning4j_tpu.data.dataset import DataSet
        rs = np.random.RandomState(5)
        X = (rs.randn(64, 4) * 100 + 50).astype("float32")
        Y = np.zeros((64, 2), np.float32)
        base = self._iter(X, Y, bs=32)
        norm = NormalizerStandardize().fit(base)
        # every wrapper/source flavor must honor its preprocessor
        sources = [
            self._iter(X, Y, bs=32).set_pre_processor(norm),
            EarlyTerminationDataSetIterator(
                self._iter(X, Y, bs=32), 1).set_pre_processor(norm),
            MultipleEpochsIterator(
                self._iter(X, Y, bs=32), 1).set_pre_processor(norm),
            SamplingDataSetIterator(DataSet(X, Y), 32,
                                    2).set_pre_processor(norm),
            AsyncDataSetIterator(     # delegates to the backing iterator
                self._iter(X, Y, bs=32), device_put=False
            ).set_pre_processor(norm),
        ]
        for src in sources:
            b = next(iter(src))
            assert abs(np.asarray(b.features).mean()) < 5.0, type(src)


def test_image_record_reader_end_to_end(tmp_path):
    """DataVec ImageRecordReader + ParentPathLabelGenerator flow: label
    dirs -> resized NHWC batches -> a CNN trains on them."""
    from PIL import Image

    from deeplearning4j_tpu.data.records import (
        ImageRecordReader, RecordReaderDataSetIterator,
    )
    rs = np.random.RandomState(0)
    # two classes with distinguishable mean intensity
    for label, base in (("dark", 40), ("light", 200)):
        d = tmp_path / "train" / label
        d.mkdir(parents=True)
        for i in range(12):
            arr = np.clip(base + rs.randn(10, 12, 3) * 10, 0,
                          255).astype("uint8")
            Image.fromarray(arr).save(d / f"{i}.png")

    rr = ImageRecordReader(8, 8, 3).initialize(str(tmp_path / "train"))
    assert rr.labels() == ["dark", "light"]
    it = RecordReaderDataSetIterator(rr, batch_size=6, label_index=-1,
                                     num_classes=2)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].features.shape == (6, 8, 8, 3)
    assert batches[0].labels.shape == (6, 2)
    # reference parity: the reader yields RAW 0-255 bytes; scaling is the
    # attached normalizer's job (and raw uint8 engages device-norm)
    assert batches[0].features.dtype == np.uint8
    assert batches[0].features.max() > 1
    # normalize=True restores the float32 [0,1] convenience mode
    rrn = ImageRecordReader(8, 8, 3, normalize=True).initialize(
        str(tmp_path / "train"))
    bn = next(iter(RecordReaderDataSetIterator(rrn, batch_size=6,
                                               label_index=-1,
                                               num_classes=2)))
    assert bn.features.dtype == np.float32
    assert 0.0 <= bn.features.min() <= bn.features.max() <= 1.0
    # the canonical DL4J flow: scaler attached to the iterator
    from deeplearning4j_tpu.data.normalization import (
        ImagePreProcessingScaler)
    it.set_pre_processor(ImagePreProcessingScaler())

    # trains end to end
    from deeplearning4j_tpu.nn.conf import (
        InputType, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel=(3, 3),
                                    activation="relu"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 3)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=15)
    assert net.evaluate(it).accuracy() > 0.9

    # grayscale channel mode
    rr1 = ImageRecordReader(8, 8, 1).initialize(str(tmp_path / "train"))
    b = next(iter(RecordReaderDataSetIterator(rr1, batch_size=4,
                                              label_index=-1,
                                              num_classes=2)))
    assert b.features.shape == (4, 8, 8, 1)
    assert b.features.dtype == np.uint8


# ------------------------------------------------ round-5 iterator tail

class TestUtilityIteratorTail:
    def test_typed_pair_iterators(self):
        from deeplearning4j_tpu.data import (
            DoublesDataSetIterator, FloatsDataSetIterator,
            INDArrayDataSetIterator,
        )
        pairs = [(np.full(3, i), np.eye(2)[i % 2]) for i in range(5)]
        it = FloatsDataSetIterator(pairs, batch_size=2)
        batches = list(it)
        assert [b.num_examples() for b in batches] == [2, 2, 1]
        assert batches[0].features.dtype == np.float32
        assert list(DoublesDataSetIterator(pairs, batch_size=5))[
            0].features.dtype == np.float64
        src = [(np.zeros(3, np.int16), np.zeros(2, np.int16))]
        assert list(INDArrayDataSetIterator(src, 1))[
            0].features.dtype == np.int16
        # re-iterable: second pass yields the same batches
        assert len(list(it)) == 3

    def test_list_dataset_iterator_rebatches(self):
        from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
        singles = [DataSet(np.full((1, 2), i, "float32"),
                           np.eye(3, dtype="float32")[[i % 3]])
                   for i in range(7)]
        out = list(ListDataSetIterator(singles, batch=3))
        assert [d.num_examples() for d in out] == [3, 3, 1]
        np.testing.assert_array_equal(out[0].features[:, 0], [0, 1, 2])
        np.testing.assert_array_equal(out[2].features[:, 0], [6])

    def test_pre_processor_combinators(self):
        from deeplearning4j_tpu.data import (
            CombinedPreProcessor, DataSet, DummyPreProcessor,
        )

        class AddOne:
            def preprocess(self, ds):
                return DataSet(ds.features + 1, ds.labels)

        ds = DataSet(np.zeros((2, 2), "float32"), np.zeros((2, 1), "float32"))
        assert DummyPreProcessor().preprocess(ds) is ds
        out = CombinedPreProcessor(AddOne(), DummyPreProcessor(),
                                   AddOne()).preprocess(ds)
        np.testing.assert_array_equal(np.asarray(out.features),
                                      np.full((2, 2), 2.0))

    def test_workspaces_shield_detaches(self):
        from deeplearning4j_tpu.data import (
            ArrayDataSetIterator, WorkspacesShieldDataSetIterator,
        )
        X = np.arange(8, dtype="float32").reshape(4, 2)
        Y = np.eye(2, dtype="float32")[[0, 1, 0, 1]]
        src = ArrayDataSetIterator(X, Y, batch_size=2)
        batches = list(WorkspacesShieldDataSetIterator(src))
        assert all(isinstance(b.features, np.ndarray) for b in batches)
        batches[0].features[0, 0] = 99.0        # mutating the copy...
        assert X[0, 0] == 0.0                   # ...never touches the source

    def test_moving_window_iterator(self):
        from deeplearning4j_tpu.data import (
            DataSet, MovingWindowBaseDataSetIterator,
        )
        ds = DataSet(np.arange(10, dtype="float32")[:, None],
                     np.arange(10, dtype="float32")[:, None])
        wins = list(MovingWindowBaseDataSetIterator(ds, window=4, stride=3))
        assert [tuple(np.asarray(w.features[:, 0]).astype(int))
                for w in wins] == [(0, 1, 2, 3), (3, 4, 5, 6), (6, 7, 8, 9)]

    def test_file_split_iterator_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.data import (
            DataSet, FileSplitDataSetIterator, load_dataset, save_dataset,
        )
        files = []
        for i in range(3):
            ds = DataSet(np.full((2, 2), i, "float32"),
                         np.eye(2, dtype="float32"))
            p = str(tmp_path / f"ds{i}.npz")
            save_dataset(ds, p)
            files.append(p)
        out = list(FileSplitDataSetIterator(files))
        assert len(out) == 3
        np.testing.assert_array_equal(out[2].features,
                                      np.full((2, 2), 2.0))
        one = load_dataset(files[1])
        assert one.features_mask is None

    def test_async_iterator_interleaved_callback(self):
        import jax

        from deeplearning4j_tpu.data import (
            ArrayDataSetIterator, AsyncDataSetIterator,
            InterleavedDataSetCallback,
        )
        X = np.random.RandomState(0).rand(16, 3).astype("float32")
        Y = np.eye(2, dtype="float32")[np.arange(16) % 2]
        it = AsyncDataSetIterator(
            ArrayDataSetIterator(X, Y, batch_size=2),
            device_put=False,
            callback=InterleavedDataSetCallback(jax.devices()[:4]))
        devs = [next(iter(b.features.devices())) for b in it]
        assert len(devs) == 8
        assert [d.id for d in devs] == [0, 1, 2, 3, 0, 1, 2, 3]

    def test_joint_parallel_iterator_modes(self):
        from deeplearning4j_tpu.data import (
            ArrayDataSetIterator, InequalityHandling,
            JointParallelDataSetIterator,
        )

        def src(n, val):
            X = np.full((n, 2), val, "float32")
            Y = np.eye(2, dtype="float32")[np.zeros(n, int)]
            return ArrayDataSetIterator(X, Y, batch_size=1)

        # PASS: short source drops out, long one keeps going
        vals = [float(b.features[0, 0]) for b in
                JointParallelDataSetIterator(
                    src(2, 1.0), src(4, 2.0),
                    inequality=InequalityHandling.PASS)]
        assert vals == [1.0, 2.0, 1.0, 2.0, 2.0, 2.0]
        # STOP_EVERYONE: the first exhaustion ends the joint stream
        vals = [float(b.features[0, 0]) for b in
                JointParallelDataSetIterator(
                    src(2, 1.0), src(4, 2.0),
                    inequality=InequalityHandling.STOP_EVERYONE)]
        assert vals == [1.0, 2.0, 1.0, 2.0]
        # RESET: short source loops until the longest finishes one pass
        vals = [float(b.features[0, 0]) for b in
                JointParallelDataSetIterator(
                    src(2, 1.0), src(4, 2.0),
                    inequality=InequalityHandling.RESET)]
        assert vals[:6] == [1.0, 2.0, 1.0, 2.0, 1.0, 2.0]
        assert vals.count(2.0) == 4


class TestUtilityIteratorTailFixes:
    def test_reset_mode_equal_length_no_spurious_batch(self):
        from deeplearning4j_tpu.data import (
            ArrayDataSetIterator, InequalityHandling,
            JointParallelDataSetIterator,
        )

        def src(n, val):
            X = np.full((n, 2), val, "float32")
            Y = np.eye(2, dtype="float32")[np.zeros(n, int)]
            return ArrayDataSetIterator(X, Y, batch_size=1)

        vals = [float(b.features[0, 0]) for b in
                JointParallelDataSetIterator(
                    src(2, 1.0), src(2, 2.0),
                    inequality=InequalityHandling.RESET)]
        assert vals == [1.0, 2.0, 1.0, 2.0]     # no reset tail

    def test_typed_iterator_materializes_generator(self):
        from deeplearning4j_tpu.data import FloatsDataSetIterator
        gen = ((np.full(2, i), np.eye(2)[i % 2]) for i in range(4))
        it = FloatsDataSetIterator(gen, batch_size=2)
        assert len(list(it)) == 2
        it.reset()
        assert len(list(it)) == 2               # second epoch still trains


def test_reset_mode_short_source_cycles_all_batches():
    """RESET must cycle the short source through ALL its batches, not
    repeat only the first one after each reset."""
    from deeplearning4j_tpu.data import (
        ArrayDataSetIterator, InequalityHandling,
        JointParallelDataSetIterator,
    )

    def src(vals):
        X = np.asarray(vals, "float32")[:, None]
        Y = np.eye(2, dtype="float32")[np.zeros(len(vals), int)]
        return ArrayDataSetIterator(X, Y, batch_size=1)

    out = [float(b.features[0, 0]) for b in
           JointParallelDataSetIterator(
               src([1, 2]), src([10, 20, 30, 40, 50]),
               inequality=InequalityHandling.RESET)]
    shorts = [v for v in out if v < 10]
    assert shorts == [1.0, 2.0, 1.0, 2.0, 1.0]      # cycles, not 1,2,1,1,1
    assert [v for v in out if v >= 10] == [10.0, 20.0, 30.0, 40.0, 50.0]


class TestFitPrefetch:
    """fit() auto-wraps plain sources in an async device-prefetch
    (reference default-wrap parity, MultiLayerNetwork.java:1272-1274).
    The wrap must be a pure pipelining change: identical trained params."""

    @staticmethod
    def _net(seed=19):
        from deeplearning4j_tpu.nn.conf.base import InputType
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=12, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        return MultiLayerNetwork(conf).init()

    @staticmethod
    def _data(n=96):
        rs = np.random.RandomState(3)
        X = rs.randn(n, 5).astype("float32")
        Y = np.eye(3, dtype="float32")[rs.randint(0, 3, n)]
        return X, Y

    def test_prefetch_is_bit_identical_to_plain(self):
        X, Y = self._data()
        net_a, net_b = self._net(), self._net()
        net_a.fit((X, Y), epochs=2, batch_size=32, prefetch=False)
        net_b.fit((X, Y), epochs=2, batch_size=32, prefetch=True)
        np.testing.assert_array_equal(np.asarray(net_a.params_flat()),
                                      np.asarray(net_b.params_flat()))

    def test_prefetch_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FIT_PREFETCH", "0")
        X, Y = self._data()
        net = self._net()
        net.fit((X, Y), epochs=1, batch_size=32)    # just must not wrap/crash
        assert np.isfinite(net.score())

    def test_prefetch_scan_path_still_stacks(self):
        # scan-fit stacks host-side; the auto-wrap must keep batches on host
        X, Y = self._data()
        net_a, net_b = self._net(), self._net()
        net_a.fit((X, Y), epochs=2, batch_size=32, scan_steps=2,
                  prefetch=False)
        net_b.fit((X, Y), epochs=2, batch_size=32, scan_steps=2,
                  prefetch=True)
        np.testing.assert_array_equal(np.asarray(net_a.params_flat()),
                                      np.asarray(net_b.params_flat()))

    def test_prefetch_graph_stream_identical(self, monkeypatch):
        from deeplearning4j_tpu.nn.conf.base import InputType
        from deeplearning4j_tpu.nn.conf.network import (
            GraphBuilder, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam
        from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
        X, Y = self._data()

        def build():
            g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(23)
                              .updater(Adam(1e-2)))
                 .add_inputs("in").set_input_types(InputType.feed_forward(5)))
            g.add_layer("d", DenseLayer(n_out=12, activation="relu"), "in")
            g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "d")
            g.set_outputs("out")
            return ComputationGraph(g.build()).init()

        it = ArrayDataSetIterator(X, Y, batch_size=32)
        net_a, net_b = build(), build()
        monkeypatch.setenv("DL4J_TPU_FIT_PREFETCH", "0")
        net_a.fit(it, epochs=2)
        monkeypatch.setenv("DL4J_TPU_FIT_PREFETCH", "1")
        it.reset()
        net_b.fit(it, epochs=2)
        np.testing.assert_array_equal(np.asarray(net_a.params_flat()),
                                      np.asarray(net_b.params_flat()))

    def test_async_host_cast_halves_bytes(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.data.async_iterator import host_cast
        a = np.ones((4, 8), "float32")
        out = host_cast(a, jnp.bfloat16)
        assert out.dtype == jnp.bfloat16 and out.nbytes == a.nbytes // 2
        # f64 and non-16-bit targets pass through untouched
        assert host_cast(a, np.float64) is a
        assert host_cast(a, None) is a


def test_record_iterators_honor_set_pre_processor():
    """setPreProcessor contract on all three record-reader bridges —
    the attached pre-processor transforms every emitted batch (DL4J
    DataSetIterator/MultiDataSetIterator contract)."""
    from deeplearning4j_tpu.data.records import (
        CollectionRecordReader, CollectionSequenceRecordReader,
        RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
        SequenceRecordReaderDataSetIterator,
    )

    class Doubler:
        def preprocess(self, ds):
            if hasattr(ds, "features_masks") or isinstance(
                    ds.features, tuple):   # MultiDataSet
                return type(ds)(tuple(f * 2 for f in ds.features),
                                ds.labels)
            return type(ds)(ds.features * 2, ds.labels,
                            ds.features_mask, ds.labels_mask)

    rr = CollectionRecordReader([[1.0, 2.0, 0.0], [3.0, 4.0, 1.0]])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     num_classes=2)
    it.set_pre_processor(Doubler())
    ds = next(iter(it))
    np.testing.assert_allclose(ds.features, [[2.0, 4.0], [6.0, 8.0]])

    srr = CollectionSequenceRecordReader(
        [[[1.0, 0.0], [2.0, 0.0]], [[3.0, 1.0], [4.0, 1.0]]])
    sit = SequenceRecordReaderDataSetIterator(
        srr, batch_size=2, label_index=1, num_classes=2)
    sit.set_pre_processor(Doubler())
    sds = next(iter(sit))
    assert float(sds.features.max()) == 8.0

    m = RecordReaderMultiDataSetIterator(batch_size=2)
    m.add_reader("a", CollectionRecordReader([[1.0, 5.0], [2.0, 6.0]]))
    m.add_input("a", 0, 0)
    m.add_output("a", 1, 1)
    m.set_pre_processor(Doubler())
    mds = next(iter(m))
    np.testing.assert_allclose(mds.features[0], [[2.0], [4.0]])
