"""Barnes-Hut t-SNE: sp-tree correctness (SpTree.java analog), theta
approximation accuracy vs the exact tiled path, O(N log N) scaling, and
the N=10k BH-vs-exact benchmark (slow)."""
import time

import numpy as np
import pytest

from deeplearning4j_tpu.manifold import BarnesHutTsne
from deeplearning4j_tpu.manifold.sptree import PySpTree, bh_repulsion


def _brute_repulsion(Y):
    d2 = ((Y[:, None, :] - Y[None, :, :]) ** 2).sum(-1)
    num = 1.0 / (1.0 + d2)
    np.fill_diagonal(num, 0.0)
    z = num.sum()
    n2 = num * num
    neg = Y * n2.sum(1)[:, None] - n2 @ Y
    return neg, z


def test_sptree_structure_invariants():
    rs = np.random.RandomState(0)
    Y = rs.randn(300, 2).astype("float32")
    tree = PySpTree(Y)
    assert tree.count[0] == 300                      # root holds all
    np.testing.assert_allclose(tree.com[0], Y.mean(0), atol=1e-4)
    # every child level partitions the parent's count
    for node in range(len(tree.hw)):
        base = tree.child_base[node]
        if base >= 0:
            assert sum(tree.count[base + s]
                       for s in range(tree.fanout)) == tree.count[node]


def test_bh_repulsion_matches_bruteforce_small_theta():
    rs = np.random.RandomState(1)
    Y = rs.randn(400, 2).astype("float32") * 3
    neg_bh, z_bh, _ = bh_repulsion(Y, theta=0.2)
    neg_ex, z_ex = _brute_repulsion(Y)
    assert abs(z_bh - z_ex) / z_ex < 0.01
    np.testing.assert_allclose(neg_bh, neg_ex, rtol=0.05, atol=1e-2)


def test_native_and_python_trees_agree():
    from deeplearning4j_tpu import native
    if not native.available():
        pytest.skip("no native toolchain")
    rs = np.random.RandomState(2)
    Y = rs.randn(500, 2).astype("float32")
    neg_n, z_n, v_n = bh_repulsion(Y, 0.5)           # native path
    neg_p, z_p, v_p = PySpTree(Y).repulsion(0.5)     # python path
    assert v_n == v_p                                # identical traversal
    assert abs(z_n - z_p) / z_p < 1e-5
    np.testing.assert_allclose(neg_n, neg_p, rtol=1e-4, atol=1e-6)


def test_bh_visits_scale_sub_quadratically():
    """O(N log N): doubling N must scale visited cells by ~2·log factor,
    far below the 4x of an O(N^2) pass."""
    rs = np.random.RandomState(3)
    visits = {}
    for n in (1000, 2000, 4000):
        Y = rs.randn(n, 2).astype("float32")
        _, _, v = bh_repulsion(Y, theta=0.5)
        visits[n] = v
    assert visits[2000] / visits[1000] < 2.8
    assert visits[4000] / visits[2000] < 2.8


def test_bh_tsne_separates_clusters_and_tracks_exact_kl():
    rs = np.random.RandomState(4)
    X = np.concatenate([rs.randn(50, 8) + c
                        for c in (0.0, 10.0, -10.0)]).astype("float32")
    labels = np.repeat([0, 1, 2], 50)
    bh = BarnesHutTsne(max_iter=300, perplexity=15, theta=0.5, seed=1)
    Y = bh.fit_transform(X)
    ex = BarnesHutTsne(max_iter=300, perplexity=15, theta=0.0, seed=1)
    ex.fit_transform(X)
    # same objective value neighborhood as the approximation-free path
    assert abs(bh.kl_divergence_ - ex.kl_divergence_) < \
        0.2 * (abs(ex.kl_divergence_) + 0.05)
    # cluster purity: nearest embedded neighbor shares the label
    d2 = ((Y[:, None] - Y[None]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    assert (labels[d2.argmin(1)] == labels).mean() > 0.95


@pytest.mark.slow
def test_bh_beats_exact_wallclock_at_10k():
    """The VERDICT-mandated benchmark: one gradient evaluation at N=10k —
    sp-tree BH must be far cheaper than the exact tiled pass, with Z in
    close agreement."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.manifold.bhtsne import _tiled_forces
    rs = np.random.RandomState(5)
    n = 10_000
    Y = (rs.randn(n, 2) * 5).astype("float32")

    t0 = time.perf_counter()
    neg, z_bh, visits = bh_repulsion(Y, theta=0.5)
    bh_dt = time.perf_counter() - t0

    # exact Z via the device-tiled kernel (theta=0 path)
    edge = jnp.zeros(1, jnp.int32)
    ep = jnp.zeros(1, jnp.float32)
    n_tiles = 10
    warm, _ = _tiled_forces(jnp.asarray(Y), edge, edge, n_tiles, ep,
                            jnp.int32(n))
    warm.block_until_ready()          # drain warmup before timing
    t0 = time.perf_counter()          # second call: compiled
    grad, _ = _tiled_forces(jnp.asarray(Y), edge, edge, n_tiles, ep,
                            jnp.int32(n))
    grad.block_until_ready()
    exact_dt = time.perf_counter() - t0

    # reference Z via blocked numpy accumulation (O(N*block) memory)
    z_np = 0.0
    for s in range(0, n, 2000):
        d2 = ((Y[s:s + 2000, None, :] - Y[None, :, :]) ** 2).sum(-1)
        num = 1.0 / (1.0 + d2)
        idx = np.arange(s, min(s + 2000, n))
        num[np.arange(len(idx)), idx] = 0.0
        z_np += num.sum()
    assert abs(z_bh - z_np) / z_np < 0.02
    assert visits < 0.03 * n * n      # sub-quadratic traversal (~290/pt)
    assert bh_dt < exact_dt, (bh_dt, exact_dt)
    print(f"\nN=10k: BH {bh_dt*1e3:.0f}ms vs exact-tiled {exact_dt*1e3:.0f}ms"
          f", Z rel err {abs(z_bh-z_np)/z_np:.2e}, visits/N^2 "
          f"{visits/n/n:.4f}")


def test_sptree_preserves_duplicate_multiplicity():
    """Splitting a leaf holding merged duplicates must keep their count
    (review r4 finding): child counts always sum to the parent's."""
    rs = np.random.RandomState(6)
    Y = rs.randn(50, 2).astype("float32")
    Y[10] = Y[11] = Y[12] = Y[13]             # 4 identical points
    tree = PySpTree(Y)
    assert tree.count[0] == 50
    for node in range(len(tree.hw)):
        base = tree.child_base[node]
        if base >= 0:
            assert sum(tree.count[base + s]
                       for s in range(tree.fanout)) == tree.count[node]
    # Z must count all pairs involving the duplicates; the only residual
    # is the reference-matching artifact that each NON-representative
    # duplicate counts itself once (BarnesHutTsne.java has the same:
    # only the stored point index is excluded as "self"): here exactly
    # the 3 merged duplicates, each contributing q(0)=1.
    _, z_bh, _ = bh_repulsion(Y, theta=0.0)   # theta=0: tree is exact
    _, z_ex = _brute_repulsion(Y)
    assert z_bh - z_ex == pytest.approx(3.0, abs=1e-3)
