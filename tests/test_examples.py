"""Every example under examples/ must run end-to-end (reduced settings) —
the analog of keeping dl4j-examples compiling against the framework."""
import importlib.util
import os

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _load(name):
    path = os.path.join(EXAMPLES, name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_quickstart_mlp(tmp_path):
    assert _load("01_quickstart_mlp.py").main(
        epochs=15, tmpdir=str(tmp_path)) > 0.9


def test_computation_graph():
    assert _load("02_computation_graph.py").main(epochs=15) > 0.85


@pytest.mark.slow
def test_cnn_digits():
    assert _load("03_cnn_digits.py").main(epochs=2) > 0.7


@pytest.mark.slow
def test_char_lstm():
    out = _load("04_char_lstm.py").main(epochs=30, units=32)
    assert len(out) == 41


def test_autoencoder_anomaly():
    assert _load("05_autoencoder_anomaly.py").main(epochs=40) > 0.8


def test_early_stopping():
    result = _load("06_early_stopping.py").main(max_epochs=40)
    assert result.best_model is not None


def test_word2vec():
    w2v = _load("07_word2vec.py").main(epochs=4)
    assert "queen" in w2v.words_nearest("king", top_n=3)


def test_parallel_training():
    assert _load("08_parallel_training.py").main(epochs=8) > 0.9


def test_keras_import(tmp_path):
    pytest.importorskip("keras")
    net = _load("09_keras_import.py").main(tmpdir=str(tmp_path))
    assert net.score() is not None


@pytest.mark.slow
def test_hyperparameter_search():
    gs = _load("10_hyperparameter_search.py").main()
    assert gs.best_score_ > 0.8


def test_transfer_learning():
    assert _load("11_transfer_learning.py").main() > 0.8


@pytest.mark.slow
def test_tsne_visualization():
    assert _load("12_tsne_visualization.py").main(n=300, max_iter=250) > 0.75


def test_custom_layer():
    assert _load("13_custom_layer.py").main(epochs=30) > 0.9


@pytest.mark.slow
def test_long_context_ring():
    _load("14_long_context_ring.py").main(epochs=4)


def test_dl4j_artifact_migration(tmp_path):
    assert _load("15_dl4j_artifact_migration.py").main(
        tmpdir=str(tmp_path)) > 0.9


def test_zero_fsdp_training():
    assert _load("16_zero_fsdp_training.py").main(epochs=8) > 0.9


def test_device_norm_image_pipeline():
    assert _load("17_device_norm_image_pipeline.py").main(epochs=10) > 0.9


def test_gspmd_sharding_plan():
    assert _load("18_gspmd_sharding_plan.py").main(epochs=8) > 0.9
