"""Clustering / t-SNE / graph embedding tests (DL4J nearestneighbor-core,
deeplearning4j-tsne, deeplearning4j-graph test strategies)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree, KMeansClustering, RandomProjection, RandomProjectionLSH, VPTree,
)
from deeplearning4j_tpu.graph import DeepWalk, Graph
from deeplearning4j_tpu.manifold import Tsne


def _three_blobs(n_per=50, d=8, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(3, d) * 8
    X = np.concatenate([centers[i] + rs.randn(n_per, d)
                        for i in range(3)]).astype("float32")
    y = np.repeat(np.arange(3), n_per)
    return X, y


def test_kmeans_recovers_blobs():
    X, y = _three_blobs()
    km = KMeansClustering(k=3, seed=1).fit(X)
    pred = km.predict(X)
    # cluster purity: every true blob maps to one dominant cluster
    for c in range(3):
        counts = np.bincount(pred[y == c], minlength=3)
        assert counts.max() / counts.sum() > 0.95
    assert km.inertia(X) < KMeansClustering(k=1, seed=1).fit(X).inertia(X)


def test_vptree_matches_bruteforce():
    X, _ = _three_blobs(n_per=30)
    tree = VPTree(X)
    rs = np.random.RandomState(3)
    for _ in range(5):
        q = rs.randn(X.shape[1]).astype("float32") * 4
        idxs, dists = tree.knn(q, k=5)
        brute = np.argsort(np.linalg.norm(X - q, axis=1))[:5]
        assert set(idxs) == set(int(i) for i in brute)
        assert dists == sorted(dists)


def test_kdtree_matches_bruteforce():
    X, _ = _three_blobs(n_per=30, d=4)
    tree = KDTree(X)
    rs = np.random.RandomState(4)
    for _ in range(5):
        q = rs.randn(4).astype("float32") * 4
        idxs, _ = tree.knn(q, k=3)
        brute = np.argsort(np.linalg.norm(X - q, axis=1))[:3]
        assert set(idxs) == set(int(i) for i in brute)


def test_lsh_finds_close_neighbors():
    X, _ = _three_blobs(n_per=60)
    lsh = RandomProjectionLSH(hash_length=8, num_tables=6, seed=0).fit(X)
    idxs, dists = lsh.query(X[0], k=5)
    assert idxs[0] == 0 and abs(dists[0]) < 1e-5
    # returned neighbors are genuinely close (same blob radius)
    assert all(d < 10.0 for d in dists)


def test_random_projection_preserves_distances():
    X, _ = _three_blobs(n_per=40, d=64)
    rp = RandomProjection(target_dim=32, seed=0).fit(X)
    Z = rp.transform(X)
    assert Z.shape == (120, 32)
    rs = np.random.RandomState(0)
    pairs = rs.randint(0, 120, (30, 2))
    dx = np.linalg.norm(X[pairs[:, 0]] - X[pairs[:, 1]], axis=1)
    dz = np.linalg.norm(Z[pairs[:, 0]] - Z[pairs[:, 1]], axis=1)
    ratio = dz / np.maximum(dx, 1e-9)
    assert 0.6 < ratio.mean() < 1.4


def test_tsne_separates_blobs():
    X, y = _three_blobs(n_per=30)
    ts = Tsne(perplexity=10, max_iter=300, seed=0)
    Y = ts.fit_transform(X)
    assert Y.shape == (90, 2)
    assert np.isfinite(ts.kl_divergence_)
    # blob centroids in the embedding are farther apart than intra spread
    cents = np.stack([Y[y == c].mean(0) for c in range(3)])
    intra = np.mean([np.linalg.norm(Y[y == c] - cents[c], axis=1).mean()
                     for c in range(3)])
    inter = np.mean([np.linalg.norm(cents[a] - cents[b])
                     for a in range(3) for b in range(a + 1, 3)])
    assert inter > 2 * intra, (inter, intra)


def test_graph_and_walks():
    g = Graph.from_edges([(0, 1), (1, 2), (2, 0), (3, 4), (4, 5), (5, 3)])
    assert g.n_vertices == 6
    assert g.num_edges() == 6
    assert set(g.neighbors(0)) == {1, 2}
    walks = list(g.random_walks(walk_length=10, walks_per_vertex=2, seed=0))
    assert len(walks) == 12
    # walks never cross between the two triangle components
    for w in walks:
        comp = set(w)
        assert comp <= {0, 1, 2} or comp <= {3, 4, 5}, w


def test_deepwalk_embeds_components_apart():
    """Two disconnected cliques: intra-component similarity must dominate."""
    edges = []
    for comp, base in ((0, 0), (1, 6)):
        for i in range(6):
            for j in range(i + 1, 6):
                edges.append((base + i, base + j))
    g = Graph.from_edges(edges)
    dw = DeepWalk(layer_size=16, window=3, walk_length=20,
                  walks_per_vertex=8, epochs=10, seed=0)
    dw.fit_graph(g)
    intra = np.mean([dw.vertex_similarity(0, j) for j in range(1, 6)])
    inter = np.mean([dw.vertex_similarity(0, j) for j in range(6, 12)])
    assert intra > inter, (intra, inter)
    near = dw.verts_nearest(0, 5)
    assert set(near) <= set(range(1, 6)), near


def test_barnes_hut_tsne_scales_with_tiled_memory():
    """The scalable t-SNE (BarnesHutTsne role): tiled repulsion + sparse
    kNN attraction. Checks (a) cluster separation like the exact version,
    (b) KL decreases over optimization, (c) per-iteration HBM stays
    O(N*tile), NOT O(N^2) (round-2 VERDICT item 5)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.manifold import BarnesHutTsne
    from deeplearning4j_tpu.manifold.bhtsne import _tiled_forces
    X, y = _three_blobs(n_per=40)
    bh = BarnesHutTsne(perplexity=10, max_iter=300, tile_rows=32, seed=0)
    Y = bh.fit_transform(X)
    assert Y.shape == (120, 2)
    assert np.isfinite(bh.kl_divergence_)
    cents = np.stack([Y[y == c].mean(0) for c in range(3)])
    intra = np.mean([np.linalg.norm(Y[y == c] - cents[c], axis=1).mean()
                     for c in range(3)])
    inter = np.mean([np.linalg.norm(cents[a] - cents[b])
                     for a in range(3) for b in range(a + 1, 3)])
    assert inter > 2 * intra, (inter, intra)
    # KL after the early-exaggeration phase must improve monotonically-ish:
    # every sampled KL after the first post-lying sample is below the first
    post = [k for k in bh.kl_history_[2:]]
    assert post and all(k <= bh.kl_history_[1] + 1e-6 for k in post), \
        bh.kl_history_

    # memory assertion: compiled gradient evaluation at tile=64 on N=1024
    # must keep temporaries well under the N^2 matrix it replaces
    n, k, tile = 1024, 8, 64
    rs = np.random.RandomState(0)
    Yb = jnp.asarray(rs.randn(n, 2).astype("float32"))
    src = jnp.asarray(np.repeat(np.arange(n), k))
    dst = jnp.asarray(rs.randint(0, n, n * k))
    p = jnp.asarray(rs.rand(n * k).astype("float32") / (n * k))
    lowered = _tiled_forces.lower(Yb, src, dst, n // tile, p,
                                  jnp.int32(n))
    ma = lowered.compile().memory_analysis()
    if ma is not None:
        n2_bytes = n * n * 4
        assert int(ma.temp_size_in_bytes) < n2_bytes // 2, \
            (ma.temp_size_in_bytes, n2_bytes)
