"""Attention, ring attention, context parallelism, TransformerLM tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    EmbeddingSequenceLayer, LayerNormLayer, MoEFeedForward,
    MultiHeadAttention, PositionalEmbeddingLayer, RnnOutputLayer,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.layers.attention import (
    dot_product_attention, rope,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.models import TransformerLM, TransformerLMMoE
from deeplearning4j_tpu.parallel import (
    ContextParallelTrainer, MeshConfig, ParallelWrapper, TrainingMode,
    blockwise_attention, build_mesh, make_ring_attention, shard_params,
)


def _qkv(b=2, t=16, h=4, d=8, seed=0):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(b, t, h, d).astype("float32"))
    return mk(), mk(), mk()


# ------------------------------------------------------------ core attention
def test_dot_product_attention_softmax_weights():
    q, k, v = _qkv()
    out = dot_product_attention(q, k, v)
    assert out.shape == q.shape
    # single-key sanity: attention over one key returns that value
    out1 = dot_product_attention(q[:, :1], k[:, :1], v[:, :1])
    np.testing.assert_allclose(np.asarray(out1), np.asarray(v[:, :1]),
                               atol=1e-5)


def test_causal_masking_blocks_future():
    q, k, v = _qkv(t=8)
    out = dot_product_attention(q, k, v, causal=True)
    # first position can only attend to itself
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(v[:, 0]),
                               atol=1e-5)
    # changing future values must not change past outputs
    v2 = v.at[:, 4:].set(0.0)
    out2 = dot_product_attention(q, k, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, :4]),
                               np.asarray(out2[:, :4]), atol=1e-6)


def test_key_mask_excludes_padded_steps():
    q, k, v = _qkv(t=8)
    mask = jnp.asarray(np.array([[1] * 8, [1] * 4 + [0] * 4], "float32"))
    out = dot_product_attention(q, k, v, mask=mask)
    # batch 1: zeroing masked-out v positions changes nothing
    v2 = v.at[1, 4:].set(123.0)
    out2 = dot_product_attention(q, k, v2, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-5)


def test_blockwise_matches_dense():
    q, k, v = _qkv(t=32)
    dense = dot_product_attention(q, k, v, causal=True)
    block = blockwise_attention(q, k, v, block_size=8, causal=True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(block),
                               atol=2e-5)


def test_blockwise_masked_matches_dense():
    q, k, v = _qkv(t=32)
    rs = np.random.RandomState(3)
    mask = jnp.asarray((rs.rand(2, 32) > 0.3).astype("float32"))
    dense = dot_product_attention(q, k, v, mask=mask)
    block = blockwise_attention(q, k, v, block_size=8, causal=False,
                                mask=mask)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(block),
                               atol=2e-5)


def test_rope_preserves_norm_and_relativity():
    q, _, _ = _qkv(t=8)
    pos = jnp.arange(8)[None]
    r = rope(q, pos)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(r), axis=-1),
                               np.linalg.norm(np.asarray(q), axis=-1),
                               rtol=1e-4)


# ------------------------------------------------------------ ring attention
def test_ring_attention_matches_dense():
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    q, k, v = _qkv(t=64)
    dense = dot_product_attention(q, k, v, causal=True)
    ring = make_ring_attention(mesh, causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=2e-5)


def test_ring_attention_masked_matches_dense():
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    q, k, v = _qkv(t=64)
    rs = np.random.RandomState(5)
    mask = jnp.asarray((rs.rand(2, 64) > 0.25).astype("float32"))
    dense = dot_product_attention(q, k, v, mask=mask)
    ring = make_ring_attention(mesh, causal=False)(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=2e-5)


# ------------------------------------------------------- layers / LM models
def _char_data(vocab=32, b=8, t=32, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randint(0, vocab, (b, t)).astype("float32")
    # next-token labels: shift by one (predictable structure: y = x+1 mod V)
    y_ids = (x.astype(int) + 1) % vocab
    y = np.eye(vocab, dtype="float32")[y_ids]
    return x, y


def test_transformer_lm_trains():
    model = TransformerLM(vocab_size=32, seq_length=32, n_layers=2,
                          n_embd=64, n_heads=4, learning_rate=3e-3)
    net = model.init()
    x, y = _char_data()
    losses = []
    for _ in range(30):
        net.fit((x, y), epochs=1, batch_size=8)
        losses.append(net.score())
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_transformer_block_and_moe_shapes():
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(EmbeddingSequenceLayer(n_out=32, n_in=16))
            .layer(PositionalEmbeddingLayer(max_length=64))
            .layer(TransformerBlock(n_out=32, n_heads=4, use_rope=False))
            .layer(MoEFeedForward(n_out=32, n_experts=4))
            .layer(LayerNormLayer())
            .layer(RnnOutputLayer(n_out=16, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(1, 8)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(0).randint(0, 16, (4, 8)).astype("float32")
    out = np.asarray(net.output(x))
    assert out.shape == (4, 8, 16)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-4)


def test_lm_conf_roundtrips():
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    conf = TransformerLMMoE(vocab_size=64, seq_length=16, n_layers=2,
                            n_embd=32, n_heads=4).conf()
    js = conf.to_json()
    assert MultiLayerConfiguration.from_json(js).to_json() == js


# ------------------------------------------------------- context parallelism
def test_context_parallel_step_matches_single_device():
    """One CP step over an 8-way seq mesh == one single-device step."""
    model = TransformerLM(vocab_size=16, seq_length=32, n_layers=1,
                          n_embd=32, n_heads=4, learning_rate=1e-2, seed=3)
    x, y = _char_data(vocab=16, b=4, t=32, seed=7)
    net_a = model.init()
    net_b = model.init()
    # single device
    net_b.fit((x, y), epochs=1, batch_size=4)
    # context parallel over seq=8
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    ContextParallelTrainer(net_a, mesh).fit((x, y), epochs=1, batch_size=4)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=2e-4)


def test_context_parallel_dp_sp_mesh_trains():
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=1,
                          n_embd=32, n_heads=4, learning_rate=3e-3)
    net = model.init()
    mesh = build_mesh(MeshConfig(data=2, model=1, seq=4))
    trainer = ContextParallelTrainer(net, mesh)
    x, y = _char_data(vocab=16, b=8, t=16)
    for _ in range(5):
        trainer.fit((x, y), epochs=1, batch_size=8)
    assert np.isfinite(net.score())


def test_context_parallel_rejects_lstm():
    from deeplearning4j_tpu.nn.layers import LSTM
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(4, 8)).build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError):
        ContextParallelTrainer(net, build_mesh(MeshConfig()))


# --------------------------------------------------------------- tp sharding
def test_transformer_tp_sharded_step():
    """dp x tp: params sharded by the megatron rules, one wrapper step."""
    mesh = build_mesh(MeshConfig(data=4, model=2))
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=2,
                          n_embd=32, n_heads=4)
    net = model.init()
    net.params = shard_params(net.params, mesh, TransformerLM.sharding_rules())
    spec = net.params["1"]["attn"]["Wq"].sharding.spec
    assert tuple(spec) == (None, "model"), spec
    w = ParallelWrapper(net, mesh=mesh, mode=TrainingMode.SYNC_GRADIENTS)
    x, y = _char_data(vocab=16, b=8, t=16)
    w.fit((x, y), epochs=1, batch_size=8)
    assert np.isfinite(net.score())


def test_moe_expert_parallel_sharding():
    mesh = build_mesh(MeshConfig(data=4, model=2))
    model = TransformerLMMoE(vocab_size=16, seq_length=16, n_layers=2,
                             n_embd=32, n_heads=4, n_experts=4)
    net = model.init()
    placed = shard_params(net.params, mesh, TransformerLM.sharding_rules())
    # MoE layer index 3 (emb=0, block=1, block=2, moe=3): W1 (E, f, h), expert
    # dim sharded over "model"
    moe_w1 = placed["3"]["W1"]
    assert tuple(moe_w1.sharding.spec) == ("model", None, None)
    # dense block W1 is 2D column-parallel
    blk_w1 = placed["1"]["W1"]
    assert tuple(blk_w1.sharding.spec) == (None, "model")


def test_context_parallel_masked_matches_single_device():
    """Masked CP step == masked single-device step: valid tokens are
    distributed unevenly across sequence shards, so the psum-weighted
    masked mean must reproduce the global objective exactly."""
    model = TransformerLM(vocab_size=16, seq_length=32, n_layers=1,
                          n_embd=32, n_heads=4, learning_rate=1e-2, seed=9)
    x, y = _char_data(vocab=16, b=4, t=32, seed=11)
    mask = np.zeros((4, 32), "float32")
    mask[:, :5] = 1.0          # valid tokens concentrated in early shards
    mask[:, 31] = 1.0
    from deeplearning4j_tpu.data.dataset import DataSet
    ds = DataSet(x, y, mask, mask)
    net_a = model.init()
    net_b = model.init()
    net_b.fit(ds, epochs=1)
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    ContextParallelTrainer(net_a, mesh).fit(ds, epochs=1, batch_size=4)
    assert abs(net_a.score() - net_b.score()) < 1e-4, \
        (net_a.score(), net_b.score())
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=2e-4)


def test_review_fixes_guards():
    """Regression guards from review: rope odd head dim fails at init;
    LastTimeStep wrapper rejected by CP; blockwise impl wired through
    TransformerLM; positional overflow raises."""
    from deeplearning4j_tpu.nn.layers import LastTimeStep, DenseLayer, OutputLayer
    with pytest.raises(ValueError, match="even head dim"):
        conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(MultiHeadAttention(n_out=36, n_heads=4))
                .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(8, 4)).build())
        MultiLayerNetwork(conf).init()
    # LastTimeStep wrapping attention still rejected by the CP guard
    conf2 = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
             .list()
             .layer(LastTimeStep(layer=MultiHeadAttention(n_out=8, n_heads=2)))
             .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
             .set_input_type(InputType.recurrent(8, 4)).build())
    net = MultiLayerNetwork(conf2).init()
    with pytest.raises(ValueError, match="sequence shards"):
        ContextParallelTrainer(net, build_mesh(MeshConfig()))
    # blockwise plumbed through the zoo model
    lm = TransformerLM(vocab_size=8, seq_length=16, n_layers=1, n_embd=16,
                       n_heads=2, attention_impl="blockwise", block_size=4)
    conf3 = lm.conf()
    assert conf3.layers[1].attention_impl == "blockwise"
    assert conf3.layers[1].block_size == 4
    # positional embedding overflow fails loudly
    with pytest.raises(ValueError, match="max_length"):
        p = PositionalEmbeddingLayer(max_length=4)
        import jax.numpy as jnp
        params, _ = p.init(jax.random.PRNGKey(0), InputType.recurrent(3, 8))
        p.apply(params, {}, jnp.zeros((1, 8, 3)))


def test_context_parallel_graph_matches_single_device():
    """CP now supports ComputationGraph (round-2 VERDICT weak #4): one CP
    step over a seq=8 mesh on a transformer-as-graph == one single-device
    graph step."""
    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer, TransformerBlock,
    )
    from deeplearning4j_tpu.nn.conf.base import InputType

    vocab, t = 16, 32

    def make_graph():
        g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(5)
                          .updater(Adam(1e-2)))
             .add_inputs("tokens")
             .set_input_types(InputType.recurrent(1, t)))
        g.add_layer("emb", EmbeddingSequenceLayer(n_in=vocab, n_out=32),
                    "tokens")
        g.add_layer("block", TransformerBlock(n_out=32, n_heads=4,
                                              causal=True, use_rope=True),
                    "emb")
        g.add_layer("head", RnnOutputLayer(n_out=vocab,
                                           activation="softmax",
                                           loss="mcxent"), "block")
        g.set_outputs("head")
        return ComputationGraph(g.build()).init()

    x, y = _char_data(vocab=vocab, b=4, t=t, seed=9)
    x3 = x[..., None]                       # (B, T, 1) token ids
    net_a = make_graph()
    net_b = make_graph()
    net_b.fit(MultiDataSet((x3,), (y,)), epochs=1)
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    ContextParallelTrainer(net_a, mesh).fit(MultiDataSet((x3,), (y,)),
                                            epochs=1)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=2e-4)


def test_context_parallel_graph_rejects_multi_input():
    from deeplearning4j_tpu.nn.conf.network import GraphBuilder
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    g = (GraphBuilder(NeuralNetConfiguration.Builder().seed(0)
                      .updater(Adam(1e-3)))
         .add_inputs("a", "b")
         .set_input_types(InputType.feed_forward(4),
                          InputType.feed_forward(4)))
    g.add_vertex("cat", MergeVertex(), "a", "b")
    g.add_layer("d", DenseLayer(n_out=4), "cat")
    g.add_layer("out", OutputLayer(n_out=2), "d")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    with pytest.raises(ValueError, match="single-input"):
        ContextParallelTrainer(net)


def test_context_parallel_honors_label_mask():
    """Label masks are threaded separately from feature masks (they used to
    be conflated): one CP step with an lmask == one single-device step."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=1,
                          n_embd=32, n_heads=4, learning_rate=1e-2, seed=4)
    x, y = _char_data(vocab=16, b=4, t=16, seed=11)
    lmask = np.ones((4, 16), np.float32)
    lmask[:, 12:] = 0.0                       # ignore the tail positions
    ds = DataSet(x, y, labels_mask=lmask)
    net_a = model.init()
    net_b = model.init()
    net_b.fit(ExistingDataSetIterator([ds]), epochs=1)
    mesh = build_mesh(MeshConfig(data=1, model=1, seq=8))
    ContextParallelTrainer(net_a, mesh).fit(ExistingDataSetIterator([ds]),
                                            epochs=1)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=2e-4)


# ------------------------------------------------------- pipeline parallelism
def test_pipeline_parallel_step_matches_single_device():
    """GPipe-over-ppermute (parallel/pipeline.py): one dp x pp step on a
    2x4 mesh == one single-device step (autodiff provides the backward
    pipeline; equivalence is the whole correctness argument)."""
    from deeplearning4j_tpu.parallel import PipelineParallelTrainer
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=4,
                          n_embd=32, n_heads=4, learning_rate=1e-2, seed=6)
    x, y = _char_data(vocab=16, b=8, t=16, seed=13)
    net_a = model.init()
    net_b = model.init()
    net_b.fit((x, y), epochs=1, batch_size=8)
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    PipelineParallelTrainer(net_a, mesh, n_microbatches=4).fit(
        (x, y), epochs=1, batch_size=8)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=2e-4)


def test_pipeline_parallel_trains():
    from deeplearning4j_tpu.parallel import PipelineParallelTrainer
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=8,
                          n_embd=32, n_heads=4, learning_rate=3e-3, seed=2)
    net = model.init()
    mesh = build_mesh(MeshConfig(data=1, stage=8))
    trainer = PipelineParallelTrainer(net, mesh, n_microbatches=8)
    x, y = _char_data(vocab=16, b=16, t=16)
    first = None
    for _ in range(6):
        trainer.fit((x, y), epochs=1, batch_size=16)
        if first is None:
            first = net.score()
    assert net.score() < first, (first, net.score())


def test_pipeline_parallel_validations():
    from deeplearning4j_tpu.parallel import PipelineParallelTrainer
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    # 3 blocks not divisible by 4 stages
    bad = TransformerLM(vocab_size=16, seq_length=8, n_layers=3,
                        n_embd=32, n_heads=4).init()
    with pytest.raises(ValueError, match="divisible"):
        PipelineParallelTrainer(bad, mesh)
    # no block torso at all
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
            .list().layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(4)).build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    with pytest.raises(ValueError, match="TransformerBlock"):
        PipelineParallelTrainer(MultiLayerNetwork(conf).init(), mesh)


def test_pipeline_parallel_honors_masks():
    """Masks ride the pipeline with the activations (bubble ticks carry
    all-ones masks so no NaN poisons real gradients): one masked dp x pp
    step == one single-device masked step."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator
    from deeplearning4j_tpu.parallel import PipelineParallelTrainer
    model = TransformerLM(vocab_size=16, seq_length=16, n_layers=4,
                          n_embd=32, n_heads=4, learning_rate=1e-2, seed=8)
    x, y = _char_data(vocab=16, b=8, t=16, seed=21)
    lmask = np.ones((8, 16), np.float32)
    lmask[:, 10:] = 0.0
    ds = DataSet(x, y, labels_mask=lmask)
    net_a = model.init()
    net_b = model.init()
    net_b.fit(ExistingDataSetIterator([ds]), epochs=1)
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    PipelineParallelTrainer(net_a, mesh, n_microbatches=4).fit(
        ExistingDataSetIterator([ds]), epochs=1)
    np.testing.assert_allclose(np.asarray(net_a.params_flat()),
                               np.asarray(net_b.params_flat()), atol=5e-4)


def test_pipeline_parallel_rejects_mixed_precision_and_stateful():
    import dataclasses
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import PipelineParallelTrainer
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    conf = dataclasses.replace(
        TransformerLM(vocab_size=8, seq_length=8, n_layers=4, n_embd=16,
                      n_heads=2).conf(), compute_dtype="bfloat16")
    with pytest.raises(ValueError, match="compute_dtype"):
        PipelineParallelTrainer(MultiLayerNetwork(conf).init(), mesh)
    # stateful layers (BatchNorm running stats) are rejected too: the pp
    # step drops state updates
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.base import InputType
    from deeplearning4j_tpu.nn.layers import (
        BatchNormalization, EmbeddingSequenceLayer, RnnOutputLayer,
        TransformerBlock,
    )
    b = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
         .list()
         .layer(EmbeddingSequenceLayer(n_in=8, n_out=16))
         .layer(TransformerBlock(n_out=16, n_heads=2))
         .layer(TransformerBlock(n_out=16, n_heads=2))
         .layer(TransformerBlock(n_out=16, n_heads=2))
         .layer(TransformerBlock(n_out=16, n_heads=2))
         .layer(BatchNormalization())
         .layer(RnnOutputLayer(n_out=8))
         .set_input_type(InputType.recurrent(1, 8)).build())
    with pytest.raises(ValueError, match="carries state"):
        PipelineParallelTrainer(MultiLayerNetwork(b).init(), mesh)


def test_blockwise_impl_handles_non_divisible_sequence():
    """attention_impl="blockwise" with T < or not divisible by block_size
    must clamp + pad like the flash fallback, not raise (round-5 fix)."""
    from deeplearning4j_tpu.nn.layers import MultiHeadAttention

    rs = np.random.RandomState(0)
    layer = MultiHeadAttention(n_out=16, n_heads=2,
                               attention_impl="blockwise")  # block 512
    params, state = layer.init(jax.random.PRNGKey(0),
                               InputType.recurrent(8, 12))
    x = jnp.asarray(rs.randn(3, 12, 8).astype("float32"))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (3, 12, 16)
    dense = MultiHeadAttention(n_out=16, n_heads=2, attention_impl="dense")
    y2, _ = dense.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2),
                               rtol=2e-4, atol=2e-4)
